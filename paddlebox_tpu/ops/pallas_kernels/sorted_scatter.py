"""Sorted segment scatter-accumulate — the CopyForPush-class kernel.

Role of the reference's push-side CUDA kernels (``box_wrapper.cu``
CopyForPush + ``heter_comm`` dynamic_merge_grad): merge a batch of
per-occurrence sparse updates into a per-row accumulator at memory
bandwidth. XLA's TPU scatter costs ~7 ns/element regardless of hints
(PROFILE.md) — ~55 ms for the bench step's 426K×20 update. This kernel
instead SORTS the updates by destination row (XLA sort — cheap) and
streams the accumulator through VMEM one block at a time, applying each
block's contiguous run of updates with in-VMEM dynamic-row adds.

    acc = sorted_scatter_accumulate(rows, payload, num_rows)
    # == jnp.zeros((num_rows, AW)).at[rows].add(payload)  (exact)

Updates whose row == ``num_rows`` (or anything >= the padded row bound)
are DROPPED — callers use that as the padding/trash sentinel.

Skew guard: per-block update counts are data-dependent; if any block's
run exceeds the static per-block budget (a pathologically hot row), the
caller's wrapper falls back to the XLA scatter via ``lax.cond`` — the
kernel itself never reads past its budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Rows per accumulator block streamed through VMEM. f32 lane padding makes
# a [BLOCK, AW<=128] block cost BLOCK*128*4 bytes of VMEM (~4 MB at 8192).
BLOCK = 8192
# Static per-block update budget (DMA slice size). Uniform-hash rows give
# ~n/nblocks per block; 4096 covers the binomial tail by orders of
# magnitude — overflow means a genuinely hot row, handled by fallback.
UCAP = 4096
# DMA source offsets must be provably tile-aligned (i32 1-D VMEM tiles at
# 1024 elements; f32 2-D at 8 sublanes — 1024 covers both): each block's
# staging copy starts at the run's offset rounded DOWN to ALIGN and the
# window carries ALIGN rows of slack, with the kernel skipping into it.
ALIGN = 1024
WINDOW = UCAP + ALIGN


def _kernel(starts_ref, rows_ref, payload_ref, acc_ref, rows_s, pay_s,
            sem0, sem1):
    b = pl.program_id(0)
    lo = starts_ref[b]
    cnt = starts_ref[b + 1] - lo

    # Stage this block's run of (row, payload) updates: row ids into SMEM
    # (they are read one scalar at a time at a data-dependent index — VMEM
    # vector loads need tile-aligned offsets Mosaic cannot prove for a
    # dynamic scalar index), payloads into VMEM. The copy starts at the
    # run's offset rounded down to the tile boundary (ALIGN) — Mosaic
    # requires provably aligned DMA source offsets — and the loop skips
    # the `off` leading rows of slack. Inputs are padded by WINDOW rows
    # so the fixed-size slice never reads out of bounds.
    lo_a = pl.multiple_of((lo // ALIGN) * ALIGN, ALIGN)
    off = lo - lo_a
    dma0 = pltpu.make_async_copy(rows_ref.at[pl.ds(lo_a, WINDOW)], rows_s,
                                 sem0)
    dma1 = pltpu.make_async_copy(payload_ref.at[pl.ds(lo_a, WINDOW), :],
                                 pay_s, sem1)
    dma0.start()
    dma1.start()
    acc_ref[:] = jnp.zeros_like(acc_ref)
    dma0.wait()
    dma1.wait()

    base = b * BLOCK

    aw = acc_ref.shape[1]

    def body(j, _):
        r = rows_s[j] - base
        acc_ref[pl.ds(r, 1), :] += pay_s[pl.ds(j, 1), :aw]
        return 0

    lax.fori_loop(off, off + jnp.minimum(cnt, UCAP), body, 0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _sorted_accumulate(sorted_rows: jax.Array, sorted_payload: jax.Array,
                       rows_pad: int, interpret: bool) -> jax.Array:
    npad, aw = sorted_payload.shape
    nblocks = rows_pad // BLOCK
    boundaries = jnp.arange(nblocks + 1, dtype=jnp.int32) * BLOCK
    starts = jnp.searchsorted(sorted_rows, boundaries).astype(jnp.int32)

    # DMA slices must cover full 128-lane tiles: pad the payload's lane
    # dim to the physical width (the HBM buffer is (1,128)-tiled and
    # lane-padded regardless — this only makes the logical shape match
    # so Mosaic accepts the copy; the kernel adds back only aw lanes).
    lanes = 128
    pay_full = jnp.pad(sorted_payload, ((0, 0), (0, lanes - aw)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),      # sorted rows (HBM)
            pl.BlockSpec(memory_space=pl.ANY),      # payload (HBM)
        ],
        out_specs=pl.BlockSpec((BLOCK, aw), lambda b, starts: (b, 0)),
        scratch_shapes=[
            pltpu.SMEM((WINDOW,), jnp.int32),
            pltpu.VMEM((WINDOW, lanes), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows_pad, aw), jnp.float32),
        interpret=interpret,
    )(starts, sorted_rows, pay_full)


def sorted_scatter_accumulate(rows: jax.Array, payload: jax.Array,
                              num_rows: int, *,
                              interpret: bool = False,
                              layout=None) -> jax.Array:
    """zeros([num_rows, AW]).at[rows].add(payload), exactly — via sort +
    VMEM-streamed accumulation. rows [n] int32 (entries >= num_rows are
    dropped); payload [n, AW<=128] float32. ``layout`` is an optional
    precomputed ``sorted_gather.sorted_stream_layout(rows, num_rows)``
    so the pull gather and this push scatter share ONE argsort per step.
    Falls back to the XLA scatter when a block's update run exceeds the
    kernel budget (hot row)."""
    n, aw = payload.shape
    if aw > 128:
        raise ValueError(
            f"payload width {aw} > 128: the kernel stages updates in "
            f"single-tile (128-lane) VMEM rows; split wider payloads "
            f"into <=128-wide accumulations")
    rows_pad = -(-num_rows // BLOCK) * BLOCK
    nblocks = rows_pad // BLOCK

    if layout is None:
        # Dropped rows (>= num_rows) are remapped to rows_pad so they
        # sort PAST the last block boundary. Leaving them in
        # [num_rows, rows_pad) would count them in the last block's run
        # — and since droppers concentrate (every padding lane carries
        # the same sentinel), that would trip the hot-row fallback on
        # every call for any num_rows not a multiple of BLOCK.
        remapped = jnp.where(rows >= num_rows, rows_pad, rows)
        order = jnp.argsort(remapped)
        # Pad by WINDOW so the kernel's fixed-size aligned DMA slices
        # stay in bounds; pad rows use the drop sentinel.
        sorted_rows = jnp.concatenate(
            [remapped[order].astype(jnp.int32),
             jnp.full((WINDOW,), rows_pad, jnp.int32)])
        boundaries = jnp.arange(nblocks + 1, dtype=jnp.int32) * BLOCK
        # Padding entries (== rows_pad) sort past the last boundary and
        # fall in no block; the same holds for dropped (sentinel) rows.
        starts = jnp.searchsorted(sorted_rows, boundaries)
        max_run = jnp.max(starts[1:] - starts[:-1])
    else:
        sorted_rows, order, starts, max_run = layout
        if (sorted_rows.shape[0] != n + WINDOW
                or starts.shape[0] != nblocks + 1):
            raise ValueError(
                f"shared layout shapes {sorted_rows.shape[0]}/"
                f"{starts.shape[0]} do not match rows/num_rows "
                f"({n + WINDOW}/{nblocks + 1}) — it was built for "
                f"different (rows, num_rows)")
    sorted_payload = jnp.concatenate(
        [payload[order].astype(jnp.float32),
         jnp.zeros((WINDOW, aw), jnp.float32)])

    def pallas_path(_):
        acc = _sorted_accumulate(sorted_rows, sorted_payload, rows_pad,
                                 interpret)
        return acc[:num_rows]

    def xla_path(_):
        keep = rows < num_rows
        safe = jnp.where(keep, rows, 0)
        contrib = jnp.where(keep[:, None], payload, 0.0)
        return jnp.zeros((num_rows, aw), jnp.float32).at[safe].add(contrib)

    return lax.cond(max_run <= UCAP, pallas_path, xla_path, operand=None)
