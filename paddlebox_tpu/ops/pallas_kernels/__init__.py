"""Hand-written Pallas TPU kernels for the hot ops.

Role of the reference's hand-written CUDA kernels (SURVEY.md §2.2): where
Paddle drops to .cu files for ops XLA-era compilers couldn't fuse
(``operators/fused/fused_attention_op.cu``, ``fused_seqpool_cvm_op.cu``,
``fused_multi_transformer_op.cu``), this package drops to Pallas — the
TPU kernel language — for the same reason: control over VMEM tiling,
on-chip accumulators, and MXU scheduling on the few ops where generic XLA
lowering leaves performance on the table.

Every kernel has an XLA reference implementation used (a) as the
correctness oracle in tests and (b) as the automatic fallback on
non-TPU backends (kernels run under ``interpret=True`` only when
explicitly requested — the interpreter is for testing, not production).
"""

from paddlebox_tpu.ops.pallas_kernels.flash_attention import (
    flash_attention,
    flash_attention_reference,
)
from paddlebox_tpu.ops.pallas_kernels.seqpool_cvm import (
    seqpool_cvm_pallas,
)

__all__ = [
    "flash_attention",
    "flash_attention_reference",
    "seqpool_cvm_pallas",
]
