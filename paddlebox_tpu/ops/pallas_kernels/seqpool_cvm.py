"""Fused sequence-pool + CVM as a Pallas TPU kernel.

Role of the reference's hand-written CUDA kernel
``operators/fused/fused_seqpool_cvm_op.cu`` (SURVEY.md §2.2): pool each
instance's variable-length slot embeddings and apply the CVM counter
transform in one pass over the data.

TPU-first design: scatter-free pooling as an MXU matmul — the CSR
segment-id vector becomes a one-hot block ``onehot[n, b] = (seg[n] == b)``
and ``pooled = onehot^T @ x`` rides the systolic array, blocked over
(batch rows, input rows) with the input-row axis innermost so the VMEM
accumulator persists across grid steps. The CVM log-transform happens in
VMEM right before the single output write — the same fusion the CUDA
kernel does by hand. Padding rows carry segment id >= num_rows and fall
out of the one-hot automatically (the reference's "discard row").

The XLA reference path (``ops/seqpool.py``, segment_sum-based) is the
correctness oracle and the non-TPU fallback.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pool_kernel(seg_ref, x_ref, out_ref, acc, *, block_b: int,
                 block_n: int, use_cvm: bool):
    bi = pl.program_id(0)
    ni = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    seg = seg_ref[0]                      # [block_n]
    rows = (bi * block_b
            + lax.broadcasted_iota(jnp.int32, (block_n, block_b), 1))
    onehot = (seg[:, None] == rows).astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)      # [block_n, F]
    acc[:] += jnp.dot(onehot.T, x, preferred_element_type=jnp.float32)

    @pl.when(ni == nn - 1)
    def _():
        pooled = acc[:]
        if use_cvm:
            show = pooled[:, :1]
            click = pooled[:, 1:2]
            log_show = jnp.log(show + 1.0)
            ctr = jnp.log(click + 1.0) - log_show
            pooled = jnp.concatenate([log_show, ctr, pooled[:, 2:]],
                                     axis=1)
        out_ref[:] = pooled.astype(out_ref.dtype)


def _pool_pallas(x, segments, num_rows, *, use_cvm, block_b, block_n,
                 interpret):
    n, f = x.shape
    n_pad = _round_up(max(n, 1), block_n)
    b_pad = _round_up(max(num_rows, 1), block_b)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        segments = jnp.pad(segments, (0, n_pad - n),
                           constant_values=num_rows)
    seg2 = segments.astype(jnp.int32).reshape(1, n_pad)
    out = pl.pallas_call(
        functools.partial(_pool_kernel, block_b=block_b, block_n=block_n,
                          use_cvm=use_cvm),
        grid=(b_pad // block_b, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((1, block_n), lambda b, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, f), lambda b, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, f), lambda b, i: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, f), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_b, f), jnp.float32)],
        interpret=interpret,
    )(seg2, x)
    return out[:num_rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _seqpool_cvm(x, segments, num_rows, use_cvm, block_b, block_n,
                 interpret):
    out, _ = _seqpool_cvm_fwd(x, segments, num_rows, use_cvm, block_b,
                              block_n, interpret)
    return out


def _seqpool_cvm_fwd(x, segments, num_rows, use_cvm, block_b, block_n,
                     interpret):
    out = _pool_pallas(x, segments, num_rows, use_cvm=use_cvm,
                       block_b=block_b, block_n=block_n,
                       interpret=interpret)
    pooled_counters = None
    if use_cvm:
        # Raw pooled (show, click) recovered from the outputs: the CVM
        # transform is invertible — show = exp(out0)-1, click = exp(ctr
        # + log_show)-1 — so no extra residual pass is needed.
        pooled_counters = (jnp.exp(out[:, 0]) - 1.0,
                           jnp.exp(out[:, 1] + out[:, 0]) - 1.0)
    return out, (segments, pooled_counters)


def _seqpool_cvm_bwd(num_rows, use_cvm, block_b, block_n, interpret,
                     res, g):
    segments, pooled_counters = res
    g = g.astype(jnp.float32)
    if use_cvm:
        show, click = pooled_counters
        d_show = g[:, 0] / (show + 1.0) - g[:, 1] / (show + 1.0)
        d_click = g[:, 1] / (click + 1.0)
        g = jnp.concatenate([d_show[:, None], d_click[:, None], g[:, 2:]],
                            axis=1)
    # dx[i] = dpooled[seg[i]]; discard rows (seg >= num_rows) get zero.
    gpad = jnp.concatenate([g, jnp.zeros((1, g.shape[1]), g.dtype)], 0)
    seg = jnp.minimum(segments.astype(jnp.int32), num_rows)
    return gpad[seg], None


_seqpool_cvm.defvjp(_seqpool_cvm_fwd, _seqpool_cvm_bwd)


def seqpool_cvm_pallas(emb: jax.Array, show: jax.Array, click: jax.Array,
                       segments: jax.Array, num_rows: int, *,
                       use_cvm: bool = True,
                       clip_value: Optional[float] = None,
                       block_b: int = 256, block_n: int = 256,
                       use_pallas: Optional[bool] = None,
                       interpret: bool = False) -> jax.Array:
    """Drop-in Pallas twin of ``ops.fused_seqpool_cvm`` (sum mode).

    emb [n, D], show/click [n], segments [n] sorted CSR row ids with
    ``num_rows`` marking padding. Returns [num_rows, 2+D] (use_cvm) or
    [num_rows, D].
    """
    if use_pallas is None:
        from paddlebox_tpu.core import flags as _flags
        use_pallas = interpret or _flags.pallas_kernels_enabled()
    if not use_pallas:
        from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
        return fused_seqpool_cvm(emb, show, click, segments, num_rows,
                                 use_cvm=use_cvm, clip_value=clip_value)
    if clip_value is not None:
        emb = jnp.clip(emb, -clip_value, clip_value)
    x = jnp.concatenate([show[:, None], click[:, None], emb], axis=-1)
    out = _seqpool_cvm(x, segments, num_rows, use_cvm, block_b, block_n,
                       interpret)
    if not use_cvm:
        out = out[:, 2:]
    return out
