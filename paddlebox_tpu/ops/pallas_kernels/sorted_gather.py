"""Sorted-stream pull gather — the CopyForPull-class kernel.

Role of the reference's pull-side CUDA kernels (``box_wrapper.cu``
CopyForPull + the HeterComm per-shard table get): materialize the pull
payload ``table[rows, :pw]`` for a batch of request rows at memory
bandwidth. XLA's TPU gather costs ~6 ns/element regardless of layout
(PROFILE.md: 16.2 ms for [426K x 16], 25.4 ms at pull width 40) — two
orders of magnitude off HBM bandwidth for what is a streaming read. This
kernel instead SORTS the requests by destination row (XLA argsort —
cheap, and SHARED with the push-side ``sorted_scatter`` via
``sorted_stream_layout``), streams the table through VMEM one block at a
time via the Pallas pipeline, services each block's contiguous run of
requests with in-VMEM dynamic-row reads into per-block staging slots,
then inverse-permutes the slots back to original request order.

    out = sorted_gather(rows, table, width=pw)
    # == jnp.where(rows[:, None] < num_rows, table[rows, :pw], 0)  (exact)

Requests whose row >= ``num_rows`` are DROPPED (zeros) — callers use
that as the padding/trash sentinel, mirroring the scatter's drop
semantics (the lookup trash row carries zero pull columns, so dropping
is value-identical to gathering it).

Skew guard: per-block request counts are data-dependent; if any block's
run exceeds the static per-block budget (a pathologically hot row,
requested > UCAP times without dedup), ``lax.cond`` falls back to the
XLA gather — the kernel itself never reads past its budget. The budget,
block size, and DMA alignment constants are the scatter's: the two
kernels must agree for one argsort + one ``starts`` table to serve both
(``embedding/lookup.py`` shares the layout per width group per step).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
    ALIGN, BLOCK, UCAP, WINDOW)


def sorted_stream_layout(rows: jax.Array, num_rows: int) -> Tuple[
        jax.Array, jax.Array, jax.Array, jax.Array]:
    """The per-(rows, num_rows) sort layout BOTH sorted-stream kernels
    consume: (sorted_rows [n+WINDOW] incl. the sentinel pad, order [n],
    starts [nblocks+1], max_run []). Computing it once per width group
    and passing it to ``sorted_gather`` (pull) and
    ``sorted_scatter_accumulate`` (push) makes the step pay the argsort
    once instead of twice — rows >= num_rows are remapped to the
    one-past-the-last-block sentinel so they sort past every block
    boundary and count toward no block's run (the scatter's exact
    dropped-row convention)."""
    rows = rows.astype(jnp.int32)
    rows_pad = -(-num_rows // BLOCK) * BLOCK
    rows = jnp.where(rows >= num_rows, rows_pad, rows)
    order = jnp.argsort(rows).astype(jnp.int32)
    sorted_rows = jnp.concatenate(
        [rows[order], jnp.full((WINDOW,), rows_pad, jnp.int32)])
    nblocks = rows_pad // BLOCK
    boundaries = jnp.arange(nblocks + 1, dtype=jnp.int32) * BLOCK
    starts = jnp.searchsorted(sorted_rows, boundaries).astype(jnp.int32)
    max_run = jnp.max(starts[1:] - starts[:-1])
    return sorted_rows, order, starts, max_run


def _kernel(starts_ref, rows_ref, tbl_ref, out_ref, rows_s, sem):
    b = pl.program_id(0)
    lo = starts_ref[b]
    cnt = starts_ref[b + 1] - lo

    # Stage this block's run of request rows into SMEM (read one scalar
    # at a time at a data-dependent index — see sorted_scatter._kernel
    # for why SMEM + the ALIGN'd window): the copy starts at the run's
    # offset rounded down to the tile boundary and the loop skips the
    # `off` leading rows of slack. The rows input is padded by WINDOW so
    # the fixed-size slice never reads out of bounds.
    lo_a = pl.multiple_of((lo // ALIGN) * ALIGN, ALIGN)
    off = lo - lo_a
    dma = pltpu.make_async_copy(rows_ref.at[pl.ds(lo_a, WINDOW)], rows_s,
                                sem)
    dma.start()
    # Staging slots the run does not fill must not leak garbage (the
    # inverse permute only reads filled slots, but zeroing is one cheap
    # VMEM store and keeps interpret/compiled bit-identical); overlaps
    # the rows DMA like the scatter's accumulator zeroing.
    out_ref[:] = jnp.zeros_like(out_ref)
    dma.wait()

    base = b * BLOCK
    pw = out_ref.shape[1]

    def body(j, _):
        r = rows_s[j] - base
        out_ref[pl.ds(j - off, 1), :] = tbl_ref[pl.ds(r, 1), :pw]
        return 0

    lax.fori_loop(off, off + jnp.minimum(cnt, UCAP), body, 0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _sorted_gather_blocks(sorted_rows: jax.Array, table: jax.Array,
                          pw: int, interpret: bool) -> jax.Array:
    """[nblocks * UCAP, pw] staging slots: block b's run of requests
    lands at slots [b*UCAP, b*UCAP + run_len) in sorted order."""
    num_rows, w = table.shape
    nblocks = -(-num_rows // BLOCK)
    boundaries = jnp.arange(nblocks + 1, dtype=jnp.int32) * BLOCK
    starts = jnp.searchsorted(sorted_rows, boundaries).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),      # sorted rows (HBM)
            # The table streams through VMEM one [BLOCK, w] slab at a
            # time — the Pallas pipeline double-buffers the HBM reads,
            # so the random-access gather becomes a sequential sweep.
            # The last block may overhang num_rows; its padding rows are
            # never indexed (rows >= num_rows carry the sort sentinel).
            pl.BlockSpec((BLOCK, w), lambda b, starts: (b, 0)),
        ],
        out_specs=pl.BlockSpec((UCAP, pw), lambda b, starts: (b, 0)),
        scratch_shapes=[
            pltpu.SMEM((WINDOW,), jnp.int32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nblocks * UCAP, pw), jnp.float32),
        interpret=interpret,
    )(starts, sorted_rows, table)


def sorted_gather(rows: jax.Array, table: jax.Array, *,
                  width: int = None, interpret: bool = False,
                  layout: Tuple = None) -> jax.Array:
    """``jnp.where(rows[:, None] < num_rows, table[rows, :width], 0)``,
    exactly — via sort + VMEM-streamed block service. rows [n] int32
    (entries >= num_rows yield zeros); table [num_rows, W<=128] float32;
    width <= W selects the leading pull slice. ``layout`` is an optional
    precomputed ``sorted_stream_layout(rows, num_rows)`` (the push
    scatter shares it). Falls back to the XLA gather when a block's
    request run exceeds the kernel budget (hot row)."""
    n = rows.shape[0]
    num_rows, w = table.shape
    pw = w if width is None else width
    if w > 128:
        raise ValueError(
            f"table width {w} > 128: the kernel streams full fused rows "
            f"through single-tile (128-lane) VMEM blocks; gather wider "
            f"records with the XLA path or split the record")
    if not 0 < pw <= w:
        raise ValueError(f"width {pw} outside (0, {w}]")
    table = table.astype(jnp.float32)
    rows_pad = -(-num_rows // BLOCK) * BLOCK
    nblocks = rows_pad // BLOCK
    if layout is None:
        layout = sorted_stream_layout(rows, num_rows)
    sorted_rows, order, starts, max_run = layout
    if sorted_rows.shape[0] != n + WINDOW or starts.shape[0] != nblocks + 1:
        raise ValueError(
            f"shared layout shapes {sorted_rows.shape[0]}/"
            f"{starts.shape[0]} do not match rows/table "
            f"({n + WINDOW}/{nblocks + 1}) — it was built for different "
            f"(rows, num_rows)")

    def pallas_path(_):
        staged = _sorted_gather_blocks(sorted_rows, table, pw, interpret)
        # Slot of sorted rank s: its block's slot base + its rank within
        # the block's run. Sentinel (dropped) entries get the
        # one-past-the-end slot, turned into zeros after the gather.
        nslots = nblocks * UCAP
        s = jnp.arange(n, dtype=jnp.int32)
        srows = sorted_rows[:n]
        blk = jnp.minimum(srows // BLOCK, nblocks)
        slot = blk * UCAP + (s - starts[blk])
        slot = jnp.where(srows < num_rows, slot, nslots)
        # Inverse permute: order maps sorted rank -> original position,
        # so one small int32 scatter routes every slot index home and
        # the payload moves in a single compact gather.
        idx = jnp.zeros((n,), jnp.int32).at[order].set(slot)
        picked = staged[jnp.minimum(idx, nslots - 1)]
        return jnp.where((idx < nslots)[:, None], picked, 0.0)

    def xla_path(_):
        keep = rows < num_rows
        safe = jnp.where(keep, rows, 0)
        return jnp.where(keep[:, None], table[safe, :pw], 0.0)

    return lax.cond(max_run <= UCAP, pallas_path, xla_path, operand=None)
