"""Blocked flash attention as a Pallas TPU kernel (fwd + bwd).

Role of the reference's fused attention CUDA ops
(``operators/fused/fused_attention_op.cu``,
``fused_multi_transformer_op.cu``, ``fused_softmax_mask.cu.h``): one
kernel computes softmax(QK^T)V without materializing the [S, S] score
matrix in HBM.

TPU-first design: the classic flash schedule mapped onto the Pallas grid —
grid (batch*heads, q_blocks, k_blocks) with the k-block axis innermost so
VMEM scratch (acc, running max m, running sum l) persists across the
sequential TPU grid steps; QK^T and PV ride the MXU via ``jnp.dot`` with
``preferred_element_type=float32``; the online-softmax rescale is VPU
work fused in VMEM. The backward pass is two more kernels (dq, and dk/dv)
recomputing P from the saved logsumexp — the standard recompute-not-store
flash backward.

``q_offset``/``k_offset`` shift the *global* positions used for causal
masking, so the same kernel serves ring attention's per-step blocks
(``parallel/sp.py``) where each device holds a rotated K/V shard.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30


def _pick_block(s: int, preferred: int) -> int:
    """Block size for a sequence of length s: the preferred tile when the
    sequence is at least that long, else s rounded up to a sublane
    multiple (the wrapper pads the sequence to a block multiple)."""
    if s >= preferred:
        return preferred
    return max(8, -(-s // 8) * 8)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _block_live(qoff_ref, koff_ref, kreal_ref, qi, ki, *, causal,
                block_q, block_k):
    """Scalar predicate: does block (qi, ki) contain any unmasked entry?
    False for k-padding blocks and the causal upper triangle — lets every
    kernel skip them (the flash 2x-causal saving)."""
    live = (ki * block_k) < kreal_ref[0, 0]
    if causal:
        first_k = koff_ref[0, 0] + ki * block_k
        last_q = qoff_ref[0, 0] + qi * block_q + (block_q - 1)
        live = jnp.logical_and(live, first_k <= last_q)
    return live


def _fwd_kernel(qoff_ref, koff_ref, kreal_ref, q_ref, k_ref, v_ref,
                out_ref, lse_ref, acc, m_scr, l_scr, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_pos = (qoff_ref[0, 0] + qi * block_q
             + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
    k_local = (ki * block_k
               + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    k_pos = koff_ref[0, 0] + k_local
    valid = k_local < kreal_ref[0, 0]
    if causal:
        valid = jnp.logical_and(valid, k_pos <= q_pos)

    # Skip fully-masked k blocks (the causal upper triangle).
    any_valid = _block_live(qoff_ref, koff_ref, kreal_ref, qi, ki,
                            causal=causal, block_q=block_q,
                            block_k=block_k)

    @pl.when(any_valid)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = jnp.where(valid, s, _NEG_BIG)

        m_prev = m_scr[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.where(valid, jnp.exp(s - m_cur[:, None]), 0.0)
        w_prev = jnp.exp(m_prev - m_cur)
        l_scr[:, 0] = l_scr[:, 0] * w_prev + jnp.sum(p, axis=1)
        v = v_ref[0].astype(jnp.float32)
        acc[:] = (acc[:] * w_prev[:, None]
                  + jnp.dot(p, v, preferred_element_type=jnp.float32))
        m_scr[:, 0] = m_cur

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, 0]
        m = m_scr[:, 0]
        out_ref[0] = (acc[:] / jnp.maximum(l, 1e-20)[:, None]
                      ).astype(out_ref.dtype)
        # lse block is (1, 1, block_q): TPU tiling requires the block's
        # second-minor dim to divide 8 or equal the array dim, which a
        # (1, block_q) view of [BH, Sq] cannot satisfy — row stats ride
        # as [BH, 1, Sq] instead.
        lse_ref[0, 0] = jnp.where(l > 0.0,
                                  m + jnp.log(jnp.maximum(l, 1e-20)),
                                  _NEG_BIG)


def _fwd_pallas(q3, k3, v3, qoff, koff, sk_real, *, scale, causal,
                block_q, block_k, interpret):
    """q3 [BH, Sq, D] (padded); returns (out [BH, Sq, D], lse [BH, Sq])."""
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq, nk = sq // block_q, sk // block_k
    grid = (bh, nq, nk)
    smem = functools.partial(pl.BlockSpec, (1, 1),
                             memory_space=pltpu.SMEM)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem(lambda b, i, j: (0, 0)),
            smem(lambda b, i, j: (0, 0)),
            smem(lambda b, i, j: (0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, sk_real, q3, k3, v3)
    return out, lse3.reshape(bh, sq)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _recompute_p(q_ref, k_ref, lse_ref, qoff_ref, koff_ref, kreal_ref,
                 qi, ki, *, scale, causal, block_q, block_k):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    q_pos = (qoff_ref[0, 0] + qi * block_q
             + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
    k_local = (ki * block_k
               + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    k_pos = koff_ref[0, 0] + k_local
    valid = k_local < kreal_ref[0, 0]
    if causal:
        valid = jnp.logical_and(valid, k_pos <= q_pos)
    lse = lse_ref[0, 0]
    p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
    return p, valid


def _dq_kernel(qoff_ref, koff_ref, kreal_ref, q_ref, k_ref, v_ref,
               do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, scale,
               causal, block_q, block_k):
    qi, ki, nk = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_live(qoff_ref, koff_ref, kreal_ref, qi, ki,
                         causal=causal, block_q=block_q, block_k=block_k))
    def _():
        p, _ = _recompute_p(q_ref, k_ref, lse_ref, qoff_ref, koff_ref,
                            kreal_ref, qi, ki, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        k = k_ref[0].astype(jnp.float32)
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, koff_ref, kreal_ref, q_ref, k_ref, v_ref,
                do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc,
                dv_acc, *, scale, causal, block_q, block_k):
    ki, qi, nq = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_live(qoff_ref, koff_ref, kreal_ref, qi, ki,
                         causal=causal, block_q=block_q, block_k=block_k))
    def _():
        p, _ = _recompute_p(q_ref, k_ref, lse_ref, qoff_ref, koff_ref,
                            kreal_ref, qi, ki, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        q = q_ref[0].astype(jnp.float32)
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q3, k3, v3, out3, lse, do3, qoff, koff, sk_real, *,
                scale, causal, block_q, block_k, interpret):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.sum(do3.astype(jnp.float32) * out3.astype(jnp.float32),
                    axis=-1)
    # Row stats as [BH, 1, Sq] — (1, block) blocks of a 2-D array break
    # the TPU block-tiling rule (see the fwd lse spec).
    lse3 = lse.reshape(bh, 1, sq)
    delta3 = delta.reshape(bh, 1, sq)
    smem = functools.partial(pl.BlockSpec, (1, 1),
                             memory_space=pltpu.SMEM)
    qspec = lambda bm, im: pl.BlockSpec((1, bm, d), im,
                                        memory_space=pltpu.VMEM)
    rspec = lambda bm, im: pl.BlockSpec((1, 1, bm), im,
                                        memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            smem(lambda b, i, j: (0, 0)), smem(lambda b, i, j: (0, 0)),
            smem(lambda b, i, j: (0, 0)),
            qspec(block_q, lambda b, i, j: (b, i, 0)),
            qspec(block_k, lambda b, i, j: (b, j, 0)),
            qspec(block_k, lambda b, i, j: (b, j, 0)),
            qspec(block_q, lambda b, i, j: (b, i, 0)),
            rspec(block_q, lambda b, i, j: (b, 0, i)),
            rspec(block_q, lambda b, i, j: (b, 0, i)),
        ],
        out_specs=qspec(block_q, lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qoff, koff, sk_real, q3, k3, v3, do3, lse3, delta3)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            smem(lambda b, j, i: (0, 0)), smem(lambda b, j, i: (0, 0)),
            smem(lambda b, j, i: (0, 0)),
            qspec(block_q, lambda b, j, i: (b, i, 0)),
            qspec(block_k, lambda b, j, i: (b, j, 0)),
            qspec(block_k, lambda b, j, i: (b, j, 0)),
            qspec(block_q, lambda b, j, i: (b, i, 0)),
            rspec(block_q, lambda b, j, i: (b, 0, i)),
            rspec(block_q, lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[qspec(block_k, lambda b, j, i: (b, j, 0)),
                   qspec(block_k, lambda b, j, i: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v3.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qoff, koff, sk_real, q3, k3, v3, do3, lse3, delta3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API: custom-VJP wrapper over [B, S, H, D] tensors
# ---------------------------------------------------------------------------

def _to3d(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _to4d(x3, b, h):
    bh, s, d = x3.shape
    return x3.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _pad_seq(x3, block):
    s = x3.shape[1]
    pad = (-s) % block
    if pad:
        x3 = jnp.pad(x3, ((0, 0), (0, pad), (0, 0)))
    return x3


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q3, k3, v3, qoff, koff, scale, causal, block_q, block_k,
           interpret):
    out, _ = _flash_fwd(q3, k3, v3, qoff, koff, scale, causal, block_q,
                        block_k, interpret)
    return out


def _flash_fwd(q3, k3, v3, qoff, koff, scale, causal, block_q, block_k,
               interpret):
    sq, sk = q3.shape[1], k3.shape[1]
    sk_real = jnp.full((1, 1), sk, jnp.int32)
    qp = _pad_seq(q3, block_q)
    kp = _pad_seq(k3, block_k)
    vp = _pad_seq(v3, block_k)
    out, lse = _fwd_pallas(qp, kp, vp, qoff, koff, sk_real, scale=scale,
                           causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)
    out = out[:, :sq]
    lse = lse[:, :sq]
    return out, (q3, k3, v3, out, lse, qoff, koff)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q3, k3, v3, out, lse, qoff, koff = res
    sq, sk = q3.shape[1], k3.shape[1]
    sk_real = jnp.full((1, 1), sk, jnp.int32)
    qp, dop = _pad_seq(q3, block_q), _pad_seq(g, block_q)
    outp = _pad_seq(out, block_q)
    # Padded q rows recompute against lse=0 garbage; force them inert.
    lsep = jnp.pad(lse, ((0, 0), (0, qp.shape[1] - sq)),
                   constant_values=jnp.inf)
    kp, vp = _pad_seq(k3, block_k), _pad_seq(v3, block_k)
    dq, dk, dv = _bwd_pallas(qp, kp, vp, outp, lsep, dop, qoff, koff,
                             sk_real, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk], None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_reference(q, k, v, *, causal: bool = False,
                              scale: Optional[float] = None,
                              q_offset=0, k_offset=0) -> jax.Array:
    """XLA reference (materializes scores): oracle + non-TPU fallback."""
    d = q.shape[-1]
    if scale is None:
        scale = float(d) ** -0.5
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, :, None, :], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(p.dtype),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, q_offset=0, k_offset=0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False) -> jax.Array:
    """Flash attention over [B, S, H, D] tensors (differentiable).

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU backends,
    the XLA reference elsewhere (``interpret=True`` forces the kernel in
    interpreter mode — for tests). ``block_q``/``block_k`` default to
    the ``flash_block_{q,k}`` flags (tuned per hardware by
    tools/tune_flash_blocks.py) so every call site picks up the tuned
    tiles without plumbing.
    """
    from paddlebox_tpu.core import flags as _flags
    # Per-parameter None checks: an explicit (invalid) 0 must error in
    # the kernel's own validation, not silently fall back to the flag.
    if block_q is None:
        block_q = int(_flags.flag("flash_block_q"))
    if block_k is None:
        block_k = int(_flags.flag("flash_block_k"))
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    if use_pallas is None:
        use_pallas = interpret or _flags.pallas_kernels_enabled()
    if not use_pallas:
        return flash_attention_reference(q, k, v, causal=causal,
                                         scale=scale, q_offset=q_offset,
                                         k_offset=k_offset)
    b, sq, h, d = q.shape
    bq = _pick_block(max(sq, 1), block_q)
    bk = _pick_block(max(k.shape[1], 1), block_k)
    qoff = jnp.full((1, 1), q_offset, jnp.int32)
    koff = jnp.full((1, 1), k_offset, jnp.int32)
    out3 = _flash(_to3d(q), _to3d(k), _to3d(v), qoff, koff, scale,
                  causal, bq, bk, interpret)
    return _to4d(out3, b, h).astype(q.dtype)
