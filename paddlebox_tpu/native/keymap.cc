// Native host key-map: pass-key dedup + feasign -> device-row lookup.
//
// Role of the CPU-side hot path of the reference's pass build and batch
// feed: PreBuildTask's multi-thread key dedup into shard buckets
// (ps_gpu_wrapper.cc:114) and the per-batch key->row flattening feeding
// CopyKeys (box_wrapper.cu). SURVEY.md §7 ranks "per-pass index build
// throughput on host" as hard part #1 — numpy's unique/searchsorted are
// single-threaded O(n log n); this is a sharded open-addressing hash map
// with counting-scatter parallel build and parallel batch lookup.
//
// Exposed via a C ABI consumed by ctypes (native/keymap_py.py).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64 finalizer: well-mixed 64-bit hash, injective.
static inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

static inline int num_threads_for(int64_t n) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  int t = static_cast<int>(std::min<int64_t>(hw, (n + (1 << 16) - 1) >> 16));
  return t < 1 ? 1 : t;
}

template <typename Fn>
static void parallel_chunks(int64_t n, int nt, Fn fn) {
  if (nt <= 1) {
    fn(0, 0, n);
    return;
  }
  std::vector<std::thread> ths;
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    ths.emplace_back([fn, t, lo, hi]() { fn(t, lo, hi); });
  }
  for (auto& th : ths) th.join();
}

// One open-addressing sub-table (linear probing). Keys are nonzero
// (0 = null feasign, handled explicitly); empty slot sentinel key = 0.
struct SubMap {
  std::vector<uint64_t> keys;
  std::vector<int64_t> vals;
  uint64_t mask = 0;

  void init(size_t n) {
    size_t cap = 16;
    while (cap < n * 2) cap <<= 1;  // load factor <= 0.5
    keys.assign(cap, 0);
    vals.assign(cap, -1);
    mask = cap - 1;
  }

  inline void insert(uint64_t k, int64_t v) {
    uint64_t i = mix64(k) & mask;
    while (keys[i] != 0) i = (i + 1) & mask;
    keys[i] = k;
    vals[i] = v;
  }

  // Insert if absent; returns true when newly inserted.
  inline bool insert_unique(uint64_t k) {
    uint64_t i = mix64(k) & mask;
    while (true) {
      if (keys[i] == k) return false;
      if (keys[i] == 0) {
        keys[i] = k;
        return true;
      }
      i = (i + 1) & mask;
    }
  }

  inline int64_t find(uint64_t k) const {
    uint64_t i = mix64(k) & mask;
    while (true) {
      if (keys[i] == k) return vals[i];
      if (keys[i] == 0) return -1;
      i = (i + 1) & mask;
    }
  }
};

constexpr int kShardBits = 6;  // 64 sub-maps
constexpr int kShards = 1 << kShardBits;

static inline int shard_of(uint64_t k) {
  return static_cast<int>(mix64(k) >> (64 - kShardBits));
}

struct KeyMap {
  SubMap shards[kShards];
  int64_t n = 0;
};

// Counting scatter: partition values of in[0..n) into per-shard contiguous
// regions of out (+ optional payload), using shard_fn. Returns per-shard
// (start, size). Two parallel passes: count, then scatter into disjoint
// per-(thread, shard) windows — no locks, no atomics on the hot path.
template <typename ShardFn>
static std::vector<std::pair<int64_t, int64_t>> counting_scatter(
    const uint64_t* in, int64_t n, int nshards, ShardFn shard_fn, int nt,
    std::vector<uint64_t>* out, std::vector<int64_t>* payload_out) {
  std::vector<std::vector<int64_t>> counts(
      nt, std::vector<int64_t>(nshards, 0));
  parallel_chunks(n, nt, [&](int t, int64_t lo, int64_t hi) {
    auto& c = counts[t];
    for (int64_t i = lo; i < hi; ++i) {
      int s = shard_fn(in[i]);
      if (s >= 0) ++c[s];
    }
  });
  // offsets[t][s] = write cursor for thread t within shard s's region.
  std::vector<std::pair<int64_t, int64_t>> regions(nshards);
  std::vector<std::vector<int64_t>> offsets(
      nt, std::vector<int64_t>(nshards, 0));
  int64_t pos = 0;
  for (int s = 0; s < nshards; ++s) {
    regions[s].first = pos;
    for (int t = 0; t < nt; ++t) {
      offsets[t][s] = pos;
      pos += counts[t][s];
    }
    regions[s].second = pos - regions[s].first;
  }
  out->resize(pos);
  if (payload_out) payload_out->resize(pos);
  parallel_chunks(n, nt, [&](int t, int64_t lo, int64_t hi) {
    auto& off = offsets[t];
    for (int64_t i = lo; i < hi; ++i) {
      int s = shard_fn(in[i]);
      if (s < 0) continue;
      int64_t w = off[s]++;
      (*out)[w] = in[i];
      if (payload_out) (*payload_out)[w] = i;
    }
  });
  return regions;
}

}  // namespace

extern "C" {

// Build a key -> rank map from the pass's SORTED unique key array (rank =
// position in that array, the global row id before shard-block layout).
void* pbx_keymap_build(const uint64_t* sorted_keys, int64_t n) {
  KeyMap* m = new KeyMap();
  m->n = n;
  int nt = num_threads_for(n);
  std::vector<uint64_t> scat_keys;
  std::vector<int64_t> scat_rank;
  auto regions = counting_scatter(
      sorted_keys, n, kShards, [](uint64_t k) { return shard_of(k); }, nt,
      &scat_keys, &scat_rank);
  // Build sub-maps in parallel, each from its contiguous region.
  std::atomic<int> next{0};
  std::vector<std::thread> ths;
  for (int t = 0; t < nt; ++t) {
    ths.emplace_back([&]() {
      int s;
      while ((s = next.fetch_add(1)) < kShards) {
        auto [lo, sz] = regions[s];
        m->shards[s].init(static_cast<size_t>(sz) + 1);
        for (int64_t i = lo; i < lo + sz; ++i)
          m->shards[s].insert(scat_keys[i], scat_rank[i]);
      }
    });
  }
  for (auto& th : ths) th.join();
  return m;
}

int64_t pbx_keymap_size(void* h) { return static_cast<KeyMap*>(h)->n; }

// Batch lookup: keys[m] -> device rows in the round-robin sharded layout
// (table.py map_keys_to_rows contract): found rank g -> shard g % S at
// slot g / S (the deal keeps every shard ~equally loaded under the pow2
// rows_per_shard rounding); missing or 0 -> round-robin trash row
// (position % num_shards).
void pbx_keymap_lookup(void* h, const uint64_t* batch, int64_t m,
                       int32_t rows_per_shard, int32_t num_shards,
                       int32_t* out_rows) {
  KeyMap* km = static_cast<KeyMap*>(h);
  int64_t block = rows_per_shard + 1;
  parallel_chunks(m, num_threads_for(m), [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint64_t k = batch[i];
      int64_t g = (k == 0) ? -1 : km->shards[shard_of(k)].find(k);
      if (g < 0) {
        int64_t pad_shard = i % num_shards;
        out_rows[i] =
            static_cast<int32_t>(pad_shard * block + rows_per_shard);
      } else {
        int64_t shard = g % num_shards;
        int64_t row = g / num_shards;
        out_rows[i] = static_cast<int32_t>(shard * block + row);
      }
    }
  });
}

void pbx_keymap_free(void* h) { delete static_cast<KeyMap*>(h); }

// ---------------------------------------------------------------------------
// Dedup: unsorted (possibly huge, duplicate-heavy) pass keys -> sorted
// unique array (np.unique replacement for feed_pass). Range-sharded by top
// key byte so per-shard sorted outputs concatenate globally sorted; each
// shard dedups with a local hash set before sorting only its unique keys.
// ---------------------------------------------------------------------------

namespace {
struct DedupResult {
  std::vector<std::vector<uint64_t>> parts;
  int64_t total = 0;
};
constexpr int kRangeShards = 256;
}  // namespace

void* pbx_dedup_u64(const uint64_t* keys, int64_t n) {
  DedupResult* r = new DedupResult();
  r->parts.resize(kRangeShards);
  int nt = num_threads_for(n);
  std::vector<uint64_t> scat;
  auto regions = counting_scatter(
      keys, n, kRangeShards,
      [](uint64_t k) { return k == 0 ? -1 : static_cast<int>(k >> 56); },
      nt, &scat, nullptr);
  std::atomic<int> next{0};
  std::atomic<int64_t> total{0};
  std::vector<std::thread> ths;
  for (int t = 0; t < nt; ++t) {
    ths.emplace_back([&]() {
      int s;
      while ((s = next.fetch_add(1)) < kRangeShards) {
        auto [lo, sz] = regions[s];
        if (sz == 0) continue;
        SubMap set;
        set.init(static_cast<size_t>(sz) + 1);
        std::vector<uint64_t> uniq;
        uniq.reserve(sz);
        for (int64_t i = lo; i < lo + sz; ++i) {
          if (set.insert_unique(scat[i])) uniq.push_back(scat[i]);
        }
        std::sort(uniq.begin(), uniq.end());
        total.fetch_add(static_cast<int64_t>(uniq.size()));
        r->parts[s] = std::move(uniq);
      }
    });
  }
  for (auto& th : ths) th.join();
  r->total = total.load();
  return r;
}

int64_t pbx_dedup_size(void* h) { return static_cast<DedupResult*>(h)->total; }

void pbx_dedup_fill(void* h, uint64_t* out) {
  DedupResult* r = static_cast<DedupResult*>(h);
  int64_t off = 0;
  for (auto& p : r->parts) {
    if (!p.empty()) {
      std::memcpy(out + off, p.data(), p.size() * sizeof(uint64_t));
      off += static_cast<int64_t>(p.size());
    }
  }
}

void pbx_dedup_free(void* h) { delete static_cast<DedupResult*>(h); }

}  // extern "C"
