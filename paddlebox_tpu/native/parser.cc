// Native svm-format parser: text chunk -> columnar CSR arrays.
//
// Role of the reference's C++ reader parse loops
// (SlotRecordInMemoryDataFeed::ParseOneInstance / LoadIntoMemoryByLine,
// paddle/fluid/framework/data_feed.cc:2142-2395): the data-pipeline hot
// path is tokenizing gigabytes of text into slot records. Python-level
// parsing is ~50x slower; this library parses into the exact columnar
// layout paddlebox_tpu/data/columnar.py consumes.
//
// Format per line (see data/parser.py):
//   <label...> <slot>:<feasign> ... <slot>:v1,v2,... ...
//
// C ABI (ctypes): two-phase — parse into C++ vectors, query sizes,
// caller allocates numpy arrays, fill, free.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct SlotDef {
  int index;        // dense or sparse ordinal
  bool is_dense;
  int dim;          // dense only
};

struct ParseResult {
  int num_labels = 0;
  int n_sparse = 0;
  int n_dense = 0;
  int64_t n_rows = 0;
  int64_t malformed = 0;
  int64_t dropped_signs = 0;  // null/out-of-range feasigns
  std::vector<float> labels;                       // n_rows * num_labels
  std::vector<std::vector<uint64_t>> sparse_ids;   // per sparse slot
  std::vector<std::vector<int64_t>> sparse_offsets;  // per slot, n_rows+1
  std::vector<std::vector<float>> dense_vals;      // per dense slot, n*dim
};

inline bool parse_double(const char* b, const char* e, double* out) {
  if (b == e) return false;
  char* endp = nullptr;
  std::string tmp(b, e - b);  // strtod needs NUL; tokens are short
  *out = std::strtod(tmp.c_str(), &endp);
  return endp == tmp.c_str() + tmp.size();
}

// Feasign parse outcomes mirror the python parser's contract:
// a syntactically-valid integer that is negative/zero/overflowing is a
// DROPPED token (line kept); non-integer garbage rejects the LINE.
enum FeasignStatus { FS_OK, FS_NOT_INT, FS_DROP };

inline FeasignStatus parse_feasign(const char* b, const char* e,
                                   uint64_t* out) {
  if (b == e) return FS_NOT_INT;
  bool neg = false;
  if (*b == '-') { neg = true; ++b; if (b == e) return FS_NOT_INT; }
  uint64_t v = 0;
  for (const char* p = b; p != e; ++p) {
    if (*p < '0' || *p > '9') return FS_NOT_INT;
    uint64_t digit = static_cast<uint64_t>(*p - '0');
    if (v > (UINT64_MAX - digit) / 10u) return FS_DROP;  // overflow
    v = v * 10u + digit;
  }
  if (neg || v == 0) return FS_DROP;  // negative / null sentinel
  *out = v;
  return FS_OK;
}

}  // namespace

extern "C" {

// slot_names: n_slots NUL-terminated names; is_dense/dims parallel arrays.
ParseResult* pbx_parse_svm(const char* buf, int64_t len,
                           const char** slot_names, const uint8_t* is_dense,
                           const int32_t* dims, int32_t n_slots,
                           int32_t num_labels) {
  auto* res = new ParseResult();
  res->num_labels = num_labels;
  std::unordered_map<std::string, SlotDef> slots;
  for (int i = 0; i < n_slots; ++i) {
    SlotDef d;
    d.is_dense = is_dense[i] != 0;
    d.dim = dims[i];
    d.index = d.is_dense ? res->n_dense++ : res->n_sparse++;
    slots.emplace(slot_names[i], d);
  }
  res->sparse_ids.resize(res->n_sparse);
  res->sparse_offsets.assign(res->n_sparse, std::vector<int64_t>{0});
  res->dense_vals.resize(res->n_dense);

  std::vector<float> row_labels(num_labels);
  std::vector<float> row_dense;  // scratch per dense slot
  const char* p = buf;
  const char* end = buf + len;
  std::string key;  // reused
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    const char* q = p;
    const char* line_start = p;
    p = line_end + 1;
    bool blank = true;
    for (const char* c = line_start; c < line_end; ++c)
      if (*c != ' ' && *c != '\r' && *c != '\t') { blank = false; break; }
    if (blank) continue;

    // --- labels ---
    bool ok = true;
    for (int li = 0; li < num_labels; ++li) {
      while (q < line_end && *q == ' ') ++q;
      const char* tb = q;
      while (q < line_end && *q != ' ') ++q;
      double d;
      if (!parse_double(tb, q, &d)) { ok = false; break; }
      row_labels[li] = static_cast<float>(d);
    }
    if (!ok) { res->malformed++; continue; }

    // --- tokens: stage into per-row buffers so a malformed token can
    // reject the whole line (parity with the python parser) ---
    std::vector<std::pair<int, uint64_t>> row_sparse;
    std::vector<std::pair<int, std::vector<float>>> row_dense_vals;
    int64_t row_dropped = 0;
    while (ok && q < line_end) {
      while (q < line_end && *q == ' ') ++q;
      if (q >= line_end) break;
      const char* tb = q;
      while (q < line_end && *q != ' ') ++q;
      const char* colon = static_cast<const char*>(
          memchr(tb, ':', static_cast<size_t>(q - tb)));
      if (!colon) { ok = false; break; }
      key.assign(tb, static_cast<size_t>(colon - tb));
      auto it = slots.find(key);
      if (it == slots.end()) continue;  // unused slot
      if (it->second.is_dense) {
        std::vector<float> vals;
        const char* vb = colon + 1;
        while (vb <= q) {
          const char* ve = static_cast<const char*>(
              memchr(vb, ',', static_cast<size_t>(q - vb)));
          if (!ve) ve = q;
          double d;
          if (!parse_double(vb, ve, &d)) { ok = false; break; }
          vals.push_back(static_cast<float>(d));
          vb = ve + 1;
          if (ve == q) break;
        }
        if (!ok) break;
        row_dense_vals.emplace_back(it->second.index, std::move(vals));
      } else {
        uint64_t sign;
        FeasignStatus st = parse_feasign(colon + 1, q, &sign);
        if (st == FS_NOT_INT) { ok = false; break; }
        if (st == FS_DROP) { row_dropped++; continue; }
        row_sparse.emplace_back(it->second.index, sign);
      }
    }
    if (!ok) { res->malformed++; continue; }

    // --- commit row ---
    res->dropped_signs += row_dropped;
    res->labels.insert(res->labels.end(), row_labels.begin(),
                       row_labels.end());
    for (auto& pr : row_sparse) res->sparse_ids[pr.first].push_back(pr.second);
    for (int s = 0; s < res->n_sparse; ++s)
      res->sparse_offsets[s].push_back(
          static_cast<int64_t>(res->sparse_ids[s].size()));
    // dense: fixed dim per slot, zero-fill
    for (int dslot = 0; dslot < res->n_dense; ++dslot) {
      int dim = 0;
      for (int i = 0; i < n_slots; ++i)
        if (is_dense[i]) { if (slots[slot_names[i]].index == dslot) dim = dims[i]; }
      size_t base = res->dense_vals[dslot].size();
      res->dense_vals[dslot].resize(base + static_cast<size_t>(dim), 0.f);
      for (auto& pr : row_dense_vals) {
        if (pr.first == dslot) {
          for (size_t k = 0; k < pr.second.size() &&
               k < static_cast<size_t>(dim); ++k)
            res->dense_vals[dslot][base + k] = pr.second[k];
        }
      }
    }
    res->n_rows++;
  }
  return res;
}

int64_t pbx_result_rows(ParseResult* r) { return r->n_rows; }
int64_t pbx_result_malformed(ParseResult* r) { return r->malformed; }
int64_t pbx_result_dropped(ParseResult* r) { return r->dropped_signs; }
int64_t pbx_result_sparse_size(ParseResult* r, int32_t slot) {
  return static_cast<int64_t>(r->sparse_ids[slot].size());
}

void pbx_result_fill(ParseResult* r, float* labels, uint64_t** sparse_ids,
                     int64_t** sparse_offsets, float** dense_vals) {
  memcpy(labels, r->labels.data(), r->labels.size() * sizeof(float));
  for (int s = 0; s < r->n_sparse; ++s) {
    memcpy(sparse_ids[s], r->sparse_ids[s].data(),
           r->sparse_ids[s].size() * sizeof(uint64_t));
    memcpy(sparse_offsets[s], r->sparse_offsets[s].data(),
           r->sparse_offsets[s].size() * sizeof(int64_t));
  }
  for (int d = 0; d < r->n_dense; ++d) {
    memcpy(dense_vals[d], r->dense_vals[d].data(),
           r->dense_vals[d].size() * sizeof(float));
  }
}

void pbx_result_free(ParseResult* r) { delete r; }

}  // extern "C"
