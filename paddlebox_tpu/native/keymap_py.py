"""ctypes wrapper for the native key-map + dedup (native/keymap.cc).

``KeyMap`` is the per-pass feasign → device-row index (role of the
PreBuildTask shard tables + CopyKeys host map); ``dedup_keys`` replaces
``np.unique`` for pass-key registration. Both fall back to numpy when the
native library is unavailable, preserving exact semantics
(``table.map_keys_to_rows``).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from paddlebox_tpu.core import flags, monitor
from paddlebox_tpu.embedding.table import map_keys_to_rows
from paddlebox_tpu.native.build import load_library

# Shared worker pool for the sharded numpy-fallback lookup (the native
# path parallelizes inside the GIL-releasing C call and never uses it).
_POOL = None
_POOL_LOCK = threading.Lock()
_POOL_WORKERS = 8


def _lookup_pool():
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _POOL = ThreadPoolExecutor(max_workers=_POOL_WORKERS,
                                       thread_name_prefix="pbx-keymap")
        return _POOL


def _fallback_threads(m: int) -> int:
    """Worker count for a numpy-fallback lookup of m ids
    (FLAGS_keymap_lookup_threads; 0 = auto). Small batches stay
    single-threaded — thread handoff would cost more than the
    searchsorted."""
    n = int(flags.flag("keymap_lookup_threads"))
    if n <= 0:
        if m < (1 << 16):
            return 1
        n = min(4, max(1, (os.cpu_count() or 1) // 2))
    return max(1, min(n, _POOL_WORKERS))


def dedup_keys(keys: np.ndarray) -> np.ndarray:
    """Sorted unique nonzero keys (np.unique + drop-0 equivalent).

    The native path wins by parallelism (hash-shard dedup across cores);
    on boxes with few cores numpy's single-threaded sort is faster, so
    fall back there.
    """
    keys = np.ascontiguousarray(keys, np.uint64)
    lib = load_library()
    if lib is None or keys.size == 0 or (os.cpu_count() or 1) < 4:
        u = np.unique(keys)
        return u[u != 0]
    h = lib.pbx_dedup_u64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), keys.size)
    try:
        n = lib.pbx_dedup_size(h)
        out = np.empty((n,), np.uint64)
        if n:
            lib.pbx_dedup_fill(
                h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        monitor.add("native/dedup_keys", int(keys.size))
        return out
    finally:
        lib.pbx_dedup_free(h)


class KeyMap:
    """Hash map from the pass's sorted unique keys to their rank, serving
    batch key→device-row lookups (round-robin sharded layout — rank g ->
    shard g % S at slot g // S — with round-robin trash sentinels; exact
    ``map_keys_to_rows`` semantics)."""

    def __init__(self, sorted_keys: np.ndarray, rows_per_shard: int,
                 num_shards: int = 1):
        self._keys = np.ascontiguousarray(sorted_keys, np.uint64)
        self.rows_per_shard = int(rows_per_shard)
        self.num_shards = int(num_shards)
        self._lib = load_library()
        self._handle: Optional[int] = None
        if self._lib is not None and self._keys.size:
            self._handle = self._lib.pbx_keymap_build(
                self._keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                self._keys.size)

    def lookup(self, batch_keys: np.ndarray,
               out: Optional[np.ndarray] = None) -> np.ndarray:
        """batch feasigns [m] → device rows [m] int32.

        ``out``: optional preallocated int32 [m] buffer for callers that
        recycle one per pipeline slot instead of allocating ~1.7 MB per
        batch. The native path releases the GIL and
        parallelizes internally (keymap.cc parallel_chunks); the numpy
        fallback shards the batch across the module worker pool
        (searchsorted releases the GIL on large inputs), staying
        bit-identical via the position-offset-aware trash assignment."""
        batch = np.ascontiguousarray(batch_keys, np.uint64)
        m = batch.size
        if out is None:
            out = np.empty((m,), np.int32)
        if self._handle is not None:
            if m:
                self._lib.pbx_keymap_lookup(
                    self._handle,
                    batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                    m, self.rows_per_shard, self.num_shards,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return out
        nt = _fallback_threads(m)
        if nt <= 1:
            out[:m] = map_keys_to_rows(self._keys, batch,
                                       self.rows_per_shard,
                                       self.num_shards)
            return out
        chunk = -(-m // nt)

        def work(lo: int) -> None:
            hi = min(m, lo + chunk)
            out[lo:hi] = map_keys_to_rows(
                self._keys, batch[lo:hi], self.rows_per_shard,
                self.num_shards, index_offset=lo)

        futs = [_lookup_pool().submit(work, lo)
                for lo in range(0, m, chunk)]
        for f in futs:
            f.result()
        monitor.add("native/keymap_lookup_sharded", m)
        return out

    def close(self) -> None:
        if self._handle is not None and self._lib is not None:
            self._lib.pbx_keymap_free(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
