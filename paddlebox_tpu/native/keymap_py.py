"""ctypes wrapper for the native key-map + dedup (native/keymap.cc).

``KeyMap`` is the per-pass feasign → device-row index (role of the
PreBuildTask shard tables + CopyKeys host map); ``dedup_keys`` replaces
``np.unique`` for pass-key registration. Both fall back to numpy when the
native library is unavailable, preserving exact semantics
(``table.map_keys_to_rows``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.embedding.table import map_keys_to_rows
from paddlebox_tpu.native.build import load_library


def dedup_keys(keys: np.ndarray) -> np.ndarray:
    """Sorted unique nonzero keys (np.unique + drop-0 equivalent).

    The native path wins by parallelism (hash-shard dedup across cores);
    on boxes with few cores numpy's single-threaded sort is faster, so
    fall back there.
    """
    keys = np.ascontiguousarray(keys, np.uint64)
    lib = load_library()
    if lib is None or keys.size == 0 or (os.cpu_count() or 1) < 4:
        u = np.unique(keys)
        return u[u != 0]
    h = lib.pbx_dedup_u64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), keys.size)
    try:
        n = lib.pbx_dedup_size(h)
        out = np.empty((n,), np.uint64)
        if n:
            lib.pbx_dedup_fill(
                h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        monitor.add("native/dedup_keys", int(keys.size))
        return out
    finally:
        lib.pbx_dedup_free(h)


class KeyMap:
    """Hash map from the pass's sorted unique keys to their rank, serving
    batch key→device-row lookups (round-robin sharded layout — rank g ->
    shard g % S at slot g // S — with round-robin trash sentinels; exact
    ``map_keys_to_rows`` semantics)."""

    def __init__(self, sorted_keys: np.ndarray, rows_per_shard: int,
                 num_shards: int = 1):
        self._keys = np.ascontiguousarray(sorted_keys, np.uint64)
        self.rows_per_shard = int(rows_per_shard)
        self.num_shards = int(num_shards)
        self._lib = load_library()
        self._handle: Optional[int] = None
        if self._lib is not None and self._keys.size:
            self._handle = self._lib.pbx_keymap_build(
                self._keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                self._keys.size)

    def lookup(self, batch_keys: np.ndarray) -> np.ndarray:
        """batch feasigns [m] → device rows [m] int32."""
        batch = np.ascontiguousarray(batch_keys, np.uint64)
        if self._handle is None:
            return map_keys_to_rows(self._keys, batch, self.rows_per_shard,
                                    self.num_shards)
        out = np.empty((batch.size,), np.int32)
        if batch.size:
            self._lib.pbx_keymap_lookup(
                self._handle,
                batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                batch.size, self.rows_per_shard, self.num_shards,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def close(self) -> None:
        if self._handle is not None and self._lib is not None:
            self._lib.pbx_keymap_free(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
