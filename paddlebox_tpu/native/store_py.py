"""ctypes wrappers for the native store engine (native/store.cc).

Two surfaces:

- :class:`KeyIndex` — incremental key→row hash index (host half of the
  device-resident feature store, embedding/device_store.py). Rows are
  assigned in first-insertion order and never move.
- Module functions ``ss_locate`` / ``gather_rows`` / ``scatter_rows`` /
  ``merge_sorted`` / ``init_uniform`` — threaded primitives for the
  host-RAM store tier (embedding/store.py hot loops; role of the
  reference's multithreaded PreBuildTask/BuildPull walk,
  ps_gpu_wrapper.cc:114,362). Each has an exact numpy fallback when the
  native library is unavailable.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from paddlebox_tpu.native.build import load_library

_u64p = ctypes.POINTER(ctypes.c_uint64)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_f32p = ctypes.POINTER(ctypes.c_float)


def _p(a: np.ndarray, t):
    return a.ctypes.data_as(t)


def is_sorted_unique_nonzero(keys: np.ndarray) -> bool:
    """True when ``keys`` is strictly ascending with no 0 (the shape
    dedup_keys produces) — the precondition for the bulk-build bypasses.
    One vectorized O(n) pass, cheap next to any build it guards."""
    k = keys
    if k.size == 0:
        return True
    return bool(k[0] != 0) and (k.size == 1 or bool(np.all(k[1:] > k[:-1])))


def merge_unique(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two SORTED UNIQUE key arrays, O(n + m) — ss_locate drops
    b's duplicates, merge_sorted interleaves the disjoint remainder (both
    threaded native with exact numpy fallbacks)."""
    a = np.ascontiguousarray(a, np.uint64)
    b = np.ascontiguousarray(b, np.uint64)
    if a.size == 0:
        return b.copy() if b.base is not None else b
    if b.size == 0:
        return a
    found, _ = ss_locate(a, b)
    b_new = b[~found] if found.any() else b
    if b_new.size == 0:
        return a
    merged, _ = merge_sorted(a, b_new)
    return merged


class SortedRunMerger:
    """Accumulates sorted unique key runs (one per ingest chunk) and
    k-way merges them on demand — the sorted-run store build (round 13):
    each chunk's dedup overlaps ingest, and the final merge is linear
    instead of one giant end-of-pass sort. ``merge()`` is a balanced
    pairwise tree (O(N log k) with k runs), bit-identical to
    ``np.unique(concat(runs))``."""

    def __init__(self):
        self._runs: list = []

    def add_run(self, sorted_unique: np.ndarray) -> None:
        if sorted_unique.size:
            self._runs.append(np.ascontiguousarray(sorted_unique,
                                                   np.uint64))

    @property
    def num_runs(self) -> int:
        return len(self._runs)

    def merge(self) -> np.ndarray:
        runs = self._runs
        if not runs:
            return np.empty((0,), np.uint64)
        while len(runs) > 1:
            nxt = [merge_unique(runs[i], runs[i + 1])
                   for i in range(0, len(runs) - 1, 2)]
            if len(runs) % 2:
                nxt.append(runs[-1])
            runs = nxt
        self._runs = runs
        return runs[0]

    def clear(self) -> None:
        self._runs = []


class KeyIndex:
    """Incremental key → row index. Not internally synchronized — callers
    serialize mutating calls (the pass lifecycle already does).

    The no-native fallback is VECTORIZED (round 13): a maintained sorted
    key view + row permutation served by threaded searchsorted
    (ss_locate), with new keys batch-appended through merge_sorted — the
    prior per-key python dict walk was ~100x off the native path and set
    BENCH_r02's 406K keys/s store-build wall on no-native hosts."""

    def __init__(self):
        self._lib = load_library()
        self._closed = False
        if self._lib is not None:
            self._h = self._lib.pbx_index_new()
        else:
            self._h = None
            # Fallback state: sorted unique keys + their rows, plus the
            # append-order key log (rows are first-appearance ranks).
            self._fb_sorted = np.empty((0,), np.uint64)
            self._fb_rows = np.empty((0,), np.int64)
            self._fb_by_row = np.empty((0,), np.uint64)
            self._fb_size = 0

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("KeyIndex used after close()")

    @property
    def size(self) -> int:
        self._check_open()
        if self._h is not None:
            return int(self._lib.pbx_index_size(self._h))
        return self._fb_size

    def reserve(self, n: int) -> None:
        """Pre-size for ~n more keys (skips incremental rehash churn; in
        the fallback, pre-grows the append log so batched upserts never
        reallocate it mid-build)."""
        if self._h is not None:
            self._lib.pbx_index_reserve(self._h, int(n))
        else:
            self._fb_grow_log(self._fb_size + int(n))

    def _fb_grow_log(self, want: int) -> None:
        if self._fb_by_row.shape[0] < want:
            grown = np.empty((max(want, 2 * self._fb_by_row.shape[0]),),
                             np.uint64)
            grown[:self._fb_size] = self._fb_by_row[:self._fb_size]
            # graftlint: allow-lock(caller-serialized by class contract)
            self._fb_by_row = grown

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """rows [n] int64; -1 for absent (and for the 0 null feasign)."""
        self._check_open()
        k = np.ascontiguousarray(keys, np.uint64)
        if self._h is not None:
            out = np.empty((k.size,), np.int64)
            if k.size:
                self._lib.pbx_index_lookup(self._h, _p(k, _u64p), k.size,
                                           _p(out, _i64p))
            return out
        out = np.full((k.size,), -1, np.int64)
        if k.size and self._fb_size:
            found, pos = ss_locate(self._fb_sorted, k)
            if found.any():
                out[found] = self._fb_rows[pos[found]]
        return out

    def upsert(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """(rows [n] int64, n_new). New keys get rows size.. in
        first-appearance order; key 0 maps to -1 and is never inserted."""
        self._check_open()
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.empty((k.size,), np.int64)
        if self._h is not None:
            n_new = int(self._lib.pbx_index_upsert(self._h, _p(k, _u64p),
                                                   k.size, _p(out, _i64p)))
            return out, n_new
        if k.size == 0:
            return out, 0
        found, pos = ss_locate(self._fb_sorted, k)
        out[found] = self._fb_rows[pos[found]] if found.any() else 0
        zero = k == 0
        out[zero] = -1
        new_m = ~(found | zero)
        if not new_m.any():
            return out, 0
        nk = k[new_m]
        uniq, first, inv = np.unique(nk, return_index=True,
                                     return_inverse=True)
        # Rows follow FIRST-APPEARANCE order within the batch (the
        # native contract), not sorted order.
        order = np.argsort(first, kind="stable")
        rank = np.empty((order.size,), np.int64)
        rank[order] = np.arange(order.size)
        rows_of_uniq = self._fb_size + rank      # aligned to sorted uniq
        out[new_m] = rows_of_uniq[inv]
        n_old = self._fb_sorted.shape[0]
        merged, src = merge_sorted(self._fb_sorted, uniq)
        rows_merged = np.empty((merged.shape[0],), np.int64)
        is_new = src >= n_old
        rows_merged[~is_new] = self._fb_rows[src[~is_new]]
        rows_merged[is_new] = rows_of_uniq[src[is_new] - n_old]
        # graftlint: allow-lock(caller-serialized by class contract)
        self._fb_sorted, self._fb_rows = merged, rows_merged
        self._fb_grow_log(self._fb_size + order.size)
        self._fb_by_row[self._fb_size:self._fb_size + order.size] = \
            uniq[order]
        # graftlint: allow-lock(class contract: callers serialize)
        self._fb_size += int(order.size)
        return out, int(order.size)

    def bulk_build(self, sorted_keys: np.ndarray) -> np.ndarray:
        """Fresh-build bypass: populate an EMPTY index from sorted unique
        nonzero keys with rows 0..n-1 — bit-identical to ``upsert`` of
        the same array, but placement parallelizes (native: CAS-claimed
        slots across cores; fallback: the sorted view IS the input, no
        merge at all). Returns the rows (arange). Raises on a non-empty
        index or unsorted input — the caller chose the wrong API."""
        self._check_open()
        if self.size != 0:
            raise ValueError("bulk_build on a non-empty KeyIndex")
        k = np.ascontiguousarray(sorted_keys, np.uint64)
        if not is_sorted_unique_nonzero(k):
            raise ValueError(
                "bulk_build wants sorted unique nonzero keys "
                "(dedup_keys output) — use upsert for raw batches")
        if self._h is not None:
            got = int(self._lib.pbx_index_bulk_build(self._h, _p(k, _u64p),
                                                     k.size))
            if got != k.size:  # pragma: no cover - guarded above
                raise ValueError("native bulk_build rejected the input")
        else:
            n = k.shape[0]
            self._fb_sorted = k.copy()
            self._fb_rows = np.arange(n, dtype=np.int64)
            self._fb_by_row = k.copy()
            self._fb_size = n
        return np.arange(k.shape[0], dtype=np.int64)

    def keys_by_row(self) -> np.ndarray:
        """All keys, index = row (append order)."""
        self._check_open()
        n = self.size
        out = np.empty((n,), np.uint64)
        if self._h is not None:
            if n:
                self._lib.pbx_index_keys_fill(self._h, _p(out, _u64p))
            return out
        out[:] = self._fb_by_row[:n]
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._h is not None:
            self._lib.pbx_index_free(self._h)
            self._h = None
        self._fb_sorted = self._fb_rows = self._fb_by_row = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def bench_index_build(n_keys: int, *, chunk: int = 10_000_000,
                      seed: int = 7, tick=None,
                      mode: str = "upsert") -> float:
    """ONE definition of the 'host pass-build' metric (SURVEY hard part
    #1 — PreBuildTask role, ps_gpu_wrapper.cc:114): fresh build of
    n_keys uniform-random keys into a pre-sized KeyIndex, chunked like a
    production bulk build. Returns keys/s. Shared by bench.py
    (host_index_build_keys_per_s), tools/bench_native_store.py and the
    round-13 sorted-run acceptance so recorded numbers can never drift
    in methodology. ``tick`` is an optional per-chunk progress callback
    (the bench watchdog).

    Modes (same keys in, same index out — rows differ only in the order
    contract each mode documents):

    - ``upsert``: the incremental find-or-insert walk (r02 methodology).
    - ``bulk``: the sorted-run build — per-chunk dedup_keys → sorted
      runs → k-way merge_unique → KeyIndex.bulk_build.
    - ``dict``: the pre-round-13 per-key python dict loop, kept as the
      measurable fallback baseline the 10x acceptance compares against.
    """
    import time as _time
    rng = np.random.default_rng(seed)
    keys = rng.integers(1, 1 << 62, n_keys, dtype=np.uint64)
    t0 = _time.perf_counter()
    if mode == "bulk":
        from paddlebox_tpu.native.keymap_py import dedup_keys
        merger = SortedRunMerger()
        for lo in range(0, n_keys, chunk):
            merger.add_run(dedup_keys(keys[lo:lo + chunk]))
            if tick is not None:
                tick(lo)
        idx = KeyIndex()
        idx.bulk_build(merger.merge())
    elif mode == "dict":
        fb: dict = {}
        out = np.empty((min(chunk, n_keys),), np.int64)
        for lo in range(0, n_keys, chunk):
            for i, kk in enumerate(keys[lo:lo + chunk].tolist()):
                if not kk:
                    out[i] = -1
                    continue
                r = fb.get(kk)
                if r is None:
                    r = len(fb)
                    fb[kk] = r
                out[i] = r
            if tick is not None:
                tick(lo)
        dt = _time.perf_counter() - t0
        return n_keys / dt
    else:
        if mode != "upsert":
            raise ValueError(f"unknown bench_index_build mode {mode!r}")
        idx = KeyIndex()
        idx.reserve(n_keys)
        for lo in range(0, n_keys, chunk):
            idx.upsert(keys[lo:lo + chunk])
            if tick is not None:
                tick(lo)
    dt = _time.perf_counter() - t0
    idx.close()
    return n_keys / dt


def ss_locate(sorted_keys: np.ndarray, queries: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    """(found mask [m] bool, clipped positions [m] int64) of queries in the
    sorted array — threaded searchsorted (store.py _locate contract)."""
    s = np.ascontiguousarray(sorted_keys, np.uint64)
    q = np.ascontiguousarray(queries, np.uint64)
    m, n = q.size, s.size
    lib = load_library()
    if lib is None or n == 0 or m == 0:
        if n == 0:
            return np.zeros(m, bool), np.zeros(m, np.int64)
        pos = np.searchsorted(s, q)
        pos_c = np.minimum(pos, n - 1)
        return s[pos_c] == q, pos_c
    pos = np.empty((m,), np.int64)
    found = np.empty((m,), np.uint8)
    lib.pbx_ss_locate(_p(s, _u64p), n, _p(q, _u64p), m, _p(pos, _i64p),
                      _p(found, _u8p))
    return found.astype(bool), pos


def _rows2d(a: np.ndarray) -> Tuple[np.ndarray, int]:
    """View any row-shaped array as [n, width] contiguous float32."""
    v = np.ascontiguousarray(a, np.float32)
    width = int(np.prod(v.shape[1:], dtype=np.int64)) if v.ndim > 1 else 1
    return v.reshape(v.shape[0] if v.size else 0, max(width, 1)), width


def gather_rows(src: np.ndarray, idx: np.ndarray,
                mask: Optional[np.ndarray] = None,
                out: Optional[np.ndarray] = None) -> np.ndarray:
    """out[i] = src[idx[i]] (float32 rows), threaded; with ``mask`` only
    masked rows are written (others left as-is in a provided ``out``, or
    zero in a fresh one)."""
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int64)
    src2, width = _rows2d(src)
    if out is None:
        alloc = np.zeros if mask is not None else np.empty
        out = alloc((idx.size,) + src.shape[1:], np.float32)
    elif out.dtype != np.float32:
        raise ValueError("gather_rows: out must be float32")
    if lib is None or idx.size == 0:
        if idx.size:
            if mask is None:
                out[...] = src[idx]
            else:
                out[mask] = src[idx[mask]]
        return out
    out2 = out.reshape(idx.size, max(width, 1))
    if not out2.flags.c_contiguous:
        raise ValueError("gather_rows: out must be C-contiguous")
    if mask is None:
        lib.pbx_gather_rows(_p(src2, _f32p), _p(idx, _i64p), idx.size,
                            width, _p(out2, _f32p))
    else:
        mk = np.ascontiguousarray(mask, np.uint8)
        lib.pbx_gather_rows_masked(_p(src2, _f32p), _p(idx, _i64p),
                                   _p(mk, _u8p), idx.size, width,
                                   _p(out2, _f32p))
    return out


def scatter_rows(dst: np.ndarray, idx: np.ndarray, src: np.ndarray,
                 mask: Optional[np.ndarray] = None) -> None:
    """dst[idx[i]] = src[i] (float32 rows), threaded; idx duplicate-free
    (duplicates would race). ``mask`` limits to masked rows."""
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int64)
    if idx.size == 0:
        return
    if (lib is None or not dst.flags.c_contiguous
            or dst.dtype != np.float32):
        if mask is None:
            dst[idx] = src
        else:
            dst[idx[mask]] = src[mask]
        return
    dst2, width = _rows2d(dst)
    src2 = np.ascontiguousarray(src, np.float32).reshape(
        idx.size, max(width, 1))
    if mask is None:
        lib.pbx_scatter_rows(_p(dst2, _f32p), _p(idx, _i64p), idx.size,
                             width, _p(src2, _f32p))
    else:
        mk = np.ascontiguousarray(mask, np.uint8)
        lib.pbx_scatter_rows_masked(_p(dst2, _f32p), _p(idx, _i64p),
                                    _p(mk, _u8p), idx.size, width,
                                    _p(src2, _f32p))


def merge_sorted(old_keys: np.ndarray, add_keys: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Merge two sorted disjoint key arrays: returns (merged_keys [n+m],
    src [n+m] int64) with src[i] < n meaning old row src[i], else add row
    src[i]-n — one gather then materializes any merged value column."""
    o = np.ascontiguousarray(old_keys, np.uint64)
    a = np.ascontiguousarray(add_keys, np.uint64)
    n, m = o.size, a.size
    lib = load_library()
    if lib is None:
        ins = np.searchsorted(o, a)
        dst_new = ins + np.arange(m)
        merged = np.empty(n + m, np.uint64)
        src = np.empty(n + m, np.int64)
        is_new = np.zeros(n + m, bool)
        is_new[dst_new] = True
        merged[dst_new] = a
        src[dst_new] = n + np.arange(m)
        old_pos = np.flatnonzero(~is_new)
        merged[old_pos] = o
        src[old_pos] = np.arange(n)
        return merged, src
    merged = np.empty((n + m,), np.uint64)
    src = np.empty((n + m,), np.int64)
    lib.pbx_merge_sorted(_p(o, _u64p), n, _p(a, _u64p), m,
                         _p(merged, _u64p), _p(src, _i64p))
    return merged, src


def init_uniform(keys: np.ndarray, dim: int, seed: int,
                 scale: float) -> np.ndarray:
    """[n, dim] deterministic per-key uniform(-scale, scale) init —
    bit-exact twin of store.py _per_key_uniform."""
    k = np.ascontiguousarray(keys, np.uint64)
    lib = load_library()
    if lib is None or k.size == 0:
        from paddlebox_tpu.embedding.store import _per_key_uniform
        return _per_key_uniform(k, dim, np.uint64(seed), scale)
    out = np.empty((k.size, dim), np.float32)
    lib.pbx_init_uniform(_p(k, _u64p), k.size, dim, seed, scale,
                         _p(out, _f32p))
    return out
