"""ctypes wrappers for the native store engine (native/store.cc).

Two surfaces:

- :class:`KeyIndex` — incremental key→row hash index (host half of the
  device-resident feature store, embedding/device_store.py). Rows are
  assigned in first-insertion order and never move.
- Module functions ``ss_locate`` / ``gather_rows`` / ``scatter_rows`` /
  ``merge_sorted`` / ``init_uniform`` — threaded primitives for the
  host-RAM store tier (embedding/store.py hot loops; role of the
  reference's multithreaded PreBuildTask/BuildPull walk,
  ps_gpu_wrapper.cc:114,362). Each has an exact numpy fallback when the
  native library is unavailable.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from paddlebox_tpu.native.build import load_library

_u64p = ctypes.POINTER(ctypes.c_uint64)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_f32p = ctypes.POINTER(ctypes.c_float)


def _p(a: np.ndarray, t):
    return a.ctypes.data_as(t)


class KeyIndex:
    """Incremental key → row index. Not internally synchronized — callers
    serialize mutating calls (the pass lifecycle already does)."""

    def __init__(self):
        self._lib = load_library()
        self._closed = False
        if self._lib is not None:
            self._h = self._lib.pbx_index_new()
            self._fallback = None
        else:
            self._h = None
            self._fallback = {}

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("KeyIndex used after close()")

    @property
    def size(self) -> int:
        self._check_open()
        if self._h is not None:
            return int(self._lib.pbx_index_size(self._h))
        return len(self._fallback)

    def reserve(self, n: int) -> None:
        """Pre-size for ~n more keys (skips incremental rehash churn)."""
        if self._h is not None:
            self._lib.pbx_index_reserve(self._h, int(n))

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """rows [n] int64; -1 for absent (and for the 0 null feasign)."""
        self._check_open()
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.empty((k.size,), np.int64)
        if self._h is not None:
            if k.size:
                self._lib.pbx_index_lookup(self._h, _p(k, _u64p), k.size,
                                           _p(out, _i64p))
            return out
        fb = self._fallback
        for i, kk in enumerate(k.tolist()):
            out[i] = fb.get(kk, -1) if kk else -1
        return out

    def upsert(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """(rows [n] int64, n_new). New keys get rows size.. in
        first-appearance order; key 0 maps to -1 and is never inserted."""
        self._check_open()
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.empty((k.size,), np.int64)
        if self._h is not None:
            n_new = int(self._lib.pbx_index_upsert(self._h, _p(k, _u64p),
                                                   k.size, _p(out, _i64p)))
            return out, n_new
        fb = self._fallback
        n_new = 0
        for i, kk in enumerate(k.tolist()):
            if not kk:
                out[i] = -1
                continue
            r = fb.get(kk)
            if r is None:
                r = len(fb)
                fb[kk] = r
                n_new += 1
            out[i] = r
        return out, n_new

    def keys_by_row(self) -> np.ndarray:
        """All keys, index = row (append order)."""
        self._check_open()
        n = self.size
        out = np.empty((n,), np.uint64)
        if self._h is not None:
            if n:
                self._lib.pbx_index_keys_fill(self._h, _p(out, _u64p))
            return out
        for kk, r in self._fallback.items():
            out[r] = kk
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._h is not None:
            self._lib.pbx_index_free(self._h)
            self._h = None
        self._fallback = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def bench_index_build(n_keys: int, *, chunk: int = 10_000_000,
                      seed: int = 7, tick=None) -> float:
    """ONE definition of the 'host pass-build' metric (SURVEY hard part
    #1 — PreBuildTask role, ps_gpu_wrapper.cc:114): fresh upsert of
    n_keys uniform-random keys into a pre-sized KeyIndex, chunked like a
    production bulk build. Returns keys/s. Shared by bench.py
    (host_index_build_keys_per_s) and tools/bench_native_store.py so the
    two recorded numbers can never drift in methodology. ``tick`` is an
    optional per-chunk progress callback (the bench watchdog)."""
    import time as _time
    rng = np.random.default_rng(seed)
    keys = rng.integers(1, 1 << 62, n_keys, dtype=np.uint64)
    idx = KeyIndex()
    idx.reserve(n_keys)
    t0 = _time.perf_counter()
    for lo in range(0, n_keys, chunk):
        idx.upsert(keys[lo:lo + chunk])
        if tick is not None:
            tick(lo)
    dt = _time.perf_counter() - t0
    idx.close()
    return n_keys / dt


def ss_locate(sorted_keys: np.ndarray, queries: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    """(found mask [m] bool, clipped positions [m] int64) of queries in the
    sorted array — threaded searchsorted (store.py _locate contract)."""
    s = np.ascontiguousarray(sorted_keys, np.uint64)
    q = np.ascontiguousarray(queries, np.uint64)
    m, n = q.size, s.size
    lib = load_library()
    if lib is None or n == 0 or m == 0:
        if n == 0:
            return np.zeros(m, bool), np.zeros(m, np.int64)
        pos = np.searchsorted(s, q)
        pos_c = np.minimum(pos, n - 1)
        return s[pos_c] == q, pos_c
    pos = np.empty((m,), np.int64)
    found = np.empty((m,), np.uint8)
    lib.pbx_ss_locate(_p(s, _u64p), n, _p(q, _u64p), m, _p(pos, _i64p),
                      _p(found, _u8p))
    return found.astype(bool), pos


def _rows2d(a: np.ndarray) -> Tuple[np.ndarray, int]:
    """View any row-shaped array as [n, width] contiguous float32."""
    v = np.ascontiguousarray(a, np.float32)
    width = int(np.prod(v.shape[1:], dtype=np.int64)) if v.ndim > 1 else 1
    return v.reshape(v.shape[0] if v.size else 0, max(width, 1)), width


def gather_rows(src: np.ndarray, idx: np.ndarray,
                mask: Optional[np.ndarray] = None,
                out: Optional[np.ndarray] = None) -> np.ndarray:
    """out[i] = src[idx[i]] (float32 rows), threaded; with ``mask`` only
    masked rows are written (others left as-is in a provided ``out``, or
    zero in a fresh one)."""
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int64)
    src2, width = _rows2d(src)
    if out is None:
        alloc = np.zeros if mask is not None else np.empty
        out = alloc((idx.size,) + src.shape[1:], np.float32)
    elif out.dtype != np.float32:
        raise ValueError("gather_rows: out must be float32")
    if lib is None or idx.size == 0:
        if idx.size:
            if mask is None:
                out[...] = src[idx]
            else:
                out[mask] = src[idx[mask]]
        return out
    out2 = out.reshape(idx.size, max(width, 1))
    if not out2.flags.c_contiguous:
        raise ValueError("gather_rows: out must be C-contiguous")
    if mask is None:
        lib.pbx_gather_rows(_p(src2, _f32p), _p(idx, _i64p), idx.size,
                            width, _p(out2, _f32p))
    else:
        mk = np.ascontiguousarray(mask, np.uint8)
        lib.pbx_gather_rows_masked(_p(src2, _f32p), _p(idx, _i64p),
                                   _p(mk, _u8p), idx.size, width,
                                   _p(out2, _f32p))
    return out


def scatter_rows(dst: np.ndarray, idx: np.ndarray, src: np.ndarray,
                 mask: Optional[np.ndarray] = None) -> None:
    """dst[idx[i]] = src[i] (float32 rows), threaded; idx duplicate-free
    (duplicates would race). ``mask`` limits to masked rows."""
    lib = load_library()
    idx = np.ascontiguousarray(idx, np.int64)
    if idx.size == 0:
        return
    if (lib is None or not dst.flags.c_contiguous
            or dst.dtype != np.float32):
        if mask is None:
            dst[idx] = src
        else:
            dst[idx[mask]] = src[mask]
        return
    dst2, width = _rows2d(dst)
    src2 = np.ascontiguousarray(src, np.float32).reshape(
        idx.size, max(width, 1))
    if mask is None:
        lib.pbx_scatter_rows(_p(dst2, _f32p), _p(idx, _i64p), idx.size,
                             width, _p(src2, _f32p))
    else:
        mk = np.ascontiguousarray(mask, np.uint8)
        lib.pbx_scatter_rows_masked(_p(dst2, _f32p), _p(idx, _i64p),
                                    _p(mk, _u8p), idx.size, width,
                                    _p(src2, _f32p))


def merge_sorted(old_keys: np.ndarray, add_keys: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Merge two sorted disjoint key arrays: returns (merged_keys [n+m],
    src [n+m] int64) with src[i] < n meaning old row src[i], else add row
    src[i]-n — one gather then materializes any merged value column."""
    o = np.ascontiguousarray(old_keys, np.uint64)
    a = np.ascontiguousarray(add_keys, np.uint64)
    n, m = o.size, a.size
    lib = load_library()
    if lib is None:
        ins = np.searchsorted(o, a)
        dst_new = ins + np.arange(m)
        merged = np.empty(n + m, np.uint64)
        src = np.empty(n + m, np.int64)
        is_new = np.zeros(n + m, bool)
        is_new[dst_new] = True
        merged[dst_new] = a
        src[dst_new] = n + np.arange(m)
        old_pos = np.flatnonzero(~is_new)
        merged[old_pos] = o
        src[old_pos] = np.arange(n)
        return merged, src
    merged = np.empty((n + m,), np.uint64)
    src = np.empty((n + m,), np.int64)
    lib.pbx_merge_sorted(_p(o, _u64p), n, _p(a, _u64p), m,
                         _p(merged, _u64p), _p(src, _i64p))
    return merged, src


def init_uniform(keys: np.ndarray, dim: int, seed: int,
                 scale: float) -> np.ndarray:
    """[n, dim] deterministic per-key uniform(-scale, scale) init —
    bit-exact twin of store.py _per_key_uniform."""
    k = np.ascontiguousarray(keys, np.uint64)
    lib = load_library()
    if lib is None or k.size == 0:
        from paddlebox_tpu.embedding.store import _per_key_uniform
        return _per_key_uniform(k, dim, np.uint64(seed), scale)
    out = np.empty((k.size, dim), np.float32)
    lib.pbx_init_uniform(_p(k, _u64p), k.size, dim, seed, scale,
                         _p(out, _f32p))
    return out
