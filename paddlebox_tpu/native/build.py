"""On-demand g++ build of the native library, with content-hash caching."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

from paddlebox_tpu.core import log

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["parser.cc", "keymap.cc", "store.cc", "graph.cc"]
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _cache_dir() -> str:
    d = os.environ.get("PBX_NATIVE_CACHE",
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddlebox_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> Optional[str]:
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    so_path = os.path.join(_cache_dir(), f"libpbx_native_{h.hexdigest()[:16]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", so_path + ".tmp"] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired) as e:
        err = getattr(e, "stderr", b"")
        log.warning("native build failed (%s); using python fallbacks: %s",
                    e, err.decode() if isinstance(err, bytes) else err)
        return None
    os.replace(so_path + ".tmp", so_path)
    log.vlog(1, "built native library -> %s", so_path)
    return so_path


def load_library() -> Optional[ctypes.CDLL]:
    """Build (cached) + dlopen the native library; None if unavailable."""
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        path = _build()
        if path is None:
            _failed = True
            return None
        lib = ctypes.CDLL(path)
        # Signatures.
        lib.pbx_parse_svm.restype = ctypes.c_void_p
        lib.pbx_parse_svm.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32]
        for fn in ("pbx_result_rows", "pbx_result_malformed",
                   "pbx_result_dropped"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.pbx_result_sparse_size.restype = ctypes.c_int64
        lib.pbx_result_sparse_size.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int32]
        lib.pbx_result_fill.restype = None
        lib.pbx_result_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        lib.pbx_result_free.restype = None
        lib.pbx_result_free.argtypes = [ctypes.c_void_p]
        # keymap.cc
        lib.pbx_keymap_build.restype = ctypes.c_void_p
        lib.pbx_keymap_build.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.pbx_keymap_size.restype = ctypes.c_int64
        lib.pbx_keymap_size.argtypes = [ctypes.c_void_p]
        lib.pbx_keymap_lookup.restype = None
        lib.pbx_keymap_lookup.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32)]
        lib.pbx_keymap_free.restype = None
        lib.pbx_keymap_free.argtypes = [ctypes.c_void_p]
        lib.pbx_dedup_u64.restype = ctypes.c_void_p
        lib.pbx_dedup_u64.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
        lib.pbx_dedup_size.restype = ctypes.c_int64
        lib.pbx_dedup_size.argtypes = [ctypes.c_void_p]
        lib.pbx_dedup_fill.restype = None
        lib.pbx_dedup_fill.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        lib.pbx_dedup_free.restype = None
        lib.pbx_dedup_free.argtypes = [ctypes.c_void_p]
        # store.cc — incremental index
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.pbx_index_new.restype = ctypes.c_void_p
        lib.pbx_index_new.argtypes = []
        lib.pbx_index_size.restype = ctypes.c_int64
        lib.pbx_index_size.argtypes = [ctypes.c_void_p]
        lib.pbx_index_reserve.restype = None
        lib.pbx_index_reserve.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pbx_index_lookup.restype = None
        lib.pbx_index_lookup.argtypes = [ctypes.c_void_p, u64p,
                                         ctypes.c_int64, i64p]
        lib.pbx_index_upsert.restype = ctypes.c_int64
        lib.pbx_index_upsert.argtypes = [ctypes.c_void_p, u64p,
                                         ctypes.c_int64, i64p]
        lib.pbx_index_keys_fill.restype = None
        lib.pbx_index_keys_fill.argtypes = [ctypes.c_void_p, u64p]
        lib.pbx_index_bulk_build.restype = ctypes.c_int64
        lib.pbx_index_bulk_build.argtypes = [ctypes.c_void_p, u64p,
                                             ctypes.c_int64]
        lib.pbx_index_free.restype = None
        lib.pbx_index_free.argtypes = [ctypes.c_void_p]
        # store.cc — sorted-store primitives
        lib.pbx_ss_locate.restype = None
        lib.pbx_ss_locate.argtypes = [u64p, ctypes.c_int64, u64p,
                                      ctypes.c_int64, i64p, u8p]
        lib.pbx_gather_rows.restype = None
        lib.pbx_gather_rows.argtypes = [f32p, i64p, ctypes.c_int64,
                                        ctypes.c_int64, f32p]
        lib.pbx_scatter_rows.restype = None
        lib.pbx_scatter_rows.argtypes = [f32p, i64p, ctypes.c_int64,
                                         ctypes.c_int64, f32p]
        lib.pbx_gather_rows_masked.restype = None
        lib.pbx_gather_rows_masked.argtypes = [f32p, i64p, u8p,
                                               ctypes.c_int64,
                                               ctypes.c_int64, f32p]
        lib.pbx_scatter_rows_masked.restype = None
        lib.pbx_scatter_rows_masked.argtypes = [f32p, i64p, u8p,
                                                ctypes.c_int64,
                                                ctypes.c_int64, f32p]
        lib.pbx_merge_sorted.restype = None
        lib.pbx_merge_sorted.argtypes = [u64p, ctypes.c_int64, u64p,
                                         ctypes.c_int64, u64p, i64p]
        lib.pbx_init_uniform.restype = None
        lib.pbx_init_uniform.argtypes = [u64p, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_uint64,
                                         ctypes.c_double, f32p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_library() is not None
