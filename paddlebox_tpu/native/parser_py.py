"""ctypes wrapper: native svm parse → ColumnarChunk."""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.columnar import ColumnarChunk
from paddlebox_tpu.data.slots import DataFeedConfig
from paddlebox_tpu.native.build import load_library


def parse_chunk_native(text: bytes, config: DataFeedConfig
                       ) -> Optional[ColumnarChunk]:
    """Parse a text buffer with the C++ parser; None if lib unavailable."""
    lib = load_library()
    if lib is None:
        return None
    slots = list(config.slots)
    used = [s for s in slots if s.is_used]
    names = (ctypes.c_char_p * len(used))(
        *[s.name.encode() for s in used])
    is_dense = (ctypes.c_uint8 * len(used))(
        *[1 if s.is_dense else 0 for s in used])
    dims = (ctypes.c_int32 * len(used))(
        *[s.dim if s.is_dense else 0 for s in used])

    handle = lib.pbx_parse_svm(text, len(text), names, is_dense, dims,
                               len(used), config.num_labels)
    try:
        n = lib.pbx_result_rows(handle)
        malformed = lib.pbx_result_malformed(handle)
        dropped = lib.pbx_result_dropped(handle)
        if malformed:
            monitor.add("parser/malformed_lines", int(malformed))
        if dropped:
            monitor.add("parser/null_or_oob_feasign", int(dropped))

        sparse_slots = [s for s in used if not s.is_dense]
        dense_slots = [s for s in used if s.is_dense]
        labels = np.empty((n, config.num_labels), np.float32)
        ids = {}
        offs = {}
        id_ptrs = (ctypes.POINTER(ctypes.c_uint64) * max(len(sparse_slots), 1))()
        off_ptrs = (ctypes.POINTER(ctypes.c_int64) * max(len(sparse_slots), 1))()
        for i, s in enumerate(sparse_slots):
            sz = lib.pbx_result_sparse_size(handle, i)
            ids[s.name] = np.empty((sz,), np.uint64)
            offs[s.name] = np.empty((n + 1,), np.int64)
            id_ptrs[i] = ids[s.name].ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64))
            off_ptrs[i] = offs[s.name].ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64))
        dense = {}
        dense_ptrs = (ctypes.POINTER(ctypes.c_float) * max(len(dense_slots), 1))()
        for i, s in enumerate(dense_slots):
            dense[s.name] = np.zeros((n, s.dim), np.float32)
            dense_ptrs[i] = dense[s.name].ctypes.data_as(
                ctypes.POINTER(ctypes.c_float))

        lib.pbx_result_fill(
            handle, labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            id_ptrs, off_ptrs, dense_ptrs)
        return ColumnarChunk(labels=labels, sparse_ids=ids,
                             sparse_offsets=offs, dense=dense)
    finally:
        lib.pbx_result_free(handle)
