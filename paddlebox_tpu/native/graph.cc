// Parallel CSR build: stable counting sort of an edge list by source.
//
// Role of the reference's graph load/build path (GraphGpuWrapper::
// load_edge_file + GpuPsGraphTable upload_batch building per-partition
// neighbor arrays): the host-side step that turns a raw (src, dst[, w])
// edge list into the compact adjacency the samplers consume. The numpy
// path (graph/table.py build_csr) pays an O(E log E) argsort; src values
// live in [0, num_nodes), so a two-pass counting sort is O(E) and
// parallelizes per thread with exact stability — the output layout is
// BIT-IDENTICAL to numpy's stable argsort (chunk-major scatter with
// per-thread cursors preserves original edge order within each source).
//
// C ABI (ctypes, no pybind): pbx_csr_build fills caller-allocated
// indptr[num_nodes+1], cols[n], and (optionally) w_out[n].

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int graph_threads_for(int64_t n, int64_t num_nodes) {
  unsigned hw = std::thread::hardware_concurrency();
  int t = hw ? static_cast<int>(hw) : 1;
  // Small inputs: thread spawn + per-thread count arrays cost more than
  // they save.
  if (n < (1 << 16)) return 1;
  // The count scratch is nt * num_nodes * 8 bytes: cap threads so a
  // sparse id space (few edges over a huge node range) cannot balloon
  // the transient past ~the numpy path's single bincount array.
  const int64_t by_mem = std::max<int64_t>(1, n / std::max<int64_t>(
                                                   num_nodes, 1));
  return static_cast<int>(std::min<int64_t>(std::min<int>(t, 16), by_mem));
}

}  // namespace

extern "C" {

void pbx_csr_build(const int64_t* src, const int64_t* dst, const float* w,
                   int64_t n, int64_t num_nodes, int64_t* indptr,
                   int64_t* cols, float* w_out) {
  const int nt = graph_threads_for(n, num_nodes);
  // Per-thread counts over the node space. [nt][num_nodes] — for the
  // 10M-edge / 1M-node bench shape at 8 threads this is 64 MB of
  // transient int64, far under the edge arrays it sorts.
  std::vector<std::vector<int64_t>> counts(
      nt, std::vector<int64_t>(static_cast<size_t>(num_nodes), 0));
  const int64_t chunk = (n + nt - 1) / nt;

  {
    std::vector<std::thread> ths;
    ths.reserve(nt);
    for (int t = 0; t < nt; ++t) {
      ths.emplace_back([&, t] {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(n, lo + chunk);
        auto& c = counts[t];
        for (int64_t i = lo; i < hi; ++i) ++c[src[i]];
      });
    }
    for (auto& th : ths) th.join();
  }

  // indptr = exclusive prefix over total counts; per-thread cursors =
  // indptr[v] + counts from earlier (lower-index, i.e. earlier-edge)
  // threads — turning each counts[t][v] into that thread's write base.
  int64_t running = 0;
  for (int64_t v = 0; v < num_nodes; ++v) {
    indptr[v] = running;
    int64_t total = 0;
    for (int t = 0; t < nt; ++t) {
      const int64_t c = counts[t][v];
      counts[t][v] = running + total;  // thread t's first slot for v
      total += c;
    }
    running += total;
  }
  indptr[num_nodes] = running;

  {
    std::vector<std::thread> ths;
    ths.reserve(nt);
    for (int t = 0; t < nt; ++t) {
      ths.emplace_back([&, t] {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(n, lo + chunk);
        auto& cur = counts[t];
        for (int64_t i = lo; i < hi; ++i) {
          const int64_t pos = cur[src[i]]++;
          cols[pos] = dst[i];
          if (w_out) w_out[pos] = w[i];
        }
      });
    }
    for (auto& th : ths) th.join();
  }
}

}  // extern "C"
