// Native host store engine: incremental key->row index + threaded
// sorted-array store primitives.
//
// Two roles from the reference's CPU-side PS machinery:
//  1. The incremental index (pbx_index_*) is the host half of the
//     TPU-resident feature store (embedding/device_store.py): the role of
//     the HeterPS GPU hashtable's key->slot mapping (heter_ps/hashtable.h)
//     moved to the host, where it is cheap, so the device side stays a
//     plain dense array. Rows are assigned in first-insertion order and
//     never move (append-only), so device value rows never need rehashing.
//  2. The sorted-store primitives (pbx_ss_*, pbx_merge_*, pbx_init_*,
//     pbx_gather/scatter_rows) are the hot loops of the host-RAM tier
//     (embedding/store.py): the role of PreBuildTask/BuildPull's
//     multithreaded C++ table walk (ps_gpu_wrapper.cc:114,362) — numpy's
//     single-threaded searchsorted/fancy-index was the r02 bottleneck
//     (406K keys/s store build; VERDICT r02 task 3).
//
// Exposed via a C ABI consumed by ctypes (native/store_py.py). Calls
// release the GIL (ctypes does) and thread internally. The index is NOT
// internally synchronized: callers serialize mutating calls (the pass
// lifecycle already does).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#ifdef __linux__
#include <sys/mman.h>
#endif

namespace {

static inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

static inline int num_threads_for(int64_t n) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  int t = static_cast<int>(std::min<int64_t>(hw, (n + (1 << 16) - 1) >> 16));
  return t < 1 ? 1 : t;
}

template <typename Fn>
static void parallel_chunks(int64_t n, int nt, Fn fn) {
  if (nt <= 1) {
    fn(0, 0, n);
    return;
  }
  std::vector<std::thread> ths;
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    ths.emplace_back([fn, t, lo, hi]() { fn(t, lo, hi); });
  }
  for (auto& th : ths) th.join();
}

// Resizable open-addressing map: key -> row (insertion order). Load
// factor kept <= 0.5 by doubling. Entries interleave (key, row) in one
// 16-byte slot (one cache line touch per probe, not two), and batch
// operations software-prefetch a window of slots ahead — on this class
// of host (single core, ~100ns memory) memory-level parallelism is the
// only lever, worth ~5x on random probes. The slot array lives in an
// anonymous mmap with MADV_HUGEPAGE: at production sizes (50M keys ->
// 2 GiB of slots) random probes on 4 KiB pages page-walk on every
// access, and 2 MiB pages measured 2.66 -> 7.0 M upserts/s on this
// host (with the window at 32); vector/new allocations don't reliably
// get THP-backed.
struct Entry {
  uint64_t key;
  int64_t row;
};

constexpr int kPrefetchWindow = 32;

// out_mmapped records which allocator produced the block — the free
// path must match it exactly (munmap on a new[] fallback pointer would
// be heap corruption; delete[] on an mmap would abort).
static Entry* slots_alloc(size_t cap, bool* out_mmapped) {
#ifdef __linux__
  void* p = mmap(nullptr, cap * sizeof(Entry), PROT_READ | PROT_WRITE,
                 MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (p != MAP_FAILED) {
    madvise(p, cap * sizeof(Entry), MADV_HUGEPAGE);
    *out_mmapped = true;
    return static_cast<Entry*>(p);  // zero-filled: key 0 == empty
  }
#endif
  *out_mmapped = false;
  return new Entry[cap]();
}

static void slots_free(Entry* p, size_t cap, bool mmapped) {
  if (p == nullptr) return;
#ifdef __linux__
  if (mmapped) {
    munmap(p, cap * sizeof(Entry));
    return;
  }
#endif
  (void)cap;
  delete[] p;
}

struct GrowMap {
  Entry* slots = nullptr;
  size_t cap = 0;
  bool slots_mmapped = false;
  std::vector<uint64_t> by_row;  // row -> key (append order)
  uint64_t mask = 0;
  int64_t used = 0;

  GrowMap() { rehash(1 << 16); }
  ~GrowMap() { slots_free(slots, cap, slots_mmapped); }

  void rehash(size_t new_cap) {
    Entry* old = slots;
    size_t old_cap = cap;
    bool old_mmapped = slots_mmapped;
    slots = slots_alloc(new_cap, &slots_mmapped);
    cap = new_cap;
    mask = new_cap - 1;
    if (old != nullptr) {
      for (size_t i = 0; i + kPrefetchWindow < old_cap; ++i) {
        __builtin_prefetch(
            &slots[mix64(old[i + kPrefetchWindow].key) & mask], 1, 1);
        if (old[i].key != 0) place(old[i].key, old[i].row);
      }
      for (size_t i = old_cap > kPrefetchWindow
                          ? old_cap - kPrefetchWindow : 0;
           i < old_cap; ++i) {
        if (old[i].key != 0) place(old[i].key, old[i].row);
      }
      slots_free(old, old_cap, old_mmapped);
    }
  }

  inline void place(uint64_t k, int64_t r) {
    uint64_t i = mix64(k) & mask;
    while (slots[i].key != 0) i = (i + 1) & mask;
    slots[i] = Entry{k, r};
  }

  inline int64_t find(uint64_t k) const {
    uint64_t i = mix64(k) & mask;
    while (true) {
      if (slots[i].key == k) return slots[i].row;
      if (slots[i].key == 0) return -1;
      i = (i + 1) & mask;
    }
  }

  // (Find-or-insert lives ONLY in pbx_index_upsert's inlined batch loop
  // — a per-element member with its own growth check would be a second
  // diverging copy of the probe logic.)

  inline void prefetch(uint64_t k, int write) const {
    __builtin_prefetch(&slots[mix64(k) & mask], write, 1);
  }
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Incremental key -> row index (device-store host half).
// ---------------------------------------------------------------------------

void* pbx_index_new() { return new GrowMap(); }

int64_t pbx_index_size(void* h) {
  return static_cast<int64_t>(static_cast<GrowMap*>(h)->by_row.size());
}

// Pre-size for an expected total key count (avoids rehash churn when the
// caller knows the build size, e.g. a base-model load or bulk prebuild).
void pbx_index_reserve(void* h, int64_t n) {
  GrowMap* m = static_cast<GrowMap*>(h);
  uint64_t want = static_cast<uint64_t>(m->used + n);
  if (want * 2 > m->mask + 1) {
    size_t cap = m->mask + 1;
    while (want * 2 > cap) cap <<= 1;
    m->rehash(cap);
  }
  m->by_row.reserve(want);
}

// Lookup only: out_rows[i] = row of keys[i], or -1 when absent (key 0 is
// always absent — the null feasign). Threaded, read-only.
void pbx_index_lookup(void* h, const uint64_t* keys, int64_t n,
                      int64_t* out_rows) {
  GrowMap* m = static_cast<GrowMap*>(h);
  parallel_chunks(n, num_threads_for(n), [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kPrefetchWindow < hi && keys[i + kPrefetchWindow])
        m->prefetch(keys[i + kPrefetchWindow], 0);
      out_rows[i] = (keys[i] == 0) ? -1 : m->find(keys[i]);
    }
  });
}

// Find-or-insert: new keys get rows size.. in first-appearance order.
// Returns the number of newly inserted keys. Serial over the input (row
// assignment must be deterministic); pre-sizes the table for the worst
// case so a bulk insert never rehashes mid-stream (rehash churn on a
// growing multi-GB table was measured at ~9x the insert cost itself).
int64_t pbx_index_upsert(void* h, const uint64_t* keys, int64_t n,
                         int64_t* out_rows) {
  GrowMap* m = static_cast<GrowMap*>(h);
  uint64_t want = static_cast<uint64_t>(m->used + n);
  if (want * 2 > m->mask + 1) {
    size_t cap = m->mask + 1;
    while (want * 2 > cap) cap <<= 1;
    m->rehash(cap);
  }
  m->by_row.reserve(m->by_row.size() + n);
  int64_t before = static_cast<int64_t>(m->by_row.size());
  // Hot loop: the pre-size above guarantees no rehash can fire inside
  // this batch, so probe inline WITHOUT the per-element growth check —
  // keeping the loop body small enough to stay inlined preserves the
  // prefetch pipeline (measured ~1.8x on the 50M fresh build vs calling
  // the checking member function per element).
  Entry* slots = m->slots;
  const uint64_t mask = m->mask;
  auto& by_row = m->by_row;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPrefetchWindow < n && keys[i + kPrefetchWindow])
      __builtin_prefetch(&slots[mix64(keys[i + kPrefetchWindow]) & mask],
                         1, 1);
    uint64_t k = keys[i];
    if (k == 0) {
      out_rows[i] = -1;
      continue;
    }
    uint64_t j = mix64(k) & mask;
    while (true) {
      if (slots[j].key == k) {
        out_rows[i] = slots[j].row;
        break;
      }
      if (slots[j].key == 0) {
        int64_t r = static_cast<int64_t>(by_row.size());
        slots[j] = Entry{k, r};
        by_row.push_back(k);
        out_rows[i] = r;
        break;
      }
      j = (j + 1) & mask;
    }
  }
  int64_t n_new = static_cast<int64_t>(m->by_row.size()) - before;
  m->used += n_new;
  return n_new;
}

// Dump keys in row order into out[size].
void pbx_index_keys_fill(void* h, uint64_t* out) {
  GrowMap* m = static_cast<GrowMap*>(h);
  if (!m->by_row.empty())
    std::memcpy(out, m->by_row.data(), m->by_row.size() * sizeof(uint64_t));
}

void pbx_index_free(void* h) { delete static_cast<GrowMap*>(h); }

// Fresh-build bypass (sorted-run store build, round 13): populate an
// EMPTY index from n sorted unique nonzero keys with rows 0..n-1 —
// bit-identical to upserting the same array into an empty index, but
// the uniqueness precondition removes the serial find-or-insert
// dependency chain, so placement parallelizes across cores (each
// thread claims slots with a CAS on the key word; rows publish at the
// join). Returns n, or -1 when the index is non-empty / the input is
// not sorted-unique-nonzero (caller falls back to upsert).
int64_t pbx_index_bulk_build(void* h, const uint64_t* keys, int64_t n) {
  GrowMap* m = static_cast<GrowMap*>(h);
  if (m->used != 0) return -1;
  if (n > 0 && keys[0] == 0) return -1;
  for (int64_t i = 1; i < n; ++i)
    if (keys[i] <= keys[i - 1]) return -1;
  uint64_t want = static_cast<uint64_t>(n);
  if (want * 2 > m->mask + 1) {
    size_t cap = m->mask + 1;
    while (want * 2 > cap) cap <<= 1;
    m->rehash(cap);
  }
  m->by_row.assign(keys, keys + n);
  Entry* slots = m->slots;
  const uint64_t mask = m->mask;
  parallel_chunks(n, num_threads_for(n), [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kPrefetchWindow < hi)
        __builtin_prefetch(&slots[mix64(keys[i + kPrefetchWindow]) & mask],
                           1, 1);
      uint64_t k = keys[i];
      uint64_t j = mix64(k) & mask;
      while (true) {
        uint64_t expected = 0;
        if (__atomic_compare_exchange_n(&slots[j].key, &expected, k, false,
                                        __ATOMIC_ACQ_REL,
                                        __ATOMIC_RELAXED)) {
          slots[j].row = static_cast<int64_t>(i);
          break;
        }
        // expected now holds the occupant; unique input means it is
        // never k — probe on.
        j = (j + 1) & mask;
      }
    }
  });
  m->used = n;
  return n;
}

// ---------------------------------------------------------------------------
// Sorted-store primitives (host-RAM tier hot loops).
// ---------------------------------------------------------------------------

// Threaded searchsorted + equality: for each query, pos = lower_bound in
// sorted[n]; found = pos < n && sorted[pos] == q. out_pos clipped to n-1.
void pbx_ss_locate(const uint64_t* sorted, int64_t n, const uint64_t* q,
                   int64_t m, int64_t* out_pos, uint8_t* out_found) {
  parallel_chunks(m, num_threads_for(m), [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint64_t* p = std::lower_bound(sorted, sorted + n, q[i]);
      int64_t pos = p - sorted;
      out_found[i] = (pos < n && *p == q[i]) ? 1 : 0;
      out_pos[i] = std::min<int64_t>(pos, n > 0 ? n - 1 : 0);
    }
  });
}

// Threaded row gather: out[i] = src[idx[i]] (rows of `width` floats).
void pbx_gather_rows(const float* src, const int64_t* idx, int64_t m,
                     int64_t width, float* out) {
  parallel_chunks(m, num_threads_for(m * width / 16),
                  [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kPrefetchWindow < hi)
        __builtin_prefetch(src + idx[i + kPrefetchWindow] * width, 0, 1);
      std::memcpy(out + i * width, src + idx[i] * width,
                  static_cast<size_t>(width) * sizeof(float));
    }
  });
}

// Threaded row scatter: dst[idx[i]] = src[i]. idx must be duplicate-free.
void pbx_scatter_rows(float* dst, const int64_t* idx, int64_t m,
                      int64_t width, const float* src) {
  parallel_chunks(m, num_threads_for(m * width / 16),
                  [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (i + kPrefetchWindow < hi)
        __builtin_prefetch(dst + idx[i + kPrefetchWindow] * width, 1, 1);
      std::memcpy(dst + idx[i] * width, src + i * width,
                  static_cast<size_t>(width) * sizeof(float));
    }
  });
}

// Masked variants: process only rows with mask[i] != 0 (the found subset
// of a locate), avoiding a host-side index compaction pass.
void pbx_gather_rows_masked(const float* src, const int64_t* idx,
                            const uint8_t* mask, int64_t m, int64_t width,
                            float* out) {
  parallel_chunks(m, num_threads_for(m * width / 16),
                  [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (mask[i])
        std::memcpy(out + i * width, src + idx[i] * width,
                    static_cast<size_t>(width) * sizeof(float));
    }
  });
}

void pbx_scatter_rows_masked(float* dst, const int64_t* idx,
                             const uint8_t* mask, int64_t m, int64_t width,
                             const float* src) {
  parallel_chunks(m, num_threads_for(m * width / 16),
                  [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (mask[i])
        std::memcpy(dst + idx[i] * width, src + i * width,
                    static_cast<size_t>(width) * sizeof(float));
    }
  });
}

// Merge positions for two sorted key arrays (old[n], add[m], disjoint):
// out_keys[n+m] = merged ascending; out_src[i] = source row (j < n -> old
// row j; else add row out_src[i] - n). Threaded by output partition: each
// thread owns an equal slice of `add` and the matching old range.
void pbx_merge_sorted(const uint64_t* old_keys, int64_t n,
                      const uint64_t* add_keys, int64_t m,
                      uint64_t* out_keys, int64_t* out_src) {
  if (m == 0) {
    if (n) std::memcpy(out_keys, old_keys, n * sizeof(uint64_t));
    for (int64_t i = 0; i < n; ++i) out_src[i] = i;
    return;
  }
  int nt = num_threads_for(n + m);
  // Partition by add index; old split via binary search on add boundaries
  // (all old keys < the boundary add key belong to earlier threads).
  std::vector<int64_t> add_lo(nt + 1), old_lo(nt + 1);
  for (int t = 0; t <= nt; ++t) {
    add_lo[t] = t * m / nt;
    old_lo[t] = (t == 0) ? 0
                : (t == nt ? n
                   : std::lower_bound(old_keys, old_keys + n,
                                      add_keys[add_lo[t]]) -
                         old_keys);
  }
  parallel_chunks(nt, nt, [&](int, int64_t tlo, int64_t thi) {
    for (int64_t t = tlo; t < thi; ++t) {
      int64_t ia = add_lo[t], ib = old_lo[t];
      int64_t w = ia + ib;
      while (ia < add_lo[t + 1] || ib < old_lo[t + 1]) {
        bool take_old =
            (ia >= add_lo[t + 1]) ||
            (ib < old_lo[t + 1] && old_keys[ib] < add_keys[ia]);
        if (take_old) {
          out_keys[w] = old_keys[ib];
          out_src[w] = ib;
          ++ib;
        } else {
          out_keys[w] = add_keys[ia];
          out_src[w] = n + ia;
          ++ia;
        }
        ++w;
      }
    }
  });
}

// Deterministic per-key uniform init (store.py _per_key_uniform contract):
// out[i, j] = uniform(-scale, scale) from a murmur3-finalizer hash of
// (key's low 32 bits, column j+1, seed) — order-independent; bit-exact
// with the numpy twin AND the on-device jnp twin (32-bit ops only, so the
// device tier can initialize rows from a 4-byte-per-key transfer).
void pbx_init_uniform(const uint64_t* keys, int64_t n, int64_t dim,
                      uint64_t seed, double scale, float* out) {
  uint32_t seed32 = static_cast<uint32_t>(seed & 0xFFFFFFFFULL);
  float fscale = static_cast<float>(scale);
  parallel_chunks(n, num_threads_for(n * dim / 8),
                  [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t k = static_cast<uint32_t>(keys[i] & 0xFFFFFFFFULL);
      for (int64_t j = 1; j <= dim; ++j) {
        uint32_t z = k + static_cast<uint32_t>(j) * 0x9E3779B9u + seed32;
        z ^= z >> 16;
        z *= 0x85EBCA6Bu;
        z ^= z >> 13;
        z *= 0xC2B2AE35u;
        z ^= z >> 16;
        float u = static_cast<float>(z >> 8) * (1.0f / 16777216.0f);
        out[i * dim + (j - 1)] = (2.0f * u - 1.0f) * fscale;
      }
    }
  });
}

}  // extern "C"
