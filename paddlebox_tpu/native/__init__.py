"""Native (C++) host-runtime components.

Role of the reference's C++ data-pipeline hot paths (SURVEY.md §2.4) — the
parts where Python-level loops cannot reach disk/parse throughput. Built
on demand with g++ into a cached shared library; every native component
has a pure-python fallback so the framework degrades gracefully when no
toolchain is present.
"""

from paddlebox_tpu.native.build import load_library, native_available

__all__ = ["load_library", "native_available"]
