"""ctypes wrapper: native parallel CSR build (stable counting sort)."""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from paddlebox_tpu.native.build import load_library

_configured = False


def _lib():
    global _configured
    lib = load_library()
    if lib is None:
        return None
    if not _configured:
        lib.pbx_csr_build.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float)]
        lib.pbx_csr_build.restype = None
        _configured = True
    return lib


def build_csr_native(src: np.ndarray, dst: np.ndarray,
                     weights: Optional[np.ndarray], num_nodes: int
                     ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                         Optional[np.ndarray]]]:
    """(indptr, cols, weights_sorted) in the exact layout of the numpy
    stable-argsort path, or None when the native lib is unavailable.
    Inputs must already be validated/in-range (build_csr does that)."""
    lib = _lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    n = src.shape[0]
    indptr = np.zeros(num_nodes + 1, np.int64)
    cols = np.empty(n, np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    if weights is not None:
        weights = np.ascontiguousarray(weights, np.float32)
        w_out = np.empty(n, np.float32)
        w_in_p = weights.ctypes.data_as(f32p)
        w_out_p = w_out.ctypes.data_as(f32p)
    else:
        w_out = None
        w_in_p = ctypes.cast(None, f32p)
        w_out_p = ctypes.cast(None, f32p)
    lib.pbx_csr_build(src.ctypes.data_as(i64p), dst.ctypes.data_as(i64p),
                      w_in_p, n, int(num_nodes),
                      indptr.ctypes.data_as(i64p),
                      cols.ctypes.data_as(i64p), w_out_p)
    return indptr, cols, w_out
