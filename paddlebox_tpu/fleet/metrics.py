"""fleet.metrics — distributed metric reductions over host stat arrays.

Role of ``python/paddle/distributed/fleet/metrics/metric.py``: each worker
holds local numpy statistics (bucketed AUC histograms, error sums, counts);
``fleet.metrics.auc/mae/rmse/acc/sum/max/min`` allreduce them across
trainers and finish the computation on host (reference reduces via fleet
util allreduce, :144,227,276).

TPU-first: the cross-worker reduction is pluggable — pass ``reduce=`` a
callable (e.g. built from a FileStore / TcpTransport control-plane channel,
or jax multihost utils); the default is single-process identity. Device-
side metric accumulation (inside the jitted step, psum over dp) lives in
:mod:`paddlebox_tpu.metrics`; this module is the *host* aggregation path
used at pass/epoch boundaries.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

Reduce = Callable[[np.ndarray], np.ndarray]


def _ident(x: np.ndarray) -> np.ndarray:
    return x


def make_store_reduce(store, name: str = "metrics") -> Reduce:
    """Build an allreduce-sum over a control-plane store exposing
    ``all_gather(name, bytes) -> List[bytes]`` (FileStore protocol)."""

    def reduce(x: np.ndarray) -> np.ndarray:
        import pickle
        parts = store.all_gather(name, pickle.dumps(np.asarray(x)))
        return np.sum([pickle.loads(p) for p in parts], axis=0)

    return reduce


def sum(value, reduce: Reduce = _ident) -> np.ndarray:  # noqa: A001
    """Global elementwise sum (metric.py:sum_metric role)."""
    return reduce(np.asarray(value, np.float64))


def auc(stat_pos: np.ndarray, stat_neg: np.ndarray,
        reduce: Reduce = _ident) -> float:
    """Exact global AUC from bucketed pos/neg prediction histograms
    (metric.py:144; math mirrors BasicAucCalculator::computeBucketAuc,
    metrics.cc:299-330: sweep buckets accumulating trapezoid area)."""
    pos = reduce(np.asarray(stat_pos, np.float64)).ravel()
    neg = reduce(np.asarray(stat_neg, np.float64)).ravel()
    if pos.shape != neg.shape:
        raise ValueError("stat_pos/stat_neg shape mismatch")
    if pos.size == 0:
        return 0.5
    # high→low sweep == reversed cumulative; vectorized trapezoid.
    tp = np.cumsum(pos[::-1])           # true positives above threshold
    fp = np.cumsum(neg[::-1])
    tot_p, tot_n = tp[-1], fp[-1]
    if tot_p == 0 or tot_n == 0:
        return 0.5
    tp_prev = np.concatenate([[0.0], tp[:-1]])
    fp_prev = np.concatenate([[0.0], fp[:-1]])
    area = np.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    return float(area / (tot_p * tot_n))


def mae(abserr: float, total_ins_num: float, reduce: Reduce = _ident) -> float:
    """Global mean absolute error (metric.py:227)."""
    s = reduce(np.asarray([abserr, total_ins_num], np.float64))
    return float(s[0] / max(s[1], 1.0))


def rmse(sqrerr: float, total_ins_num: float,
         reduce: Reduce = _ident) -> float:
    """Global root mean squared error (metric.py:252)."""
    s = reduce(np.asarray([sqrerr, total_ins_num], np.float64))
    return float(np.sqrt(s[0] / max(s[1], 1.0)))


def mse(sqrerr: float, total_ins_num: float, reduce: Reduce = _ident) -> float:
    s = reduce(np.asarray([sqrerr, total_ins_num], np.float64))
    return float(s[0] / max(s[1], 1.0))


def acc(correct: float, total: float, reduce: Reduce = _ident) -> float:
    """Global accuracy (metric.py:276)."""
    s = reduce(np.asarray([correct, total], np.float64))
    return float(s[0] / max(s[1], 1.0))
