"""DistributedStrategy — the user-facing training-strategy switchboard.

Role of the reference ``fleet.DistributedStrategy``: the protobuf
``distributed_strategy.proto:286-346`` (~40 switches + per-feature config
sub-messages) wrapped by ``fleet/base/distributed_strategy.py``. Users set
``strategy.amp = True``, ``strategy.hybrid_configs = {...}`` etc. and pass
the strategy to ``fleet.init`` / ``fleet.distributed_optimizer``; meta-
optimizers then rewrite the program accordingly.

TPU-first: there is no program rewrite — the strategy resolves into
(a) a :class:`~paddlebox_tpu.parallel.topology.HybridTopology` (mesh axes),
(b) an optax gradient-transformation chain (clip / gradient-merge / lars /
lamb / dgc), and (c) an AMP policy + loss scaler. Validation happens at
``fleet.init`` time instead of at transpile time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from paddlebox_tpu.parallel.topology import HybridTopology


@dataclasses.dataclass
class AmpConfig:
    """Sub-config of ``amp_configs`` (distributed_strategy.proto AMPConfig)."""

    dtype: str = "bfloat16"          # bf16 is the TPU-native fast dtype
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = False  # unnecessary for bf16


@dataclasses.dataclass
class RecomputeConfig:
    """Sub-config of ``recompute_configs``: which layers to rematerialize
    (role of RecomputeOptimizer checkpoint list)."""

    checkpoint_policy: str = "nothing_saveable"  # jax.checkpoint policy name


@dataclasses.dataclass
class GradientMergeConfig:
    """``gradient_merge_configs`` (k_steps accumulation before update)."""

    k_steps: int = 1
    avg: bool = True


@dataclasses.dataclass
class PipelineConfig:
    """``pipeline_configs``: microbatching for 1F1B."""

    accumulate_steps: int = 1
    micro_batch_size: int = 1
    schedule_mode: str = "1F1B"


@dataclasses.dataclass
class ShardingConfig:
    """``sharding_configs``: ZeRO stage + grouping."""

    stage: int = 2                   # 1/2: state+grad shard; 3: params too
    offload: bool = False            # host offload of optimizer state


@dataclasses.dataclass
class DGCConfig:
    """``dgc_configs``: deep gradient compression (top-k sparsification)."""

    rampup_begin_step: int = 0
    sparsity: float = 0.999          # keep top (1-sparsity) of grad entries


@dataclasses.dataclass
class DistributedStrategy:
    """Flat switches + nested configs, mirroring the proto layout.

    ``hybrid_configs`` follows the reference dict form
    (``{"dp_degree": 2, "mp_degree": 2, "pp_degree": 1, ...}``) extended
    with the TPU build's ``sp_degree`` / ``ep_degree`` axes.
    """

    # feature switches (proto bools)
    amp: bool = False
    recompute: bool = False
    pipeline: bool = False
    tensor_parallel: bool = False
    sharding: bool = False
    dgc: bool = False
    lars: bool = False
    lamb: bool = False
    gradient_merge: bool = False
    a_sync: bool = False             # PS async mode (CTR path)
    # nested configs
    amp_configs: AmpConfig = dataclasses.field(default_factory=AmpConfig)
    recompute_configs: RecomputeConfig = dataclasses.field(
        default_factory=RecomputeConfig)
    gradient_merge_configs: GradientMergeConfig = dataclasses.field(
        default_factory=GradientMergeConfig)
    pipeline_configs: PipelineConfig = dataclasses.field(
        default_factory=PipelineConfig)
    sharding_configs: ShardingConfig = dataclasses.field(
        default_factory=ShardingConfig)
    dgc_configs: DGCConfig = dataclasses.field(default_factory=DGCConfig)
    hybrid_configs: Dict[str, int] = dataclasses.field(default_factory=dict)
    # gradient clipping (reference attaches clip to the optimizer; a
    # strategy-level knob keeps the single-switchboard ergonomics)
    clip_norm: Optional[float] = None

    _DEGREES = {"dp_degree": "dp", "sharding_degree": "sharding",
                "pp_degree": "pp", "sp_degree": "sp", "ep_degree": "ep",
                "mp_degree": "mp"}

    @classmethod
    def from_proto_text(cls, text: str) -> "DistributedStrategy":
        """Build a strategy from the reference's DistributedStrategy
        proto-TEXT config (``distributed_strategy.proto:286-346`` — the
        file a migrating user already has). Top-level bool switches and
        the nested ``*_configs`` blocks map by field name onto this
        dataclass and its sub-configs; fields without a seat here are
        warned about (vlog), never silently dropped-and-forgotten.
        ``hybrid_configs`` maps degree-for-degree (dp/mp/pp/sharding,
        plus this build's sp/ep)."""
        from paddlebox_tpu.core import log
        from paddlebox_tpu.data.proto_desc import parse_proto_text

        d = parse_proto_text(text)

        def last(v):
            # parse_proto_text lists repeated fields; proto2 singular
            # semantics: the LAST value wins.
            return v[-1] if isinstance(v, list) else v

        out = cls()
        skipped = []
        for key, value in d.items():
            value = last(value)
            if key == "hybrid_configs" and isinstance(value, dict):
                hc = {k: int(last(v)) for k, v in value.items()
                      if k in cls._DEGREES}
                skipped += [f"hybrid_configs.{k}" for k in value
                            if k not in cls._DEGREES]
                out.hybrid_configs = hc
                continue
            if not hasattr(out, key) or key.startswith("_"):
                skipped.append(key)
                continue
            cur = getattr(out, key)
            if dataclasses.is_dataclass(cur):
                if not isinstance(value, dict):
                    # A scalar where a config block belongs: refusing
                    # beats planting an AttributeError for later.
                    skipped.append(key)
                    continue
                for fk, fv in value.items():
                    fv = last(fv)
                    if hasattr(cur, fk):
                        setattr(cur, fk, type(getattr(cur, fk))(fv)
                                if getattr(cur, fk) is not None else fv)
                    else:
                        skipped.append(f"{key}.{fk}")
            elif isinstance(cur, bool):
                setattr(out, key, bool(value))
            elif isinstance(value, (int, float, str, bool)):
                setattr(out, key, value)
            else:
                skipped.append(key)
        if skipped:
            log.vlog(0, "DistributedStrategy.from_proto_text: no seat "
                     "for %s — review whether they matter for this "
                     "config", sorted(skipped))
        return out

    def topology(self, world_size: Optional[int] = None) -> HybridTopology:
        """Resolve hybrid_configs into a HybridTopology. A dp_degree of -1
        (reference convention: 'fill the rest') absorbs the remaining
        devices when world_size is given."""
        unknown = set(self.hybrid_configs) - set(self._DEGREES)
        if unknown:
            raise ValueError(f"unknown hybrid_configs keys: {sorted(unknown)}")
        deg = {axis: int(self.hybrid_configs.get(key, 1))
               for key, axis in self._DEGREES.items()}
        if deg["dp"] == -1:
            if world_size is None:
                raise ValueError("dp_degree=-1 needs world_size to resolve")
            rest = 1
            for a, v in deg.items():
                if a != "dp":
                    rest *= v
            if world_size % rest:
                raise ValueError(
                    f"world {world_size} not divisible by non-dp degrees {rest}")
            deg["dp"] = world_size // rest
        topo = HybridTopology(**deg)
        if world_size is not None and topo.world_size != world_size:
            raise ValueError(
                f"hybrid degrees {topo.axis_sizes()} require "
                f"{topo.world_size} devices, have {world_size}")
        if self.pipeline and topo.pp == 1:
            raise ValueError("strategy.pipeline=True but pp_degree == 1")
        if self.tensor_parallel and topo.mp == 1:
            raise ValueError("strategy.tensor_parallel=True but mp_degree==1")
        if self.sharding and topo.sharding == 1 and topo.dp == 1:
            raise ValueError("strategy.sharding=True but sharding_degree==1")
        return topo

    # dict round-trip (role of the proto serialize used by launch to ship
    # the strategy to workers)
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DistributedStrategy":
        kw = dict(d)
        for field in ("amp_configs", "recompute_configs",
                      "gradient_merge_configs", "pipeline_configs",
                      "sharding_configs", "dgc_configs"):
            if field in kw and isinstance(kw[field], dict):
                sub_cls = cls.__dataclass_fields__[field].default_factory
                kw[field] = sub_cls(**kw[field])  # type: ignore[misc]
        return cls(**kw)
