"""fleet — the distributed-training facade.

Role of the reference fleet API (``python/paddle/distributed/fleet/base/
fleet_base.py``): ``fleet.init`` (:211) wires the role maker + hybrid
topology, ``fleet.distributed_optimizer`` (:912) applies the
DistributedStrategy's meta-optimizers, ``fleet.distributed_model`` wraps the
model for the chosen parallelism, and worker-introspection helpers
(``worker_index/worker_num/is_first_worker/barrier_worker``).

TPU-first: ``init`` builds ONE ``jax.sharding.Mesh`` from the strategy's
hybrid degrees (collectives come from pjit/shard_map over its axes, not
from per-group NCCL communicators); ``distributed_optimizer`` resolves the
strategy into an optax chain + AMP policy/scaler; ``distributed_model``
applies rematerialization (recompute). Multi-host wiring is
``jax.distributed.initialize`` driven by the launch CLI's env.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

import jax
import optax

from paddlebox_tpu import amp as amp_lib
from paddlebox_tpu import optimizers as opt_lib
from paddlebox_tpu.core import log
from paddlebox_tpu.fleet.strategy import DistributedStrategy
from paddlebox_tpu.parallel import topology as topo_lib
from paddlebox_tpu.fleet import metrics  # noqa: F401  (fleet.metrics.*)


class RoleMaker:
    """Process identity (role of PaddleCloudRoleMaker): rank/world from the
    JAX runtime, overridable by env for tests (PBT_TRAINER_ID/PBT_TRAINERS
    mirror PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM)."""

    def __init__(self, rank: Optional[int] = None,
                 world: Optional[int] = None):
        # Env overrides are checked FIRST and jax.process_index() only
        # touched when absent: querying it initializes the local backend,
        # which must not happen before jax.distributed.initialize on
        # multi-host setups — the exact case the env override serves.
        def resolve(explicit, env, fallback):
            if explicit is not None:
                return explicit
            if env in os.environ:
                return int(os.environ[env])
            return int(fallback())

        self.rank = resolve(rank, "PBT_TRAINER_ID", jax.process_index)
        self.world = resolve(world, "PBT_TRAINERS", jax.process_count)


@dataclasses.dataclass
class _FleetState:
    initialized: bool = False
    role: Optional[RoleMaker] = None
    strategy: Optional[DistributedStrategy] = None
    topology: Optional[topo_lib.HybridTopology] = None
    mesh: Optional[jax.sharding.Mesh] = None


_STATE = _FleetState()


def init(role_maker: Optional[RoleMaker] = None, *,
         is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None,
         devices=None) -> jax.sharding.Mesh:
    """Initialize fleet: resolve strategy → topology → global mesh
    (role of fleet.init, fleet_base.py:211; mesh plays the part of
    HybridCommunicateGroup, topology.py:134)."""
    del is_collective  # PS ("transpiler") mode is the CTR trainer path
    _STATE.role = role_maker or RoleMaker()
    _STATE.strategy = strategy or DistributedStrategy()
    devs = list(devices) if devices is not None else jax.devices()
    st = _STATE.strategy
    if not st.hybrid_configs:
        # No explicit degrees: everything to dp — but still through
        # topology() so strategy/degree consistency checks run (e.g.
        # pipeline=True with pp_degree==1 must fail here, not silently
        # train without a pipeline).
        st = dataclasses.replace(st, hybrid_configs={"dp_degree": -1})
    topo = st.topology(world_size=len(devs))
    _STATE.topology = topo
    _STATE.mesh = topo_lib.set_default_topology(topo, devs)
    _STATE.initialized = True
    log.vlog(0, "fleet.init: rank %d/%d topology %s", _STATE.role.rank,
             _STATE.role.world, topo.axis_sizes())
    return _STATE.mesh


def _require_init() -> _FleetState:
    if not _STATE.initialized:
        raise RuntimeError("call fleet.init() first")
    return _STATE


def mesh() -> jax.sharding.Mesh:
    return _require_init().mesh  # type: ignore[return-value]


def strategy() -> DistributedStrategy:
    return _require_init().strategy  # type: ignore[return-value]


def worker_index() -> int:
    return _require_init().role.rank  # type: ignore[union-attr]


def worker_num() -> int:
    return _require_init().role.world  # type: ignore[union-attr]


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker(store=None) -> None:
    """Cross-process barrier (role of fleet.barrier_worker). In-process
    (single-host) it is a no-op; multi-host uses the provided control-plane
    store (FileStore/TcpTransport) or JAX's global sync."""
    if worker_num() == 1:
        return
    if store is not None:
        store.barrier("fleet")
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("fleet_barrier")


@dataclasses.dataclass
class DistributedOptimizer:
    """Strategy-resolved training kit: the optax transformation chain plus
    the AMP policy/scaler the train step should use.

    Role of fleet.distributed_optimizer(...).minimize(...) (fleet_base.py:
    912,1477): where the reference rewrites the program through
    meta-optimizers (AMPOptimizer → RecomputeOptimizer → ... →
    RawProgramOptimizer), here the same decisions compose functionally:
    gradient sync is implicit in pjit sharding, so what remains is the
    update rule (tx), numerics (amp_policy/loss_scale), and microbatching
    (gradient merge via optax.MultiSteps).
    """

    tx: optax.GradientTransformation
    amp_policy: Optional[amp_lib.Policy]
    loss_scale: Optional[amp_lib.LossScaleState]
    every_k_steps: int = 1

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, state, params=None):
        return self.tx.update(grads, state, params)


def distributed_optimizer(optimizer, *,
                          strategy: Optional[DistributedStrategy] = None,
                          learning_rate=None) -> DistributedOptimizer:
    """Resolve (base optimizer, strategy) into a DistributedOptimizer.

    ``optimizer`` is an optax.GradientTransformation or a name accepted by
    :func:`paddlebox_tpu.optimizers.make_optimizer` ("adam", "lars", ...);
    names require ``learning_rate``. ``strategy.lars`` / ``strategy.lamb``
    replace a by-name base optimizer with the large-batch rule (role of
    LarsOptimizer/LambOptimizer meta-optimizers wrapping the user's
    momentum/adam); with an optax object they raise — the caller already
    fixed the rule.
    """
    st = strategy or _require_init().strategy or DistributedStrategy()
    if st.lars and st.lamb:
        raise ValueError("strategy.lars and strategy.lamb are exclusive")
    if isinstance(optimizer, str):
        if learning_rate is None:
            raise ValueError(
                f"optimizer by name ({optimizer!r}) requires learning_rate=")
        if st.lars:
            optimizer = "lars"
        elif st.lamb:
            optimizer = "lamb"
        optimizer = opt_lib.make_optimizer(optimizer, learning_rate)
    elif st.lars or st.lamb:
        raise ValueError(
            "strategy.lars/lamb need the base optimizer by name (e.g. "
            "'momentum') so the large-batch rule can replace it; got an "
            "optax object")
    chain = []
    if st.clip_norm:
        chain.append(optax.clip_by_global_norm(st.clip_norm))
    if st.dgc:
        from paddlebox_tpu.parallel.dgc import dgc_transform
        # Under gradient_merge the DGC transform only runs every k_steps
        # (MultiSteps wraps the chain), so its step counter ticks k times
        # slower than real steps — rescale the rampup boundary to inner
        # steps to honor the user's real-step configuration.
        rampup = st.dgc_configs.rampup_begin_step
        if st.gradient_merge and st.gradient_merge_configs.k_steps > 1:
            rampup = rampup // st.gradient_merge_configs.k_steps
        chain.append(dgc_transform(
            sparsity=st.dgc_configs.sparsity, rampup_begin_step=rampup))
    chain.append(optimizer)
    tx = optax.chain(*chain) if len(chain) > 1 else optimizer
    every_k = 1
    if st.gradient_merge and st.gradient_merge_configs.k_steps > 1:
        every_k = st.gradient_merge_configs.k_steps
        tx = optax.MultiSteps(tx, every_k_schedule=every_k,
                              use_grad_mean=st.gradient_merge_configs.avg)
    policy = None
    scale = None
    if st.amp:
        cfg = st.amp_configs
        if cfg.dtype in ("bfloat16", "bf16"):
            policy = amp_lib.bf16_policy()
        elif cfg.dtype in ("float16", "fp16"):
            policy = amp_lib.Policy(compute_dtype=jax.numpy.float16)
        else:
            raise ValueError(f"unknown amp dtype {cfg.dtype!r} "
                             "(want bfloat16/bf16 or float16/fp16)")
        if cfg.use_dynamic_loss_scaling:
            scale = amp_lib.loss_scale_init(
                cfg.init_loss_scaling,
                growth_interval=cfg.incr_every_n_steps,
                growth_factor=cfg.incr_ratio,
                backoff_factor=cfg.decr_ratio,
                backoff_interval=cfg.decr_every_n_nan_or_inf)
    return DistributedOptimizer(tx=tx, amp_policy=policy, loss_scale=scale,
                                every_k_steps=every_k)


def distributed_model(apply_fn: Callable[..., Any], *,
                      strategy: Optional[DistributedStrategy] = None
                      ) -> Callable[..., Any]:
    """Wrap a functional model apply for the strategy (role of
    fleet.distributed_model): recompute → ``jax.checkpoint``. TP/PP/SP
    structure lives in the model itself (parallel.{tp,pp,sp} layers) since
    JAX models are explicit about sharding."""
    st = strategy or _require_init().strategy or DistributedStrategy()
    if st.recompute:
        policy_name = st.recompute_configs.checkpoint_policy
        policy = getattr(jax.checkpoint_policies, policy_name, None)
        if policy is None:
            raise ValueError(f"unknown checkpoint policy {policy_name!r}")
        return jax.checkpoint(apply_fn, policy=policy)
    return apply_fn
