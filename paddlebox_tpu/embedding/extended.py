"""Extended (base + expand) sparse embedding pull/push.

Role of ``pull_box_extended_sparse`` (``operators/
pull_box_extended_sparse_op.{cc,cu,h}``; python wrapper
``_pull_box_extended_sparse``, ``contrib/layers/nn.py:1674``): each slot
lookup returns TWO embeddings — the stable base vector plus an "expand"
vector trained for a newer model head — letting one parameter server
serve both during model migration.

TPU-first: instead of two tables and two collective round-trips (the
reference calls into the PS once but scatters to two outputs —
``CopyForPull`` expand path, ``box_wrapper.cu``), the pass table is built
with fused width ``d_base + d_expand`` so ONE all-to-all pull moves both;
the split into (base, expand) is a free slice on the consumer side, and
pushes concatenate the two grads back into one payload.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.embedding.lookup import pull_local, push_local
from paddlebox_tpu.embedding.optimizers import SparseOptimizer
from paddlebox_tpu.embedding.table import PassTable, TableConfig


def extended_table_config(base: TableConfig, expand_dim: int) -> TableConfig:
    """Config for the fused-width table backing an extended lookup."""
    import dataclasses
    return dataclasses.replace(base, dim=base.dim + expand_dim)


def pull_local_extended(table: PassTable, dev_rows: jax.Array, *,
                        d_base: int, axis: str
                        ) -> Dict[str, jax.Array]:
    """Per-device extended pull: one collective, two embedding outputs
    (keys: emb / emb_expand / w / show / click)."""
    d_expand = table.dim - d_base
    if d_expand <= 0:
        raise ValueError(
            f"table dim {table.dim} must exceed d_base {d_base} — build it "
            "with extended_table_config(base_cfg, expand_dim)")
    out = pull_local(table, dev_rows, axis=axis)
    fused = out.pop("emb")
    out["emb"] = fused[:, :d_base]
    out["emb_expand"] = fused[:, d_base:]
    return out


def push_local_extended(table: PassTable, dev_rows: jax.Array,
                        grad_base: jax.Array, grad_expand: jax.Array,
                        grad_w: jax.Array, shows: jax.Array,
                        clicks: jax.Array, *, axis: str,
                        opt: Optional[SparseOptimizer] = None) -> PassTable:
    """Per-device extended push: concatenated grads, one collective."""
    grad = jnp.concatenate([grad_base, grad_expand], axis=-1)
    if grad.shape[-1] != table.dim:
        raise ValueError(
            f"base {grad_base.shape[-1]} + expand {grad_expand.shape[-1]} "
            f"grads != table dim {table.dim}")
    return push_local(table, dev_rows, grad, grad_w, shows, clicks,
                      axis=axis, opt=opt)
