"""HBM-resident persistent feature store — the device tier.

THE PaddleBox thesis, TPU edition: the reference keeps the sparse table
GPU-resident between passes (AIBox/BoxPS — ``README.md:48``'s
"100B features on GPU boxes"; HeterPS hashtables live in HBM across the
pass loop, ``heter_ps/hashtable.h``) and only exchanges deltas with the
CPU/SSD tiers. Here the persistent value store is ONE fused ``[rows, W]``
float32 array resident in HBM (same column layout as PassTable /
CommonFeatureValue, ``feature_value.h:44``), and the host keeps only the
key → row index (``native/store.cc`` incremental hash — the GPU
hashtable's role moved host-side where it is cheap, so the device side
stays a dense array XLA can gather/scatter at line rate).

Why this matters on this hardware: host↔device transfers run at
~25-35 MB/s over the axon tunnel (tools/profile_step.py), so the r02
host-RAM store paid ~75 s per pass moving 600 MB of values each way.
With the device tier, feed_pass/end_pass move only int32 row indices
(~16 MB per 4M-key pass) — values never leave HBM except for
checkpoints.

Row assignment: append-only, round-robin across shards — key k's dense
row r (from the host index) lives on shard ``r % S`` at slot ``r // S``,
so shards stay balanced as the table grows and rows never move (no
rehash). Each shard block carries one scratch slot (index C) absorbing
padded lanes of bucketed transfers. Capacity doubles by a device-side
reshape+pad when a shard fills. All per-pass device programs have
power-of-two-stable shapes, so steady-state passes reuse compiled code.

Capacity ceiling is HBM; for tables beyond it use the host-RAM
:class:`~paddlebox_tpu.embedding.store.FeatureStore` /
``ShardedFeatureStore`` tiers (same interface) — mirroring the
reference's GPU-mem vs CPU-mem vs SSD tier split.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import faults, log, monitor
from paddlebox_tpu.embedding import lifecycle
from paddlebox_tpu.embedding.table import (PassTable, TableConfig,
                                           extract_pass_values_host,
                                           fuse_values_host, lay_fused_host,
                                           plan_shards, table_widths)
from paddlebox_tpu.native import store_py as native_store

_FIELDS = ("emb", "emb_state", "w", "w_state", "show", "click")


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


# ---------------------------------------------------------------------------
# Cached jitted device programs. Keyed by static shape params so
# steady-state passes (stable pow2 sizes) never recompile.
#
# Every program operates on a TUPLE of column-part arrays (`widths` is
# the per-part column split of the fused record W). `fused` placement is
# the 1-tuple (W,) — byte-identical programs to the pre-split store;
# `split`/`host` carve the optimizer-slot columns into a sibling part.
# Gathers serve each part at the same indices and concatenate into the
# FUSED pass block (concat-then-gather == gather-then-concat, so the
# PassTable the trainer sees is bit-identical across placements);
# scatters split the fused block's columns back. The index plumbing and
# the collective count per boundary are unchanged — one request
# all_to_all, one fused-width reply.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _grow_fn(s: int, c_old: int, c_new: int, widths: Tuple[int, ...]):
    def grow_one(v, w):
        v3 = v.reshape(s, c_old + 1, w)
        out = jnp.zeros((s, c_new + 1, w), v.dtype)
        out = out.at[:, :c_old].set(v3[:, :c_old])
        return out.reshape(s * (c_new + 1), w)

    def grow(vs):
        return tuple(grow_one(v, w) for v, w in zip(vs, widths))
    return jax.jit(grow)


def _u32_uniform_device(keys_lo: jax.Array, dim: int, seed32: int,
                        scale: float) -> jax.Array:
    """On-device twin of store._u32_uniform / native pbx_init_uniform —
    bit-exact (32-bit integer ops + f32 arithmetic in the same order)."""
    k = keys_lo.astype(jnp.uint32)[:, None]
    j = jnp.arange(1, dim + 1, dtype=jnp.uint32)[None, :]
    z = k + j * jnp.uint32(0x9E3779B9) + jnp.uint32(seed32)
    z = z ^ (z >> jnp.uint32(16))
    z = z * jnp.uint32(0x85EBCA6B)
    z = z ^ (z >> jnp.uint32(13))
    z = z * jnp.uint32(0xC2B2AE35)
    z = z ^ (z >> jnp.uint32(16))
    u = (z >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(
        1.0 / (1 << 24))
    return ((jnp.float32(2.0) * u - jnp.float32(1.0))
            * jnp.float32(scale)).astype(jnp.float32)


def _split_cols(block: jax.Array, widths: Tuple[int, ...]):
    """Column-split a fused [n, W] block into the part widths."""
    out, off = [], 0
    for w in widths:
        out.append(lax.slice_in_dim(block, off, off + w, axis=1))
        off += w
    return out


@functools.lru_cache(maxsize=64)
def _append_fn_local(widths: Tuple[int, ...], cap: int, dim: int,
                     seed32: int, scale: float):
    """Masked dynamic-update-slice append of cnt (<= cap) NEW rows at slot
    `start`: rows are BUILT ON DEVICE from 4-byte key hashes (emb columns
    via the shared deterministic init; the state tail from a constant
    template row) — the host transfers cap*4 bytes, not cap*W*4."""
    def upd(vs, keys_lo, template, start, cnt):
        emb = _u32_uniform_device(keys_lo, dim, seed32, scale)
        keep = (jnp.arange(cap) < cnt)[:, None]
        out, off = [], 0
        for v, w in zip(vs, widths):
            rows = jnp.broadcast_to(template[off:off + w], (cap, w))
            if off == 0:
                rows = jnp.concatenate([emb, rows[:, dim:]], axis=1)
            cur = lax.dynamic_slice(v, (start, 0), (cap, w))
            out.append(lax.dynamic_update_slice(
                v, jnp.where(keep, rows, cur), (start, 0)))
            off += w
        return tuple(out)
    return jax.jit(upd, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _append_fn_sharded(mesh: Mesh, axis: str, widths: Tuple[int, ...],
                       cap: int, dim: int, seed32: int, scale: float):
    wsum = sum(widths)

    def body(vs, keys_lo, template, start, cnt):
        emb = _u32_uniform_device(keys_lo.reshape(cap), dim, seed32, scale)
        keep = (jnp.arange(cap) < cnt[0])[:, None]
        tmpl = template.reshape(1, wsum)
        out, off = [], 0
        for v, w in zip(vs, widths):
            rows = jnp.broadcast_to(tmpl[:, off:off + w], (cap, w))
            if off == 0:
                rows = jnp.concatenate([emb, rows[:, dim:]], axis=1)
            cur = lax.dynamic_slice(v, (start[0], 0), (cap, w))
            out.append(lax.dynamic_update_slice(
                v, jnp.where(keep, rows, cur), (start[0], 0)))
            off += w
        return tuple(out)
    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis), P(axis),
                                 P(axis)),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(sm, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _gather_fn_local(widths: Tuple[int, ...], rps: int):
    """vs[*][idx] into a FUSED pass block [rps+1, W]. idx == scratch (the
    store's last row) marks padding/missing lanes — they read zero.
    init_idx/init_vals overlay host-computed init records onto missing
    pass rows (read-only pulls; pads point init_idx at the trash row,
    re-zeroed)."""
    w = sum(widths)

    def gather(vs, idx, init_idx, init_vals):
        scratch = vs[0].shape[0] - 1
        miss = (idx == scratch)[:, None]
        picked = jnp.concatenate(
            [jnp.where(miss, 0.0, v[idx]) for v in vs], axis=1)
        block = jnp.concatenate([picked, jnp.zeros((1, w), picked.dtype)])
        block = block.at[init_idx].set(init_vals)
        return block.at[rps].set(0.0)
    return jax.jit(gather)


@functools.lru_cache(maxsize=64)
def _scatter_fn_local(widths: Tuple[int, ...], rps: int):
    """Write pass block rows back into store parts: vs[p][idx[i]] =
    block[i, part p's columns] for i < rps (pads point idx at the
    scratch slot)."""
    def scatter(vs, block, idx):
        parts = _split_cols(block[:rps], widths)
        return tuple(v.at[idx].set(b) for v, b in zip(vs, parts))
    return jax.jit(scatter, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _gather_fn_sharded(mesh: Mesh, axis: str, s: int, cap: int,
                       widths: Tuple[int, ...], rps: int, store_cap: int):
    w = sum(widths)

    def body(vs, rq, pl, init_idx, init_vals):
        rq2 = rq.reshape(s, cap)
        # rq2[s2, c]: slots I request from store-shard s2. Exchange so
        # each store shard receives its requests, serve, exchange back.
        recv = lax.all_to_all(rq2, axis, split_axis=0, concat_axis=0,
                              tiled=True).reshape(s, cap)
        # Scratch-slot requests (padding / missing keys) serve zeros.
        miss = (recv == store_cap)[..., None]
        served = jnp.concatenate(
            [jnp.where(miss, 0.0, v[recv]) for v in vs], axis=-1)
        reply = lax.all_to_all(
            served.reshape(s * cap, w), axis, split_axis=0,
            concat_axis=0, tiled=True).reshape(s * cap, w)
        block = jnp.zeros((rps + 1, w), served.dtype)
        block = block.at[pl.reshape(s * cap)].set(reply)
        # Read-only pulls: overlay init records for missing keys.
        block = block.at[init_idx.reshape(-1)].set(init_vals)
        # Pads aimed at the trash row are re-zeroed.
        return block.at[rps].set(0.0)
    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis), P(axis),
                                 P(axis)),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(sm)


@functools.lru_cache(maxsize=64)
def _scatter_fn_sharded(mesh: Mesh, axis: str, s: int, cap: int,
                        widths: Tuple[int, ...]):
    w = sum(widths)

    def body(vs, b, sr, ds):
        sr2 = sr.reshape(s, cap)
        payload = b[sr2]                              # [s, cap, w]
        sent = lax.all_to_all(
            payload.reshape(s * cap, w), axis, split_axis=0,
            concat_axis=0, tiled=True)
        recv_dst = lax.all_to_all(ds.reshape(s, cap), axis, split_axis=0,
                                  concat_axis=0, tiled=True)
        idx = recv_dst.reshape(s * cap)
        parts = _split_cols(sent.reshape(s * cap, w), widths)
        return tuple(v.at[idx].set(p) for v, p in zip(vs, parts))
    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis), P(axis)),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(sm, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _merge_fn_local(widths: Tuple[int, ...], rps: int):
    """Late half of the split pass build: overlay store rows vs[*][idx[i]]
    at block[place[i]] — the shared-key remainder gather AFTER the
    previous pass's write-back, merged into the early-built block. Pads
    point idx at the scratch row and place at the trash row (re-zeroed),
    so the early-gathered rows elsewhere are untouched."""
    def merge(block, vs, idx, place):
        picked = jnp.concatenate([v[idx] for v in vs], axis=1)
        out = block.at[place].set(picked)
        return out.at[rps].set(0.0)
    return jax.jit(merge, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _merge_fn_sharded(mesh: Mesh, axis: str, s: int, cap: int,
                      widths: Tuple[int, ...], rps: int, store_cap: int):
    w = sum(widths)

    def body(block, vs, rq, pl):
        rq2 = rq.reshape(s, cap)
        recv = lax.all_to_all(rq2, axis, split_axis=0, concat_axis=0,
                              tiled=True).reshape(s, cap)
        miss = (recv == store_cap)[..., None]
        served = jnp.concatenate(
            [jnp.where(miss, 0.0, v[recv]) for v in vs], axis=-1)
        reply = lax.all_to_all(
            served.reshape(s * cap, w), axis, split_axis=0,
            concat_axis=0, tiled=True).reshape(s * cap, w)
        out = block.at[pl.reshape(s * cap)].set(reply)
        return out.at[rps].set(0.0)
    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis), P(axis)),
                       out_specs=P(axis), check_vma=False)
    return jax.jit(sm, donate_argnums=(0,))


@functools.lru_cache(maxsize=64)
def _fused_boundary_fn_local(widths: Tuple[int, ...], rps_prev: int,
                             rps_next: int):
    """ONE device program for the pass boundary (FLAGS_pass_boundary_
    fuse): the previous pass's EndPass scatter followed by the next
    pass's shared-remainder gather — the gather reads the POST-scatter
    store, so shared keys observe the write-back exactly as the serial
    sequencing guarantees, but the host pays one dispatch, not two.
    Under split placement BOTH parts scatter and serve inside this same
    dispatch — the slot columns update in lockstep with the values."""
    def fused(vs, prev_block, prev_idx, next_block, idx, place):
        parts = _split_cols(prev_block[:rps_prev], widths)
        vs = tuple(v.at[prev_idx].set(p) for v, p in zip(vs, parts))
        picked = jnp.concatenate([v[idx] for v in vs], axis=1)
        nb = next_block.at[place].set(picked)
        return vs, nb.at[rps_next].set(0.0)
    return jax.jit(fused, donate_argnums=(0, 3))


@functools.lru_cache(maxsize=64)
def _fused_boundary_fn_sharded(mesh: Mesh, axis: str, s: int,
                               cap_prev: int, cap_next: int,
                               widths: Tuple[int, ...],
                               rps_prev: int, rps_next: int,
                               store_cap: int):
    w = sum(widths)

    def body(vs, b_prev, sr, ds, b_next, rq, pl):
        # EndPass scatter leg (the _scatter_fn_sharded structure).
        payload = b_prev[sr.reshape(s, cap_prev)]
        sent = lax.all_to_all(
            payload.reshape(s * cap_prev, w), axis, split_axis=0,
            concat_axis=0, tiled=True)
        recv_dst = lax.all_to_all(ds.reshape(s, cap_prev), axis,
                                  split_axis=0, concat_axis=0, tiled=True)
        idx_w = recv_dst.reshape(s * cap_prev)
        parts = _split_cols(sent.reshape(s * cap_prev, w), widths)
        vs = tuple(v.at[idx_w].set(p) for v, p in zip(vs, parts))
        # Remainder-gather leg (the _merge_fn_sharded structure) over
        # the post-scatter values.
        recv = lax.all_to_all(rq.reshape(s, cap_next), axis, split_axis=0,
                              concat_axis=0, tiled=True).reshape(s,
                                                                 cap_next)
        miss = (recv == store_cap)[..., None]
        served = jnp.concatenate(
            [jnp.where(miss, 0.0, v[recv]) for v in vs], axis=-1)
        reply = lax.all_to_all(
            served.reshape(s * cap_next, w), axis, split_axis=0,
            concat_axis=0, tiled=True).reshape(s * cap_next, w)
        nb = b_next.at[pl.reshape(s * cap_next)].set(reply)
        return vs, nb.at[rps_next].set(0.0)
    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(axis),) * 7,
                       out_specs=(P(axis), P(axis)), check_vma=False)
    return jax.jit(sm, donate_argnums=(0, 4))


@functools.lru_cache(maxsize=64)
def _decay_fn(d: int, decay: float):
    def dec(v):
        sc = v[:, d + 1:d + 3] * decay
        return jnp.concatenate([v[:, :d + 1], sc, v[:, d + 3:]], axis=1)
    return jax.jit(dec, donate_argnums=(0,))


class DeviceFeatureStore:
    """FeatureStore-compatible persistent tier living in device HBM."""

    shared = False

    def __init__(self, config: TableConfig, *, mesh: Optional[Mesh] = None,
                 table_axis: str = "dp", seed: int = 0,
                 capacity_hint: int = 0):
        self.config = config
        from paddlebox_tpu.core import flags
        from paddlebox_tpu.embedding.optimizers import make_sparse_optimizer
        self.opt = make_sparse_optimizer(config)
        self.dim, self.ke, self.kw = table_widths(config)
        self.width = self.dim + 3 + self.ke + self.kw
        self.mesh = mesh
        self.axis = table_axis
        self.num_shards = (int(mesh.shape[table_axis])
                           if mesh is not None else 1)
        self._sharding = (NamedSharding(mesh, P(table_axis))
                          if mesh is not None else None)
        # FLAGS_table_slot_placement: where the per-row optimizer-slot
        # columns live. 'fused' is the historic single [rows, W] record;
        # 'split' carves emb_state/w_state into a sibling [rows, Ke+Kw]
        # part (hot array holds exactly (D+3)*4 bytes/row); 'host'
        # additionally pins that part to host memory — HBM then holds
        # values, not values×slots, with transient crossings around the
        # boundary programs. An optimizer without slot columns has
        # nothing to carve, so it degrades to fused.
        placement = str(flags.flag("table_slot_placement"))
        if placement not in ("fused", "split", "host"):
            raise ValueError("table_slot_placement must be "
                             f"fused|split|host, got {placement!r}")
        slot_w = self.ke + self.kw
        if slot_w == 0:
            placement = "fused"
        self.placement = placement
        self._widths = ((self.width,) if placement == "fused"
                        else (self.dim + 3, slot_w))
        self._part_shardings = self._resolve_part_shardings()
        self._index = native_store.KeyIndex()
        if capacity_hint:
            self._index.reserve(capacity_hint)
        s = self.num_shards
        self._cap = _pow2(max(1 << 10, -(-int(capacity_hint) // s)))
        self._parts = self._place_parts(tuple(
            jnp.zeros((s * (self._cap + 1), w), jnp.float32)
            for w in self._widths))
        self._seed = int(seed)
        # Serializes mutations of (_index, _parts, _cap, _dirty_parts).
        # NOT reentrant: public methods lock, _*_locked helpers assume it.
        self._lock = threading.Lock()
        self._dirty_parts: List[np.ndarray] = []
        self._shrunk_since_base = False
        # Per-row unseen-days age aligned with dense row ids (host side,
        # like the key index — the HBM record is untouched): bumped by
        # shrink, zeroed by any write-back of the row's key.
        self._unseen = np.zeros((0,), np.int32)

    # -- plumbing ----------------------------------------------------------

    def _resolve_part_shardings(self) -> Tuple:
        """Persistent placement per part: device sharding for the hot
        part; 'host' pins the slot part to the backend's host memory
        kind (pinned_host on TPU; CPU backends expose unpinned_host,
        which IS their default memory — the placement is then a no-op
        byte-wise but exercises the same code path)."""
        if self.placement != "host":
            return tuple(self._sharding for _ in self._widths)
        from jax.sharding import SingleDeviceSharding
        from paddlebox_tpu.parallel.zero import _resolve_host_kind
        if self.mesh is not None:
            kind = _resolve_host_kind(self.mesh, "pinned_host")
            slot_sh = NamedSharding(self.mesh, P(self.axis),
                                    memory_kind=kind)
            return (self._sharding, slot_sh)
        dev = jax.devices()[0]
        try:
            kinds = {m.kind for m in dev.addressable_memories()}
        except Exception:
            kinds = set()
        kind = ("pinned_host" if "pinned_host" in kinds
                else "unpinned_host" if "unpinned_host" in kinds else None)
        slot_sh = (SingleDeviceSharding(dev, memory_kind=kind)
                   if kind is not None else None)
        return (None, slot_sh)

    def _place(self, arr):
        if self._sharding is not None:
            return jax.device_put(arr, self._sharding)
        return arr

    def _place_parts(self, parts) -> Tuple:
        return tuple(
            jax.device_put(p, sh) if sh is not None else p
            for p, sh in zip(parts, self._part_shardings))

    def _compute_parts(self) -> Tuple:
        """Parts staged for a jitted device program. 'host' placement
        pays its transient HBM crossing here (slot part host -> device);
        other placements pass through untouched."""
        if self.placement != "host":
            return self._parts
        dev_sh = (self._sharding if self._sharding is not None
                  else jax.devices()[0])
        return (self._parts[0],) + tuple(
            jax.device_put(p, dev_sh) for p in self._parts[1:])

    def _settle_parts(self, parts) -> Tuple:
        """Inverse of :meth:`_compute_parts`: stream mutated parts back
        to their persistent placement (slot part device -> host)."""
        if self.placement != "host":
            return tuple(parts)
        return (parts[0],) + tuple(
            jax.device_put(p, sh)
            for p, sh in zip(parts[1:], self._part_shardings[1:]))

    @property
    def num_features(self) -> int:
        return self._index.size

    def memory_stats(self) -> Dict[str, object]:
        """Measured per-device memory bytes of the live store arrays
        (actual shardings + memory kinds, not flag arithmetic), split
        hot vs slot columns; also lands the table/*_hbm_bytes gauges the
        benches record. Under 'fused' the slot share is the column
        fraction of the one array; under 'host' on TPU the slot part is
        in host memory and measures 0 HBM bytes."""
        from paddlebox_tpu.parallel.zero import tree_hbm_bytes_per_device
        with self._lock:
            parts = self._parts
        if self.placement == "fused":
            total = tree_hbm_bytes_per_device(parts[0])
            hot = total * (self.dim + 3) // self.width
            slot = total - hot
        else:
            hot = tree_hbm_bytes_per_device(parts[0])
            slot = tree_hbm_bytes_per_device(parts[1:])
        stats = {"hot_hbm_bytes": int(hot), "slot_hbm_bytes": int(slot),
                 "placement": self.placement}
        monitor.set_gauge("table/hot_hbm_bytes", float(hot))
        monitor.set_gauge("table/slot_hbm_bytes", float(slot))
        return stats

    def _ensure_capacity_locked(self, total_rows: int) -> None:
        s = self.num_shards
        need = -(-total_rows // s)
        if need <= self._cap:
            return
        c_new = self._cap
        while c_new < need:
            c_new *= 2
        log.vlog(1, "device store grow: %d -> %d slots/shard",
                 self._cap, c_new)
        self._parts = self._place_parts(
            _grow_fn(s, self._cap, c_new, self._widths)(
                self._compute_parts()))
        self._cap = c_new

    def _host_init_fused(self, keys: np.ndarray) -> np.ndarray:
        """[n, W] fused init record for brand-new keys (deterministic
        per-key init — store.py pull_for_pass contract)."""
        n = keys.shape[0]
        d = self.dim
        out = np.zeros((n, self.width), np.float32)
        out[:, :d] = native_store.init_uniform(keys, d, self._seed,
                                               self.config.init_scale)
        out[:, d + 3:d + 3 + self.ke] = self.opt.init_emb_state(n, d)
        out[:, d + 3 + self.ke:] = self.opt.init_w_state(n)
        return out

    def ensure_rows(self, keys: np.ndarray) -> np.ndarray:
        """Find-or-create store rows for (deduped, nonzero) keys; new keys
        are initialized on device. Returns dense rows [n]."""
        with self._lock:
            return self._ensure_rows_locked(keys)

    def _ensure_rows_locked(self, keys: np.ndarray) -> np.ndarray:
        k = np.ascontiguousarray(keys, np.uint64)
        base = self._index.size
        if base == 0 and k.size and native_store.is_sorted_unique_nonzero(k):
            # Fresh-build bypass (sorted-run store build, round 13):
            # pass-key arrays arrive sorted unique (dedup_keys /
            # run-merge output), so the first build skips the serial
            # find-or-insert walk — bulk placement parallelizes and the
            # rows (0..n-1 in input order) are bit-identical to upsert
            # on an empty index.
            rows = self._index.bulk_build(k)
            self._append_rows_locked(k, 0, int(k.size))
            monitor.add("device_store/new_keys", int(k.size))
            monitor.add("device_store/bulk_builds", 1)
            return rows
        rows, n_new = self._index.upsert(k)
        if n_new:
            new_keys = k[rows >= base]
            # upsert assigns new rows in input order, so new_keys (input
            # order) aligns with rows base..base+n_new-1.
            self._append_rows_locked(new_keys, base, n_new)
            monitor.add("device_store/new_keys", int(n_new))
        return rows

    @property
    def _template_row(self) -> np.ndarray:
        """[W] constant init record tail: emb columns are overwritten on
        device by the per-key hash; w/show/click zero; optimizer-state
        columns from the optimizer's init pattern (constant per column)."""
        t = getattr(self, "_template_cache", None)
        if t is None:
            t = np.zeros((self.width,), np.float32)
            d = self.dim
            t[d + 3:d + 3 + self.ke] = self.opt.init_emb_state(1, d)[0]
            t[d + 3 + self.ke:] = self.opt.init_w_state(1)[0]
            self._template_cache = t
        return t

    def _append_rows_locked(self, new_keys: np.ndarray, base: int,
                            n_new: int) -> None:
        """Initialize dense rows [base, base+n_new) for new_keys —
        per-shard contiguous slot ranges, so a masked dynamic-update-slice,
        not a scatter; only 4 bytes/key cross to the device (rows are
        built there from the key hash + a constant template)."""
        s = self.num_shards
        w = self.width
        seed32 = self._seed & 0xFFFFFFFF
        scale = float(self.config.init_scale)
        # New rows start at age 0 (inserted FOR a pass = just seen).
        self._unseen = np.concatenate(
            [self._unseen, np.zeros((n_new,), np.int32)])
        lo = (new_keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        if s == 1:
            cap = _pow2(n_new)
            # The pow2-padded DUS window [base, base+cap) must fit inside
            # the slot region — dynamic_update_slice CLAMPS an
            # out-of-bounds start, which would silently shift the write.
            self._ensure_capacity_locked((base + cap) * s)
            keys_pad = np.zeros((cap,), np.uint32)
            keys_pad[:n_new] = lo
            self._parts = self._settle_parts(_append_fn_local(
                self._widths, cap, self.dim, seed32, scale)(
                self._compute_parts(), jnp.asarray(keys_pad),
                jnp.asarray(self._template_row), base, n_new))
            return
        rows = np.arange(base, base + n_new)
        shard = rows % s
        counts = np.bincount(shard, minlength=s)
        cap = _pow2(int(counts.max()))
        start_min_per_shard = base // s
        self._ensure_capacity_locked((start_min_per_shard + cap + 1) * s)
        keys_pad = np.zeros((s, cap), np.uint32)
        starts = np.zeros((s,), np.int32)
        for sh in range(s):
            sel = shard == sh
            if sel.any():
                starts[sh] = rows[sel][0] // s
                keys_pad[sh, :int(counts[sh])] = lo[sel]
        kd = jax.device_put(keys_pad, self._sharding)
        tmpl = jax.device_put(
            np.broadcast_to(self._template_row, (s, w)).copy(),
            self._sharding)
        st = jax.device_put(starts, self._sharding)
        cn = jax.device_put(counts.astype(np.int32), self._sharding)
        self._parts = self._settle_parts(_append_fn_sharded(
            self.mesh, self.axis, self._widths, cap,
            self.dim, seed32, scale)(
            self._compute_parts(), kd, tmpl, st, cn))

    # -- pass build / write-back (the hot per-pass surface) ----------------

    def pull_pass_table(self, pass_keys_sorted: np.ndarray,
                        num_pass_shards: int, *, readonly: bool = False
                        ) -> Tuple[PassTable, np.ndarray]:
        """Build the per-pass device table by an on-device gather from the
        resident store (role of BuildPull + BuildGPUTask,
        ps_gpu_wrapper.cc:362,684 — zero host value traffic). Returns
        (table, dense store rows aligned to the sorted keys).

        ``readonly=True`` (eval passes, SetTestMode role): unknown keys
        are NOT inserted — their pass rows carry the deterministic init
        record via an overlay, and the store is left untouched; the
        returned rows have -1 at missing keys."""
        faults.faultpoint("device_store/pull")
        with self._lock:
            monitor.add("device_store/boundary_progs", 1)
            return self._pull_pass_table_locked(pass_keys_sorted,
                                                num_pass_shards,
                                                readonly=readonly)

    def pull_pass_table_partial(self, pass_keys_sorted: np.ndarray,
                                num_pass_shards: int, *,
                                select: np.ndarray,
                                readonly: bool = False
                                ) -> Tuple[PassTable, np.ndarray]:
        """EARLY half of the split pass build (role of the overlapped
        BuildPull threads, ps_gpu_wrapper.cc:907, on the HBM tier):
        gather only the ``select`` pass positions — the keys the active
        pass cannot dirty (it writes back only its own key set) — while
        it still trains. Non-selected positions read zero until
        :meth:`merge_pass_rows` / the fused boundary fills them in.
        Unseen keys are inserted here too (``readonly=False``): the
        append region is disjoint from the active pass's rows. Missing
        keys under ``readonly`` get their init-record overlay in this
        half (a missing key is never shared — it is not in the store at
        all, so it is always an early position)."""
        with self._lock:
            k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
            if readonly:
                rows = self._index.lookup(k)
            else:
                rows = self._ensure_rows_locked(k)
            n = k.shape[0]
            rps = plan_shards(n, num_pass_shards)
            sel = np.asarray(select, bool)
            rows_eff = np.where(sel, rows, -1)
            missing = np.flatnonzero(sel & (rows < 0))
            init = (self._host_init_fused(k[missing]) if missing.size
                    else np.zeros((0, self.width), np.float32))
            table_vals = self._gather_pass_locked(rows_eff, n, rps,
                                                  num_pass_shards,
                                                  missing, init)
            table = PassTable(vals=table_vals, rows_per_shard=rps,
                              num_shards=num_pass_shards, dim=self.dim,
                              ke=self.ke, kw=self.kw)
            monitor.add("store/pass_keys", n)
            monitor.add("device_store/early_rows", int(sel.sum()))
            return table, rows

    def merge_pass_rows(self, rows: np.ndarray, table: PassTable,
                        select: np.ndarray) -> PassTable:
        """LATE half of the split build: gather the ``select`` positions
        (the shared-key remainder, post write-back) from the store into
        the early-built block. Selected rows are always present (shared
        keys live in the store by definition), so no init overlay."""
        sel_pos = np.flatnonzero(np.asarray(select, bool))
        with self._lock:
            if sel_pos.size == 0:
                return table
            monitor.add("device_store/boundary_progs", 1)
            vals = self._merge_rows_locked(table.vals, rows, sel_pos,
                                           table.rows_per_shard,
                                           table.num_shards)
        return dataclasses.replace(table, vals=vals)

    def push_and_pull_merge(self, prev_keys_sorted: np.ndarray,
                            prev_rows: np.ndarray, prev_table: PassTable,
                            next_rows: np.ndarray, next_table: PassTable,
                            next_select: np.ndarray) -> PassTable:
        """Fused pass boundary (FLAGS_pass_boundary_fuse): the previous
        pass's write-back scatter AND the next pass's shared-remainder
        gather in ONE jitted program — one dispatch crosses the host
        link per boundary instead of two, and the gather reads the
        post-scatter store so shared keys observe the write-back
        bit-exactly as the serial sequencing does."""
        faults.faultpoint("device_store/fused")
        with self._lock:
            k = np.ascontiguousarray(prev_keys_sorted, np.uint64)
            n_prev = k.shape[0]
            sel_pos = np.flatnonzero(np.asarray(next_select, bool))
            s = self.num_shards
            rps_p = prev_table.rows_per_shard
            sp_p = prev_table.num_shards
            rps_n = next_table.rows_per_shard
            sp_n = next_table.num_shards
            monitor.add("device_store/boundary_progs", 1)
            monitor.add("device_store/boundary_fused", 1)
            if s == 1 and sp_p == 1 and sp_n == 1:
                scratch = s * (self._cap + 1) - 1
                idx_p = np.full((rps_p,), scratch, np.int64)
                idx_p[:n_prev] = self._dev_idx(prev_rows)
                m = sel_pos.size
                cap_m = _pow2(max(m, 1))
                idx_n = np.full((cap_m,), scratch, np.int64)
                place = np.full((cap_m,), rps_n, np.int32)
                if m:
                    idx_n[:m] = self._dev_idx(next_rows[sel_pos])
                    place[:m] = sel_pos
                parts, merged = _fused_boundary_fn_local(
                    self._widths, rps_p, rps_n)(
                    self._compute_parts(), prev_table.vals,
                    jnp.asarray(idx_p, jnp.int32), next_table.vals,
                    jnp.asarray(idx_n, jnp.int32), jnp.asarray(place))
                self._parts = self._settle_parts(parts)
            else:
                if s != sp_p or s != sp_n:
                    raise ValueError(
                        "pass shards must equal store shards")
                slot, local, _, cap_p = self._bucket_exact(
                    prev_rows, n_prev, rps_p, sp_p)
                src = np.where(local >= 0, local, rps_p).astype(np.int32)
                dst = np.where(slot >= 0, slot, self._cap).astype(np.int32)
                req, place, cap_n = self._bucket_selected(
                    next_rows, sel_pos, rps_n, sp_n)
                src_d = jax.device_put(
                    jnp.asarray(src.reshape(sp_p, s * cap_p)),
                    self._sharding)
                dst_d = jax.device_put(
                    jnp.asarray(dst.reshape(sp_p, s * cap_p)),
                    self._sharding)
                req_d = jax.device_put(
                    jnp.asarray(req.reshape(sp_n, s * cap_n)),
                    self._sharding)
                pl_d = jax.device_put(
                    jnp.asarray(place.reshape(sp_n, s * cap_n)),
                    self._sharding)
                parts, merged = _fused_boundary_fn_sharded(
                    self.mesh, self.axis, s, cap_p, cap_n, self._widths,
                    rps_p, rps_n, self._cap)(
                    self._compute_parts(), prev_table.vals, src_d, dst_d,
                    next_table.vals, req_d, pl_d)
                self._parts = self._settle_parts(parts)
            self._dirty_parts.append(k.copy())
            self._unseen[prev_rows] = 0
            monitor.add("device_store/pushed_keys", n_prev)
        return dataclasses.replace(next_table, vals=merged)

    def _bucket_selected(self, rows: np.ndarray, sel_pos: np.ndarray,
                         rps: int, sp: int
                         ) -> Tuple[np.ndarray, np.ndarray, int]:
        """[sp, s, cap] (request slots, pass-local placements) covering
        ONLY the selected pass positions (all with valid store rows);
        pads request the scratch slot and place at the trash row. cap is
        pow2-stable like _bucket_exact's."""
        s = self.num_shards
        m = sel_pos.size
        rs = rows[sel_pos]
        store_shard = (rs % s).astype(np.int64)
        store_slot = (rs // s).astype(np.int64)
        pass_shard = (sel_pos % sp).astype(np.int64)
        pass_local = (sel_pos // sp).astype(np.int64)
        counts = np.zeros((sp, s), np.int64)
        np.add.at(counts, (pass_shard, store_shard), 1)
        cap = _pow2(max(int(counts.max()) if m else 1, 1))
        req = np.full((sp, s, cap), self._cap, np.int64)
        place = np.full((sp, s, cap), rps, np.int64)
        order = np.lexsort((store_shard, pass_shard))
        gs = pass_shard[order] * s + store_shard[order]
        starts = np.searchsorted(gs, np.arange(sp * s))
        pos = np.arange(m) - starts[gs]
        req[pass_shard[order], store_shard[order], pos] = \
            store_slot[order]
        place[pass_shard[order], store_shard[order], pos] = \
            pass_local[order]
        return req.astype(np.int32), place.astype(np.int32), cap

    def _merge_rows_locked(self, block_vals: jax.Array, rows: np.ndarray,
                           sel_pos: np.ndarray, rps: int,
                           sp: int) -> jax.Array:
        s = self.num_shards
        m = sel_pos.size
        if s == 1 and sp == 1:
            cap_m = _pow2(max(m, 1))
            scratch = s * (self._cap + 1) - 1
            idx = np.full((cap_m,), scratch, np.int64)
            place = np.full((cap_m,), rps, np.int32)
            if m:
                idx[:m] = self._dev_idx(rows[sel_pos])
                place[:m] = sel_pos
            return _merge_fn_local(self._widths, rps)(
                block_vals, self._compute_parts(),
                jnp.asarray(idx, jnp.int32), jnp.asarray(place))
        if s != sp:
            raise ValueError("pass shards must equal store shards")
        req, place, cap = self._bucket_selected(rows, sel_pos, rps, sp)
        req_d = jax.device_put(
            jnp.asarray(req.reshape(sp, s * cap)), self._sharding)
        pl_d = jax.device_put(
            jnp.asarray(place.reshape(sp, s * cap)), self._sharding)
        return _merge_fn_sharded(self.mesh, self.axis, s, cap,
                                 self._widths, rps, self._cap)(
            block_vals, self._compute_parts(), req_d, pl_d)

    def _pull_pass_table_locked(self, pass_keys_sorted: np.ndarray,
                                num_pass_shards: int, *,
                                readonly: bool = False
                                ) -> Tuple[PassTable, np.ndarray]:
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        if readonly:
            rows = self._index.lookup(k)
        else:
            rows = self._ensure_rows_locked(k)
        n = k.shape[0]
        rps = plan_shards(n, num_pass_shards)
        missing = np.flatnonzero(rows < 0)
        init = (self._host_init_fused(k[missing]) if missing.size
                else np.zeros((0, self.width), np.float32))
        table_vals = self._gather_pass_locked(rows, n, rps,
                                              num_pass_shards,
                                              missing, init)
        table = PassTable(vals=table_vals, rows_per_shard=rps,
                          num_shards=num_pass_shards, dim=self.dim,
                          ke=self.ke, kw=self.kw)
        monitor.add("store/pass_keys", n)
        return table, rows

    def push_pass_table(self, pass_keys_sorted: np.ndarray,
                        rows: np.ndarray, table: PassTable) -> None:
        """Write a finished pass table back into the resident store (role
        of EndPass, ps_gpu_wrapper.cc:983 — one on-device scatter)."""
        faults.faultpoint("device_store/push")
        with self._lock:
            k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
            n = k.shape[0]
            if n == 0:
                return
            monitor.add("device_store/boundary_progs", 1)
            self._parts = self._scatter_pass_locked(
                table.vals, rows, n, table.rows_per_shard,
                table.num_shards)
            self._dirty_parts.append(k.copy())
            self._unseen[rows] = 0
            monitor.add("device_store/pushed_keys", n)

    def _dev_idx(self, rows: np.ndarray) -> np.ndarray:
        s = self.num_shards
        return ((rows % s) * (self._cap + 1) + rows // s).astype(np.int64)

    def _bucket_exact(self, rows: np.ndarray, n: int, rps: int, sp: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Host-exact bucketing for the sharded pass transfers.

        Pass rank p (round-robin: pass-shard p % sp, local p // sp —
        table.py layout) maps to store shard rows[p] % s at slot
        rows[p] // s; missing keys (row -1, read-only pulls) route to the
        scratch slot of shard p % s so they read zero. Returns
        (slot [sp,s,cap], local [sp,s,cap], counts, cap) with pads
        slot=-1/local=-1 to be sentineled by the caller; cap pow2-stable
        across passes.
        """
        s = self.num_shards
        valid = rows >= 0
        store_shard = np.where(valid, rows % s, np.arange(n) % s
                               ).astype(np.int64)
        store_slot = np.where(valid, rows // s, self._cap).astype(np.int64)
        pass_shard = (np.arange(n) % sp).astype(np.int64)
        pass_local = (np.arange(n) // sp).astype(np.int64)
        counts = np.zeros((sp, s), np.int64)
        np.add.at(counts, (pass_shard, store_shard), 1)
        cap = _pow2(max(int(counts.max()) if n else 1, 1))
        slot = np.full((sp, s, cap), -1, np.int64)
        local = np.full((sp, s, cap), -1, np.int64)
        order = np.lexsort((store_shard, pass_shard))
        gs = pass_shard[order] * s + store_shard[order]
        starts = np.searchsorted(gs, np.arange(sp * s))
        pos = np.arange(n) - starts[gs]
        slot[pass_shard[order], store_shard[order], pos] = store_slot[order]
        local[pass_shard[order], store_shard[order], pos] = \
            pass_local[order]
        return slot, local, counts, cap

    def _gather_pass_locked(self, rows: np.ndarray, n: int, rps: int,
                            sp: int, missing: Optional[np.ndarray] = None,
                            init: Optional[np.ndarray] = None) -> jax.Array:
        """missing: pass-row indices (into [0, n)) whose keys are absent
        (read-only pulls); init [len(missing), W] overlays their rows."""
        s = self.num_shards
        w = self.width
        n_miss = missing.size if missing is not None else 0
        if s == 1 and sp == 1:
            scratch = s * (self._cap + 1) - 1
            idx = np.full((rps,), scratch, np.int64)
            idx[:n] = np.where(rows >= 0, self._dev_idx(rows), scratch)
            cap_m = _pow2(max(n_miss, 1))
            init_idx = np.full((cap_m,), rps, np.int32)
            init_vals = np.zeros((cap_m, w), np.float32)
            if n_miss:
                init_idx[:n_miss] = missing
                init_vals[:n_miss] = init
            return _gather_fn_local(self._widths, rps)(
                self._compute_parts(), jnp.asarray(idx, jnp.int32),
                jnp.asarray(init_idx), jnp.asarray(init_vals))
        if s != sp:
            raise ValueError(
                f"pass shards ({sp}) must equal store shards ({s}) — both "
                f"are the size of the same table mesh axis")
        slot, local, _, cap = self._bucket_exact(rows, n, rps, sp)
        req = np.where(slot >= 0, slot, self._cap).astype(np.int32)
        place = np.where(local >= 0, local, rps).astype(np.int32)
        # Overlay init records bucketed by pass shard.
        if n_miss:
            m_shard = missing % sp
            m_local = (missing // sp).astype(np.int32)
            m_counts = np.bincount(m_shard, minlength=sp)
            cap_m = _pow2(int(m_counts.max()))
        else:
            cap_m = 1
        init_idx = np.full((sp, cap_m), rps, np.int32)
        init_vals = np.zeros((sp, cap_m, w), np.float32)
        if n_miss:
            order = np.argsort(m_shard, kind="stable")
            starts = np.searchsorted(m_shard[order], np.arange(sp))
            pos = np.arange(n_miss) - starts[m_shard[order]]
            init_idx[m_shard[order], pos] = m_local[order]
            init_vals[m_shard[order], pos] = init[order]
        req_d = jax.device_put(
            jnp.asarray(req.reshape(sp, s * cap)), self._sharding)
        place_d = jax.device_put(
            jnp.asarray(place.reshape(sp, s * cap)), self._sharding)
        init_idx_d = jax.device_put(jnp.asarray(init_idx), self._sharding)
        init_vals_d = jax.device_put(
            jnp.asarray(init_vals.reshape(sp * cap_m, w)), self._sharding)
        return _gather_fn_sharded(self.mesh, self.axis, s, cap,
                                  self._widths, rps, self._cap)(
            self._compute_parts(), req_d, place_d, init_idx_d,
            init_vals_d)

    def _scatter_pass_locked(self, block_vals: jax.Array, rows: np.ndarray,
                             n: int, rps: int, sp: int) -> Tuple:
        """Returns the new parts tuple (persistent placement)."""
        s = self.num_shards
        if s == 1 and sp == 1:
            idx = np.full((rps,), s * (self._cap + 1) - 1, np.int64)
            idx[:n] = self._dev_idx(rows)
            return self._settle_parts(_scatter_fn_local(
                self._widths, rps)(
                self._compute_parts(), block_vals,
                jnp.asarray(idx, jnp.int32)))
        if s != sp:
            raise ValueError("pass shards must equal store shards")
        slot, local, _, cap = self._bucket_exact(rows, n, rps, sp)
        src = np.where(local >= 0, local, rps).astype(np.int32)
        dst = np.where(slot >= 0, slot, self._cap).astype(np.int32)
        src_d = jax.device_put(
            jnp.asarray(src.reshape(sp, s * cap)), self._sharding)
        dst_d = jax.device_put(
            jnp.asarray(dst.reshape(sp, s * cap)), self._sharding)
        return self._settle_parts(_scatter_fn_sharded(
            self.mesh, self.axis, s, cap, self._widths)(
            self._compute_parts(), block_vals, src_d, dst_d))

    # -- FeatureStore-compatible host-dict surface -------------------------

    def contains(self, keys: np.ndarray) -> np.ndarray:
        return self._index.lookup(
            np.ascontiguousarray(keys, np.uint64)) >= 0

    def dirty_keys(self) -> np.ndarray:
        with self._lock:
            return self._dirty_compact_locked().copy()

    def _dirty_compact_locked(self) -> np.ndarray:
        if len(self._dirty_parts) > 1:
            # np.unique, not dedup_keys: key 0 is a legal dirty key here
            # (dedup_keys drops the null feasign by design).
            self._dirty_parts = [np.unique(
                np.concatenate(self._dirty_parts))]
        return (self._dirty_parts[0] if self._dirty_parts
                else np.empty((0,), np.uint64))

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        """Host-dict compat path (tools, tier interop, tests). Values
        cross to the host — per-pass training uses pull_pass_table.
        Read-only, like the host FeatureStore contract: unseen keys are
        served their deterministic init WITHOUT being inserted (only a
        push persists them)."""
        with self._lock:
            table, _ = self._pull_pass_table_locked(pass_keys_sorted,
                                                    self.num_shards,
                                                    readonly=True)
        return extract_pass_values_host(table, pass_keys_sorted.shape[0])

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray]) -> None:
        """Host-dict compat write path (delta load, tools)."""
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        if k.shape[0] == 0:
            return
        self._check_state_widths(values)
        with self._lock:
            rows = self._ensure_rows_locked(k)
            n = k.shape[0]
            s = self.num_shards
            rps = plan_shards(n, s)
            laid = self._place(jnp.asarray(
                lay_fused_host(fuse_values_host(values), s, rps)))
            self._parts = self._scatter_pass_locked(laid, rows, n, rps, s)
            self._dirty_parts.append(k.copy())
            self._unseen[rows] = 0

    def key_stats(self) -> Tuple[np.ndarray, np.ndarray]:
        with self._lock:
            keys = self._index.keys_by_row()
            show = self._fetch_column_locked(self.dim + 1, keys.shape[0])
        return keys, show

    def rows_by_coldness(self) -> np.ndarray:
        keys, show = self.key_stats()
        return keys[np.argsort(show, kind="stable")]

    def _fetch_column_locked(self, col: int, n: int) -> np.ndarray:
        """D2H one column for dense rows [0, n) (row order)."""
        if n == 0:
            return np.empty((0,), np.float32)
        s = self.num_shards
        cap1 = self._cap + 1
        host = np.asarray(
            jax.jit(lambda v: v[:, col])(self._parts[0])).reshape(s, cap1)
        rows = np.arange(n)
        return host[rows % s, rows // s]

    # -- maintenance / checkpoint ------------------------------------------

    def unseen_for(self, keys: np.ndarray) -> np.ndarray:
        """Unseen-days ages aligned to ``keys`` (0 where absent)."""
        k = np.ascontiguousarray(keys, np.uint64)
        with self._lock:
            rows = self._index.lookup(k)
            out = np.zeros(k.shape, np.int32)
            found = rows >= 0
            out[found] = self._unseen[rows[found]]
        return out

    def shrink(self, *, min_show: float = 0.0) -> int:
        """Day-boundary lifecycle on the HBM tier: ONE jitted scale over
        the fused record decays show/click in place, unseen_days bump +
        TTL/min-show eviction compact the store (role of ShrinkTable).
        Policy comes from :func:`lifecycle.shrink_params` like every
        other store variant."""
        decay, ttl, min_show = lifecycle.shrink_params(self.config,
                                                       min_show)
        with self._lock:
            self._shrunk_since_base = True
            self._parts = (self._place(_decay_fn(
                self.dim, float(decay))(self._parts[0])),
                ) + self._parts[1:]
            self._unseen += 1
            if min_show <= 0 and ttl <= 0:
                return 0
            n = self._index.size
            keep = np.ones((n,), bool)
            if min_show > 0:
                show = self._fetch_column_locked(self.dim + 1, n)
                keep &= show >= min_show
            if ttl > 0:
                over = self._unseen[:n] > ttl
                monitor.add("store/ttl_evicted", int((keep & over).sum()))
                keep &= ~over
            evicted = int((~keep).sum())
            if evicted:
                self._compact_locked(np.flatnonzero(keep))
            return evicted

    def _compact_locked(self, keep_rows: np.ndarray) -> None:
        """Rebuild with only keep_rows (ascending dense row ids)."""
        keys = self._index.keys_by_row()[keep_rows]
        # keep_rows is ascending and upsert below reassigns dense ids
        # 0..n-1 in that same order, so the age array just filters.
        ages = self._unseen[keep_rows]
        n = keys.shape[0]
        s = self.num_shards
        rps = plan_shards(max(n, 1), s)
        survivors = self._gather_pass_locked(keep_rows, n, rps, s)
        self._index.close()
        self._index = native_store.KeyIndex()
        self._index.reserve(n)
        self._cap = _pow2(max(1 << 10, -(-max(n, 1) // s)))
        self._parts = self._place_parts(tuple(
            jnp.zeros((s * (self._cap + 1), w), jnp.float32)
            for w in self._widths))
        self._unseen = ages
        if n:
            rows2, n_new = self._index.upsert(keys)
            assert n_new == n
            # Rows are fresh appends 0..n-1; values come from the gathered
            # block, not init — scatter them in directly.
            self._parts = self._scatter_pass_locked(survivors, rows2, n,
                                                    rps, s)
        log.vlog(0, "device store compacted: %d rows kept", n)

    def _snapshot_sorted_locked(self, keys_sorted: np.ndarray
                                ) -> Dict[str, np.ndarray]:
        table, _ = self._pull_pass_table_locked(keys_sorted,
                                                self.num_shards,
                                                readonly=True)
        return extract_pass_values_host(table, keys_sorted.shape[0])

    def _empty_vals(self) -> Dict[str, np.ndarray]:
        d = self.dim
        return {"emb": np.empty((0, d), np.float32),
                "emb_state": np.empty((0, self.ke), np.float32),
                "w": np.empty((0,), np.float32),
                "w_state": np.empty((0, self.kw), np.float32),
                "show": np.empty((0,), np.float32),
                "click": np.empty((0,), np.float32)}

    def reset(self) -> None:
        """Drop everything (pass-retry rollback — see FeatureStore.reset):
        fresh key index, zeroed HBM block, clean delta set."""
        self.set_all(np.empty((0,), np.uint64), self._empty_vals())

    def _save_arrays(self, path: str, keys, vals, kind: str,
                     unseen=None) -> None:
        os.makedirs(path, exist_ok=True)
        final = os.path.join(path, f"{self.config.name}.{kind}.npz")
        tmp = os.path.join(path, f".{self.config.name}.{kind}.tmp")
        with open(tmp, "wb") as f:
            np.savez_compressed(f, keys=keys, **vals)
        os.replace(tmp, final)
        if unseen is not None:
            # Unseen-days TTL sidecar aligned to the npz's key order —
            # same format as FeatureStore's (ONLINE.md), so the six
            # store variants' checkpoints stay mutually loadable.
            ages_final = os.path.join(
                path, f"{self.config.name}.{kind}.ages.npz")
            ages_tmp = os.path.join(
                path, f".{self.config.name}.{kind}.ages.tmp")
            with open(ages_tmp, "wb") as f:
                np.savez_compressed(
                    f, unseen=np.ascontiguousarray(unseen, np.int32))
            os.replace(ages_tmp, ages_final)
        meta = {"kind": kind, "num_features": int(keys.shape[0]),
                "dim": self.config.dim, "table": self.config.name}
        with open(os.path.join(path,
                               f"{self.config.name}.{kind}.meta.json"),
                  "w") as f:
            json.dump(meta, f)

    def _ages_for_locked(self, keys: np.ndarray) -> np.ndarray:
        rows = self._index.lookup(keys)
        out = np.zeros(keys.shape, np.int32)
        found = rows >= 0
        out[found] = self._unseen[rows[found]]
        return out

    def save_base(self, path: str) -> None:
        with self._lock:
            keys = np.sort(self._index.keys_by_row())
            vals = (self._snapshot_sorted_locked(keys) if keys.size
                    else self._empty_vals())
            unseen = self._ages_for_locked(keys)
            self._dirty_parts = []
            self._shrunk_since_base = False
        self._save_arrays(path, keys, vals, "base", unseen=unseen)
        log.vlog(0, "device store save_base: %d features -> %s",
                 keys.shape[0], path)

    def save_delta(self, path: str) -> None:
        with self._lock:
            if self._shrunk_since_base:
                raise RuntimeError(
                    "save_delta after shrink(): decay/eviction cannot be "
                    "expressed as a delta — save_base first (the "
                    "reference's day boundary does the same: shrink, then "
                    "base dump)")
            dirty = self._dirty_compact_locked()
            present = self._index.lookup(dirty) >= 0
            dirty = dirty[present]
            vals = (self._snapshot_sorted_locked(dirty) if dirty.size
                    else self._empty_vals())
            unseen = self._ages_for_locked(dirty)
        self._save_arrays(path, dirty, vals, "delta", unseen=unseen)
        log.vlog(0, "device store save_delta: %d features -> %s",
                 dirty.shape[0], path)

    def save_xbox(self, path: str) -> int:
        from paddlebox_tpu.embedding.store import quantize_xbox_vals
        with self._lock:
            keys = np.sort(self._index.keys_by_row())
            vals = (self._snapshot_sorted_locked(keys) if keys.size
                    else self._empty_vals())
        self._save_arrays(path, keys,
                          quantize_xbox_vals({"emb": vals["emb"],
                                              "w": vals["w"]}), "xbox")
        log.vlog(0, "device store save_xbox: %d features -> %s",
                 keys.shape[0], path)
        return int(keys.shape[0])

    def _check_state_widths(self, vals: Dict[str, np.ndarray]) -> None:
        for f, want in (("emb_state", self.ke), ("w_state", self.kw)):
            got = vals[f].shape[-1] if vals[f].ndim > 1 else 1
            if got != want:
                raise ValueError(
                    f"{f} width {got} != {want} expected by optimizer "
                    f"{self.config.optimizer!r} — checkpoint/table was "
                    f"written with a different sparse optimizer")

    def set_all(self, keys_sorted: np.ndarray,
                vals: Dict[str, np.ndarray]) -> None:
        """Replace contents (base-load semantics: delta cleared, shrink
        guard reset). Keys must be sorted unique."""
        self._check_state_widths(vals)
        with self._lock:
            s = self.num_shards
            n = int(keys_sorted.shape[0])
            self._index.close()
            self._index = native_store.KeyIndex()
            self._index.reserve(n)
            self._cap = _pow2(max(1 << 10, -(-max(n, 1) // s)))
            self._parts = self._place_parts(tuple(
                jnp.zeros((s * (self._cap + 1), w), jnp.float32)
                for w in self._widths))
            self._dirty_parts = []
            self._shrunk_since_base = False
            self._unseen = np.zeros((n,), np.int32)
            if n == 0:
                return
            rows, _ = self._index.upsert(
                np.ascontiguousarray(keys_sorted, np.uint64))
            rps = plan_shards(n, s)
            laid = self._place(jnp.asarray(
                lay_fused_host(fuse_values_host(vals), s, rps)))
            self._parts = self._scatter_pass_locked(laid, rows, n, rps, s)

    def load(self, path: str, kind: str = "base") -> None:
        data = np.load(os.path.join(path,
                                    f"{self.config.name}.{kind}.npz"))
        keys = data["keys"].astype(np.uint64)
        vals = {f: data[f] for f in _FIELDS if f in data}
        if kind == "base":
            self.set_all(keys, vals)
        else:
            self._check_state_widths(vals)
            self.push_from_pass(keys, vals)
        # Restore the unseen-days TTL sidecar (when present — see
        # FeatureStore.load): the push/set path above reset the loaded
        # keys' ages, which is correct only for genuinely-new training
        # writes, not a restart reload.
        ages_f = os.path.join(path,
                              f"{self.config.name}.{kind}.ages.npz")
        if os.path.exists(ages_f):
            ages = np.load(ages_f)["unseen"].astype(np.int32)
            if ages.shape[0] == keys.shape[0]:
                with self._lock:
                    rows = self._index.lookup(keys)
                    found = rows >= 0
                    self._unseen[rows[found]] = ages[found]
            else:
                log.warning("ages sidecar %s has %d rows, checkpoint "
                            "has %d — ignoring it", ages_f,
                            ages.shape[0], keys.shape[0])
