"""Dim-grouped embedding engine: per-slot embedding widths (dynamic mf).

Role of the reference's dynamic-mf support: ``CtrDymfAccessor``
(``paddle/fluid/distributed/ps/table/ctr_dymf_accessor.h``) and the
per-feature ``mf_dim`` carried in the HBM value record
(``heter_ps/feature_value.h:44-120``) let production CTR models mix
8/16/64-wide slots in one model.

TPU-first design: instead of a variable-width value record (which would
force dynamic shapes or per-row masks on device), slots are grouped by
embedding width and each width group gets its OWN :class:`PassEngine` —
a fixed-width PassTable, store, and pull/push all-to-all. The train step
runs one fused pull per group (G collectives instead of 1; G is tiny —
production models use 2-3 distinct widths), and every array stays
static-shape and mask-free. Keys are grouped by the slot they arrive
through; a feasign appearing in slots of two different widths trains an
independent row per group (same contract as the reference, where a
feature's mf_dim is fixed by its slot).

Checkpoint layout: ``<path>/dimD/`` per group, each a normal
base/delta/xbox store dump, so group checkpoints compose with the
done-file protocol unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import timers
from paddlebox_tpu.embedding.pass_engine import PassEngine
from paddlebox_tpu.embedding.table import PassTable, TableConfig


@dataclasses.dataclass
class DimGroup:
    """One width group: its dim, member slots (feed order), and engine."""

    dim: int
    slots: Tuple[str, ...]
    engine: PassEngine


class GroupedStore:
    """FeatureStore-shaped facade over the per-group stores so day-level
    maintenance (save/load/shrink) from DayRunner works unchanged."""

    def __init__(self, groups: Sequence[DimGroup]):
        self._groups = list(groups)
        # Shared iff every member store is shared (mixing shared and
        # per-rank tiers across groups is a config error).
        shared_flags = {getattr(g.engine.store, "shared", False)
                        for g in self._groups}
        if len(shared_flags) > 1:
            raise ValueError("all dim-group stores must agree on 'shared'")
        self.shared = shared_flags.pop() if shared_flags else False

    def _subdir(self, path: str, g: DimGroup) -> str:
        # Single-width models keep the flat layout (compatible with
        # pre-dynamic-mf checkpoints); mixed widths get dimD/ subdirs.
        if len(self._groups) == 1:
            return path
        return os.path.join(path, f"dim{g.dim}")

    def __getattr__(self, name: str):
        # Single-width models: full pass-through to the one member store
        # (dirty_keys, pull_for_pass, xbox export, tier internals — the
        # whole FeatureStore surface, unchanged from pre-dynamic-mf).
        groups = object.__getattribute__(self, "_groups")
        if len(groups) == 1:
            return getattr(groups[0].engine.store, name)
        # Mixed widths: forward optional capabilities (e.g. save_xbox)
        # only when EVERY member store provides them, so hasattr() checks
        # by callers (DayRunner's xbox export gate) stay truthful.
        if name == "save_xbox":
            members = [g.engine.store for g in groups]
            if all(hasattr(m, "save_xbox") for m in members):
                def save_xbox(path: str) -> int:
                    return sum(m.save_xbox(self._subdir(path, g))
                               for m, g in zip(members, groups))
                return save_xbox
        if name == "reset":
            # Pass-retry rollback: forwarded only when EVERY member can
            # reset, so hasattr(store, "reset") stays truthful.
            members = [g.engine.store for g in groups]
            if all(hasattr(m, "reset") for m in members):
                def reset() -> None:
                    for m in members:
                        m.reset()
                return reset
        raise AttributeError(name)

    def save_base(self, path: str) -> None:
        for g in self._groups:
            g.engine.store.save_base(self._subdir(path, g))

    def save_delta(self, path: str) -> None:
        for g in self._groups:
            g.engine.store.save_delta(self._subdir(path, g))

    def load(self, path: str, kind: str = "base") -> None:
        for g in self._groups:
            g.engine.store.load(self._subdir(path, g), kind)

    def shrink(self, *, min_show: float = 0.0) -> int:
        # Day-boundary lifecycle (FLAGS_table_* decay/TTL/min-show)
        # resolves inside each member store's shrink — a feasign trains
        # an independent row per width group, so its age is per-group
        # too (a key hot in the 8-wide slots can expire in the 64-wide
        # ones, exactly like two distinct features would).
        return sum(g.engine.store.shrink(min_show=min_show)
                   for g in self._groups)

    @property
    def num_features(self) -> int:
        return sum(g.engine.store.num_features for g in self._groups)


class GroupedEngine:
    """Pass lifecycle across width groups — same surface as PassEngine but
    tables/rows are per-group tuples (ordered by ascending dim)."""

    def __init__(self, base_config: TableConfig, slot_dims: Dict[str, int],
                 *, mesh=None, table_axis: str = "dp",
                 store_factory: Optional[Callable[[TableConfig], object]] = None):
        if not slot_dims:
            raise ValueError("slot_dims is empty")
        dims = sorted(set(slot_dims.values()))
        self.groups: List[DimGroup] = []
        for d in dims:
            slots = tuple(s for s, sd in slot_dims.items() if sd == d)
            # Single-width models keep the base table name (and, via
            # GroupedStore, the flat checkpoint layout) — fully compatible
            # with pre-dynamic-mf artifacts.
            name = (base_config.name if len(dims) == 1
                    else f"{base_config.name}_dim{d}")
            cfg = dataclasses.replace(base_config, dim=d, name=name)
            store = store_factory(cfg) if store_factory is not None else None
            eng = PassEngine(cfg, store, mesh=mesh, table_axis=table_axis)
            self.groups.append(DimGroup(dim=d, slots=slots, engine=eng))
        self.store = GroupedStore(self.groups)
        self.timers = timers.TimerGroup()
        self.num_shards = self.groups[0].engine.num_shards

    @property
    def dims(self) -> List[int]:
        return [g.dim for g in self.groups]

    def group_of_slot(self, slot: str) -> int:
        """Index into self.groups for a slot name."""
        for i, g in enumerate(self.groups):
            if slot in g.slots:
                return i
        raise KeyError(slot)

    # -- pass lifecycle (tuple-valued twins of PassEngine's surface) -------

    def feed_pass(self, keys_by_group: Sequence[np.ndarray], *,
                  async_build: bool = False, readonly: bool = False) -> None:
        if len(keys_by_group) != len(self.groups):
            raise ValueError(
                f"expected {len(self.groups)} key sets, got "
                f"{len(keys_by_group)}")
        with self.timers.scope("feed_pass"):
            for g, keys in zip(self.groups, keys_by_group):
                g.engine.feed_pass(keys, async_build=async_build,
                                   readonly=readonly)

    def wait_feed_pass_done(self) -> None:
        for g in self.groups:
            g.engine.wait_feed_pass_done()

    def begin_pass(self) -> Tuple[PassTable, ...]:
        return tuple(g.engine.begin_pass() for g in self.groups)

    @property
    def tables(self) -> Tuple[PassTable, ...]:
        return tuple(g.engine.table for g in self.groups)

    def update_tables(self, tables: Sequence[PassTable]) -> None:
        for g, t in zip(self.groups, tables):
            g.engine.update_table(t)

    def lookup_rows(self, group_index: int, batch_keys: np.ndarray
                    ) -> np.ndarray:
        return self.groups[group_index].engine.lookup_rows(batch_keys)

    def end_pass(self) -> None:
        with self.timers.scope("end_pass"):
            for g in self.groups:
                g.engine.end_pass()

    def abort_pass(self) -> None:
        """Drop the active pass without write-back (eval/test mode)."""
        for g in self.groups:
            g.engine.abort_pass()

    def abort_if_active(self) -> None:
        """Drop any active pass, no-op otherwise (retry rollback)."""
        for g in self.groups:
            g.engine.abort_if_active()

    def cancel_pending(self) -> None:
        for g in self.groups:
            g.engine.cancel_pending()

    def boundary_ms(self) -> Dict[str, float]:
        """Cumulative pass-boundary stage ms summed across width groups
        (PassEngine.boundary_ms schema)."""
        out: Dict[str, float] = {}
        for g in self.groups:
            for k, v in g.engine.boundary_ms().items():
                out[k] = out.get(k, 0.0) + v
        return out
