"""Per-pass device table: ONE fused sharded value array + index math.

Role of the HeterPS HBM structures: the per-GPU hashtable + mem_pool value
slabs (``heter_ps/hashtable.h``, ``mem_pool.h``) and the
``CommonFeatureValue`` record layout (``heter_ps/feature_value.h:44-120``:
show, click, embed_w(lr), embed_g2sum, embedx_w[mf], embedx_g2sum).

TPU-first: because the pass key set is pre-registered (pass-based design),
the device table needs NO hashtable — rows are assigned by sorted-key rank,
dealt ROUND-ROBIN across shards (rank g -> shard g % S, slot g // S). The
round-robin deal is load-bearing: ``plan_shards`` rounds rows_per_shard up
to a power of two for compile stability, and a contiguous split would then
leave the tail shards empty (a 20K-key pass over 8 shards of 4096 rows
puts everything in shards 0-4), concentrating the pull/push all-to-all on
a subset of links and overflowing their fixed-capacity buckets — the
reference gets the same balance by hashing keys to shards
(``key % shard_num``, heter_comm_inl.h:267). Each shard carries one extra
trash row (index ``rows_per_shard``) that absorbs padding lookups and
padding grads, so every kernel is mask-free and static-shape.

All per-row fields live in ONE ``[rows, W]`` float32 array (the
CommonFeatureValue packing) so the hot path is a single gather per pull and
a single scatter per push — XLA scatter/gather on TPU pays a fixed cost
per *op*, and the r02 six-arrays layout paid it six times per step
(measured: ~50 ms per 426K-row scatter; see tools/profile_step.py).

Column layout (D = emb dim, Ke/Kw = optimizer state widths):

    [ emb(D) | w | show | click | emb_state(Ke) | w_state(Kw) ]
      `--------- pull payload = [:, :D+3] (one contiguous slice) ---'

Index math (device-side, int32):
  global row g of key k  = rank of k in the sorted pass key set (host)
  shard(g)               = g %  num_shards
  row_in_shard(g)        = g // num_shards
  padding sentinel       = trash row of shard (i % S)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """Sparse table hyper-params (role of the accessor/optimizer config in
    the_one_ps.proto + optimizer_conf.h)."""

    name: str = "embedding"
    dim: int = 8                  # mf embedding width (embedx_dim)
    num_shards: int = 1           # table shards == size of the shard mesh axis
    # Initialization (role of CtrCommonAccessor init ranges).
    init_scale: float = 0.01
    # Sparse optimizer selection + hyper-params (role of optimizer_conf.h
    # bounds/decay and HeterPs optimizer_type dispatch).
    optimizer: str = "adagrad"    # adagrad | adam | adam_shared | ftrl
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    beta1: float = 0.9
    beta2: float = 0.999
    # FTRL-proximal knobs (optimizer="ftrl"; role of ftrl_op.cc attrs).
    ftrl_l1: float = 0.1
    ftrl_l2: float = 1.0
    ftrl_beta: float = 1.0
    min_bound: float = -10.0
    max_bound: float = 10.0
    # Show/click decay applied at end-of-day shrink (role of ShrinkTable).
    show_click_decay: float = 0.98

    @property
    def w_width(self) -> int:
        """Scalar LR weight + its g2sum (wide/linear term)."""
        return 2


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PassTable:
    """Device-resident per-pass table (a one-leaf pytree).

    ``vals [S*(R+1), W]`` — fused per-row record (module docstring layout);
    shard s owns rows [s*(R+1), (s+1)*(R+1)), the last row of each shard
    block being its trash row. Under shard_map the leading dim is sharded
    over the table axis so each device holds exactly its [(R+1), W] block.
    """

    vals: jax.Array
    rows_per_shard: int            # real rows (excludes trash row)
    num_shards: int
    dim: int
    ke: int                        # emb_state width
    kw: int                        # w_state width

    def tree_flatten(self):
        return (self.vals,), (self.rows_per_shard, self.num_shards,
                              self.dim, self.ke, self.kw)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        rows_per_shard, num_shards, dim, ke, kw = aux
        return cls(leaves[0], rows_per_shard=rows_per_shard,
                   num_shards=num_shards, dim=dim, ke=ke, kw=kw)

    # -- column views (read-only slices of the fused record) ---------------

    @property
    def pull_width(self) -> int:
        return self.dim + 3

    @property
    def width(self) -> int:
        return self.dim + 3 + self.ke + self.kw

    @property
    def emb(self) -> jax.Array:
        return self.vals[:, :self.dim]

    @property
    def w(self) -> jax.Array:
        return self.vals[:, self.dim]

    @property
    def show(self) -> jax.Array:
        return self.vals[:, self.dim + 1]

    @property
    def click(self) -> jax.Array:
        return self.vals[:, self.dim + 2]

    @property
    def emb_state(self) -> jax.Array:
        return self.vals[:, self.dim + 3:self.dim + 3 + self.ke]

    @property
    def w_state(self) -> jax.Array:
        return self.vals[:, self.dim + 3 + self.ke:]

    @property
    def num_rows_padded(self) -> int:
        return self.num_shards * (self.rows_per_shard + 1)

    def with_emb(self, emb: jax.Array) -> "PassTable":
        """Copy with the emb columns replaced (test/tooling helper)."""
        return dataclasses.replace(
            self, vals=self.vals.at[:, :self.dim].set(emb))


def table_widths(config: TableConfig) -> Tuple[int, int, int]:
    """(dim, ke, kw) for a config's optimizer."""
    from paddlebox_tpu.embedding.optimizers import make_sparse_optimizer
    opt = make_sparse_optimizer(config)
    return config.dim, opt.emb_state_width(config.dim), opt.w_state_width()


def plan_shards(num_keys: int, num_shards: int,
                round_pow2: Optional[bool] = None) -> int:
    """Rows per shard covering num_keys.

    By default rounds up to a power of two (``pass_table_pow2_rows``
    flag): the jitted train step's shapes depend on the table's leading
    dim, so WITHOUT rounding every pass with a new key count would
    recompile (~tens of seconds); with it, steady-state online passes hit
    the same size bucket and reuse the compiled program. Row alignment
    beyond that is unnecessary — gathers index the row dim; only the
    trailing feature dim needs TPU tiling."""
    from paddlebox_tpu.core import flags
    rps = -(-max(num_keys, 1) // num_shards)
    if round_pow2 is None:
        round_pow2 = bool(flags.flag("pass_table_pow2_rows"))
    if round_pow2:
        rps = 1 << (rps - 1).bit_length()
    return rps


def fuse_values_host(values: Dict[str, np.ndarray]) -> np.ndarray:
    """Pack the store's per-field host arrays into the fused [n, W] record
    (column layout per module docstring)."""
    n = values["emb"].shape[0]
    cols = [values["emb"],
            values["w"].reshape(n, 1),
            values["show"].reshape(n, 1),
            values["click"].reshape(n, 1),
            values["emb_state"],
            values["w_state"]]
    return np.concatenate([np.asarray(c, np.float32) for c in cols], axis=1)


def split_values_host(fused: np.ndarray, dim: int, ke: int, kw: int
                      ) -> Dict[str, np.ndarray]:
    """Inverse of fuse_values_host."""
    return {
        "emb": fused[:, :dim].copy(),
        "w": fused[:, dim].copy(),
        "show": fused[:, dim + 1].copy(),
        "click": fused[:, dim + 2].copy(),
        "emb_state": fused[:, dim + 3:dim + 3 + ke].copy(),
        "w_state": fused[:, dim + 3 + ke:dim + 3 + ke + kw].copy(),
    }


def lay_fused_host(fused: np.ndarray, num_shards: int, rps: int
                   ) -> np.ndarray:
    """[n, W] sorted-rank rows → round-robin sharded [S*(rps+1), W] with a
    zeroed trash row per shard (role of BuildGPUTask filling HBM mem-pool
    records, ps_gpu_wrapper.cc:684): rank g lands in shard g % S at slot
    g // S, so every shard holds ~n/S rows for ANY n (module docstring)."""
    n, w = fused.shape
    out = np.zeros((num_shards, rps + 1, w), np.float32)
    for s in range(num_shards):
        part = fused[s::num_shards]
        out[s, :part.shape[0]] = part
    return out.reshape(num_shards * (rps + 1), w)


def unlay_fused_host(laid: np.ndarray, num_shards: int, rps: int,
                     num_keys: int) -> np.ndarray:
    """Inverse of lay_fused_host: strip trash rows, back to sorted-rank
    order."""
    a = laid.reshape(num_shards, rps + 1, laid.shape[-1])[:, :rps]
    out = np.empty((num_keys, laid.shape[-1]), laid.dtype)
    for s in range(num_shards):
        cnt = len(range(s, num_keys, num_shards))
        out[s::num_shards] = a[s, :cnt]
    return out


def build_pass_table_host(values: Dict[str, np.ndarray], num_shards: int,
                          config: TableConfig) -> PassTable:
    """Assemble a PassTable from host arrays produced by the FeatureStore.

    ``values`` carries per-key arrays in sorted-key order: emb [N, D],
    emb_state [N, Ke], w [N], w_state [N, Kw], show [N], click [N]. One
    fused host pack + ONE H2D transfer (vs six in the r02 layout — the
    axon tunnel makes every separate transfer expensive).
    """
    dim, ke, kw = table_widths(config)
    n = values["emb"].shape[0]
    rps = plan_shards(n, num_shards)
    fused = fuse_values_host(values)
    return PassTable(
        vals=jnp.asarray(lay_fused_host(fused, num_shards, rps)),
        rows_per_shard=rps, num_shards=num_shards, dim=dim, ke=ke, kw=kw)


def extract_pass_values_host(table: PassTable, num_keys: int
                             ) -> Dict[str, np.ndarray]:
    """Inverse of build_pass_table_host: ONE D2H transfer, strip trash
    rows, return sorted-key order host arrays (role of EndPass dumping
    dirty HBM values back to the CPU table, ps_gpu_wrapper.cc:983).

    Under a multi-process cluster the table spans hosts; every process
    needs the full values (the host store is a per-rank replica), so the
    extraction is a process allgather there (role of the PS pull in the
    reference's write-back — values cross the host network exactly once
    per pass)."""
    if table.vals.is_fully_addressable:
        laid = np.asarray(table.vals)
    else:
        from jax.experimental import multihost_utils
        laid = np.asarray(
            multihost_utils.process_allgather(table.vals, tiled=True))
    fused = unlay_fused_host(laid, table.num_shards, table.rows_per_shard,
                             num_keys)
    return split_values_host(fused, table.dim, table.ke, table.kw)


def shared_key_mask(active_sorted: np.ndarray,
                    keys_sorted: np.ndarray) -> np.ndarray:
    """Boolean mask over ``keys_sorted``: True where the key is also in
    ``active_sorted`` (both sorted unique). The split pass build keys off
    this: the active pass's end_pass writes back ONLY its own keys, so
    the False positions can be pulled/gathered while it still trains."""
    if active_sorted.size == 0 or keys_sorted.size == 0:
        return np.zeros(keys_sorted.shape, bool)
    pos = np.minimum(np.searchsorted(active_sorted, keys_sorted),
                     active_sorted.size - 1)
    return active_sorted[pos] == keys_sorted


def map_keys_to_rows(pass_keys_sorted: np.ndarray, batch_keys: np.ndarray,
                     rows_per_shard: int, num_shards: int = 1,
                     index_offset: int = 0) -> np.ndarray:
    """Host-side: feasigns → device row ids in the ROUND-ROBIN sharded
    layout (rank g -> shard g % num_shards at slot g // num_shards —
    module docstring).

    Role of the key→slot flattening in CopyKeys + the per-pass perfect
    index (SURVEY.md §7 design note). Unknown keys and the 0 padding
    feasign map to trash rows, spread round-robin across ALL shards —
    padding concentrated on one shard would overflow its fixed-capacity
    all-to-all bucket and silently drop that shard's real lookups.

    ``index_offset``: global position of ``batch_keys[0]`` when the
    caller shards one big batch across lookup workers — the round-robin
    trash assignment depends on the GLOBAL position, so a chunked lookup
    must stay bit-identical to the unchunked one.
    """
    n = pass_keys_sorted.shape[0]
    m = batch_keys.shape[0]
    # Round-robin trash row per position: shard (i % S)'s trash row.
    pad_shard = (np.arange(m, dtype=np.int64)
                 + int(index_offset)) % num_shards
    sentinel = (pad_shard * (rows_per_shard + 1) + rows_per_shard
                ).astype(np.int32)
    if n == 0:
        return sentinel  # empty pass: everything hits a trash row
    g = np.searchsorted(pass_keys_sorted, batch_keys)
    g_c = np.minimum(g, n - 1)
    found = (pass_keys_sorted[g_c] == batch_keys) & (batch_keys != 0)
    shard = g_c % num_shards
    row = g_c // num_shards
    dev_row = shard * (rows_per_shard + 1) + row
    return np.where(found, dev_row, sentinel).astype(np.int32)
