"""Per-pass device table: contiguous sharded arrays + index math.

Role of the HeterPS HBM structures: the per-GPU hashtable + mem_pool value
slabs (``heter_ps/hashtable.h``, ``mem_pool.h``) and the
``CommonFeatureValue`` record layout (``heter_ps/feature_value.h:44-120``:
show, click, embed_w(lr), embed_g2sum, embedx_w[mf], embedx_g2sum).

TPU-first: because the pass key set is pre-registered (pass-based design),
the device table needs NO hashtable — rows are assigned by sorted-key rank,
split contiguously across shards. Each shard carries one extra trash row
(index ``rows_per_shard``) that absorbs padding lookups and padding grads,
so every kernel is mask-free and static-shape.

Index math (device-side, int32):
  global row g of key k  = rank of k in the sorted pass key set (host)
  shard(g)               = g // rows_per_shard
  row_in_shard(g)        = g %  rows_per_shard
  padding sentinel       = N_pad (maps to trash row of shard 0)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """Sparse table hyper-params (role of the accessor/optimizer config in
    the_one_ps.proto + optimizer_conf.h)."""

    name: str = "embedding"
    dim: int = 8                  # mf embedding width (embedx_dim)
    num_shards: int = 1           # table shards == size of the shard mesh axis
    # Initialization (role of CtrCommonAccessor init ranges).
    init_scale: float = 0.01
    # Sparse optimizer selection + hyper-params (role of optimizer_conf.h
    # bounds/decay and HeterPs optimizer_type dispatch).
    optimizer: str = "adagrad"    # adagrad | adam | adam_shared
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    beta1: float = 0.9
    beta2: float = 0.999
    min_bound: float = -10.0
    max_bound: float = 10.0
    # Show/click decay applied at end-of-day shrink (role of ShrinkTable).
    show_click_decay: float = 0.98

    @property
    def w_width(self) -> int:
        """Scalar LR weight + its g2sum (wide/linear term)."""
        return 2


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PassTable:
    """Device-resident per-pass table (a pytree of sharded arrays).

    Shapes (S = num_shards, R = rows_per_shard real rows, +1 trash row):
      emb       [S*(R+1), D]   mf embedding
      emb_state [S*(R+1), Ke]  optimizer state for emb (layout per optimizer:
                               adagrad [g2sum]; adam [m1,m2,b1pow,b2pow] —
                               the CommonFeatureValue packing,
                               feature_value.h:44 / optimizer.cuh.h:306)
      w         [S*(R+1)]      scalar LR weight (wide term)
      w_state   [S*(R+1), Kw]
      show      [S*(R+1)]      impression count
      click     [S*(R+1)]      click count

    Stored flat with shard s owning rows [s*(R+1), (s+1)*(R+1)); when used
    under shard_map the leading dim is sharded over the table axis so each
    device holds exactly its own [(R+1), ...] block.
    """

    emb: jax.Array
    emb_state: jax.Array
    w: jax.Array
    w_state: jax.Array
    show: jax.Array
    click: jax.Array
    rows_per_shard: int            # real rows (excludes trash row)
    num_shards: int

    def tree_flatten(self):
        leaves = (self.emb, self.emb_state, self.w, self.w_state,
                  self.show, self.click)
        return leaves, (self.rows_per_shard, self.num_shards)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        rows_per_shard, num_shards = aux
        return cls(*leaves, rows_per_shard=rows_per_shard,
                   num_shards=num_shards)

    @property
    def num_rows_padded(self) -> int:
        return self.num_shards * (self.rows_per_shard + 1)

    @property
    def dim(self) -> int:
        return int(self.emb.shape[-1])


def plan_shards(num_keys: int, num_shards: int,
                round_pow2: Optional[bool] = None) -> int:
    """Rows per shard covering num_keys.

    By default rounds up to a power of two (``pass_table_pow2_rows``
    flag): the jitted train step's shapes depend on the table's leading
    dim, so WITHOUT rounding every pass with a new key count would
    recompile (~tens of seconds); with it, steady-state online passes hit
    the same size bucket and reuse the compiled program. Row alignment
    beyond that is unnecessary — gathers index the row dim; only the
    trailing feature dim needs TPU tiling."""
    from paddlebox_tpu.core import flags
    rps = -(-max(num_keys, 1) // num_shards)
    if round_pow2 is None:
        round_pow2 = bool(flags.flag("pass_table_pow2_rows"))
    if round_pow2:
        rps = 1 << (rps - 1).bit_length()
    return rps


def build_pass_table_host(values: Dict[str, np.ndarray], num_shards: int,
                          config: TableConfig) -> PassTable:
    """Assemble a PassTable from host arrays produced by the FeatureStore.

    ``values`` carries per-key arrays in sorted-key order: emb [N, D],
    emb_state [N, Ke], w [N], w_state [N, Kw], show [N], click [N]. Rows are laid
    out shard-contiguously with a zeroed trash row appended per shard
    (role of BuildGPUTask filling HBM mem-pool records,
    ps_gpu_wrapper.cc:684).
    """
    n = values["emb"].shape[0]
    rps = plan_shards(n, num_shards)
    d = config.dim

    def lay(flat: np.ndarray, width: Optional[int]) -> np.ndarray:
        shape = (num_shards, rps + 1) + ((width,) if width else ())
        out = np.zeros(shape, flat.dtype)
        src = flat.reshape((n,) + ((width,) if width else ()))
        for s in range(num_shards):
            lo, hi = s * rps, min((s + 1) * rps, n)
            if lo < hi:
                out[s, :hi - lo] = src[lo:hi]
        return out.reshape((num_shards * (rps + 1),) +
                           ((width,) if width else ()))

    return PassTable(
        emb=jnp.asarray(lay(values["emb"], d)),
        emb_state=jnp.asarray(lay(values["emb_state"],
                                  values["emb_state"].shape[1])),
        w=jnp.asarray(lay(values["w"], None)),
        w_state=jnp.asarray(lay(values["w_state"],
                                values["w_state"].shape[1])),
        show=jnp.asarray(lay(values["show"], None)),
        click=jnp.asarray(lay(values["click"], None)),
        rows_per_shard=rps,
        num_shards=num_shards,
    )


def extract_pass_values_host(table: PassTable, num_keys: int) -> Dict[str, np.ndarray]:
    """Inverse of build_pass_table_host: strip trash rows, return sorted-key
    order host arrays (role of EndPass dumping dirty HBM values back to the
    CPU table, ps_gpu_wrapper.cc:983)."""
    rps = table.rows_per_shard
    s = table.num_shards

    def unlay(arr: jax.Array) -> np.ndarray:
        a = np.asarray(arr)
        a = a.reshape((s, rps + 1) + a.shape[1:])[:, :rps]  # drop trash rows
        a = a.reshape((s * rps,) + a.shape[2:])
        return a[:num_keys]

    return {
        "emb": unlay(table.emb),
        "emb_state": unlay(table.emb_state),
        "w": unlay(table.w),
        "w_state": unlay(table.w_state),
        "show": unlay(table.show),
        "click": unlay(table.click),
    }


def map_keys_to_rows(pass_keys_sorted: np.ndarray, batch_keys: np.ndarray,
                     rows_per_shard: int, num_shards: int = 1) -> np.ndarray:
    """Host-side: feasigns → device row ids in the shard-contiguous layout.

    Role of the key→slot flattening in CopyKeys + the per-pass perfect
    index (SURVEY.md §7 design note). Unknown keys and the 0 padding
    feasign map to trash rows, spread round-robin across ALL shards —
    padding concentrated on one shard would overflow its fixed-capacity
    all-to-all bucket and silently drop that shard's real lookups.
    """
    n = pass_keys_sorted.shape[0]
    m = batch_keys.shape[0]
    # Round-robin trash row per position: shard (i % S)'s trash row.
    pad_shard = np.arange(m, dtype=np.int64) % num_shards
    sentinel = (pad_shard * (rows_per_shard + 1) + rows_per_shard
                ).astype(np.int32)
    if n == 0:
        return sentinel  # empty pass: everything hits a trash row
    g = np.searchsorted(pass_keys_sorted, batch_keys)
    g_c = np.minimum(g, n - 1)
    found = (pass_keys_sorted[g_c] == batch_keys) & (batch_keys != 0)
    shard = g_c // rows_per_shard
    row = g_c % rows_per_shard
    dev_row = shard * (rows_per_shard + 1) + row
    return np.where(found, dev_row, sentinel).astype(np.int32)
