"""Pass lifecycle orchestration: feed_pass → begin_pass → train → end_pass.

Role of the BoxWrapper/BoxHelper pass driver (``box_wrapper.h:449-453,
1034-1301``): per-pass key registration (``FeedPass``), staged build of the
device table (``BeginFeedPass``/``EndFeedPass``; HeterPS ``PreBuildTask`` →
``BuildPull`` → ``BuildGPUTask``, ps_gpu_wrapper.cc:114,337,684), training
window between ``BeginPass``/``EndPass``, and write-back on ``EndPass``.

Double-buffering: ``feed_pass`` may run in a background thread while the
previous pass trains (role of PreLoadIntoMemory/WaitFeedPassDone overlap,
box_wrapper.h:1140,1161).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import (faults, flags, log, monitor,
                                pipeline_stats, timers)
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import (PassTable, TableConfig,
                                           build_pass_table_host,
                                           extract_pass_values_host,
                                           map_keys_to_rows,
                                           shared_key_mask)


class PassBuildCancelled(RuntimeError):
    """A pending async build was cancelled (cancel_pending) while it was
    parked waiting for the active pass's boundary."""


class _PendingPass:
    def __init__(self):
        self.keys: Optional[np.ndarray] = None
        self.table: Optional[PassTable] = None
        self.keymap = None
        self.rows: Optional[np.ndarray] = None   # device-store dense rows
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        # Split-build handshake (device tier): the builder publishes its
        # early state and parks; end_pass may consume it into the fused
        # boundary program and hand back the finished table.
        self.early_table: Optional[PassTable] = None
        self.early_shared: Optional[np.ndarray] = None
        self.early_ready = threading.Event()
        self.fused_table: Optional[PassTable] = None
        # Boundary wake-up: set by end_pass/abort_pass (after
        # _no_active_pass) and by cancel_pending, so a parked builder
        # never needs to poll the shared event.
        self.resume = threading.Event()
        self.cancel = threading.Event()


class PassEngine:
    """Owns the FeatureStore + the live per-pass device table."""

    def __init__(self, config: TableConfig, store: Optional[FeatureStore] = None,
                 *, mesh: Optional[Mesh] = None, table_axis: str = "dp"):
        self.config = config
        self.store = store or FeatureStore(config)
        self.mesh = mesh
        self.table_axis = table_axis
        self.num_shards = (
            int(mesh.shape[table_axis]) if mesh is not None else 1)
        self.timers = timers.TimerGroup()

        self._current_keys: Optional[np.ndarray] = None
        self._table: Optional[PassTable] = None
        self._keymap = None
        self._current_rows: Optional[np.ndarray] = None
        self._pending: Optional[_PendingPass] = None
        self._pass_id = -1
        # Sequencing for async builds: the store pull must happen AFTER the
        # previous pass's end_pass write-back, or updates to keys shared
        # between passes would be read stale and then overwritten (the
        # reference sequences BuildPull after EndPass the same way).
        self._no_active_pass = threading.Event()
        self._no_active_pass.set()
        # One pending-build slot: a feed_pass issued while an earlier
        # build is still waiting to be begun (pipelined day loops feeding
        # pass k+1 from a loader thread) blocks until begin_pass consumes
        # the earlier one. A semaphore (not an Event) so concurrent
        # feed_pass callers serialize atomically instead of both passing
        # a wait()+clear() window.
        self._pending_sem = threading.Semaphore(1)

    # -- build -------------------------------------------------------------

    def _build(self, pass_keys: np.ndarray, pending: _PendingPass,
               readonly: bool = False) -> None:
        try:
            faults.faultpoint("pass_engine/build")
            with self.timers.scope("feed_pass"):
                # Key dedup can overlap the active pass... (native
                # multi-threaded dedup, role of PreBuildTask,
                # ps_gpu_wrapper.cc:114; numpy fallback inside). Keys
                # arriving already sorted-unique-nonzero — the sorted-run
                # collector's merge (Dataset.pass_keys, round 13) — skip
                # the redundant re-sort: one O(n) vectorized check
                # replaces an O(n log n) dedup on the build path.
                from paddlebox_tpu.native.keymap_py import KeyMap, dedup_keys
                from paddlebox_tpu.native.store_py import \
                    is_sorted_unique_nonzero
                keys = np.asarray(pass_keys, np.uint64)
                if not is_sorted_unique_nonzero(keys):
                    keys = dedup_keys(keys)
                if hasattr(self.store, "pull_pass_table"):
                    # Device-resident store tier: the build is an on-device
                    # gather — values never cross the host boundary. Only
                    # rows the active pass will write back (its own keys)
                    # must wait for end_pass; everything else — unseen-key
                    # insertion (append region disjoint from the active
                    # rows), the NOT-shared gather, and the keymap build —
                    # overlaps the active pass's training (split-key early
                    # build, role of the overlapped BuildPull threads,
                    # ps_gpu_wrapper.cc:907).
                    active = self._current_keys  # snapshot; sorted or None
                    split = (bool(flags.flag("pass_split_build"))
                             and hasattr(self.store,
                                         "pull_pass_table_partial")
                             and active is not None and active.size
                             and keys.size
                             and not self._no_active_pass.is_set())
                    shared = (shared_key_mask(active, keys) if split
                              else None)
                    if shared is not None:
                        # Even a fully-shared pass goes through the
                        # split path: the early half is then just the
                        # (overlapped) keymap build + a zero-filled
                        # block, but the whole-table remainder gather
                        # can ride the fused boundary program — one
                        # dispatch at the boundary instead of two.
                        table, rows = self.store.pull_pass_table_partial(
                            keys, self.num_shards, select=~shared,
                            readonly=readonly)
                        pending.keys = keys
                        pending.rows = rows
                        # Keymap built during the overlap window; hung on
                        # the pending NOW so every discard path
                        # (cancel_pending, a begin_pass error) closes it.
                        pending.keymap = KeyMap(keys, table.rows_per_shard,
                                                self.num_shards)
                        monitor.add("pass/split_builds", 1)
                        if shared.any():
                            # Publish early state, then park: end_pass
                            # either fuses its scatter with our remainder
                            # gather (ONE dispatch) or just releases us
                            # to merge ourselves.
                            pending.early_table = table
                            pending.early_shared = shared
                            pending.early_ready.set()
                            self._wait_boundary(pending)
                            if pending.fused_table is not None:
                                table = pending.fused_table
                            else:
                                table = self.store.merge_pass_rows(
                                    rows, table, shared)
                        pending.table = table
                        monitor.add("pass/built", 1)
                        return
                    # Serial build (no active pass, all keys shared, or
                    # split disabled): the whole gather observes the
                    # write-back.
                    self._wait_boundary(pending)
                    table, rows = self.store.pull_pass_table(
                        keys, self.num_shards, readonly=readonly)
                    pending.keys = keys
                    pending.table = table
                    pending.rows = rows
                    pending.keymap = KeyMap(keys, table.rows_per_shard,
                                            self.num_shards)
                    monitor.add("pass/built", 1)
                    return
                # Split pull (role of the double-buffered build threads,
                # ps_gpu_wrapper.cc:907): the active pass's end_pass only
                # writes back ITS OWN keys, so values for keys NOT in the
                # active set can be pulled while it trains; only the
                # intersection must wait for write-back. Consecutive
                # online passes typically share a minority of keys, so
                # most of the pull overlaps training.
                active = self._current_keys  # snapshot; sorted or None
                vals = None
                shared = None
                # Multi-host tier: plan-aware partial pulls slice ONE
                # cached owner plan (keyed by this pass's id) instead of
                # re-deriving an argsort per sub-pull, and the key set
                # publishes EARLY so the active pass's end_pass can
                # split its push into the priority slice (rows this
                # pass pulls back at the boundary) + an overlapped bulk
                # remainder on the exchange worker.
                mh = hasattr(self.store, "push_from_pass_async")
                pid = self._pass_id + 1 if mh else None
                if mh:
                    pending.keys = keys
                if (active is not None and active.size and keys.size
                        and not self._no_active_pass.is_set()):
                    shared = shared_key_mask(active, keys)
                    if shared.any() and not shared.all():
                        part = (self.store.pull_for_pass(
                                    keys, ~shared, pass_id=pid) if mh
                                else self.store.pull_for_pass(
                                    keys[~shared]))
                        n = keys.shape[0]
                        vals = {f: np.empty((n,) + v.shape[1:], v.dtype)
                                for f, v in part.items()}
                        for f, v in part.items():
                            vals[f][~shared] = v
                    elif not shared.any():
                        vals = (self.store.pull_for_pass(
                                    keys, pass_id=pid) if mh
                                else self.store.pull_for_pass(keys))
                        shared = None
                self._wait_boundary(pending)
                if vals is None:
                    vals = (self.store.pull_for_pass(keys, pass_id=pid)
                            if mh else self.store.pull_for_pass(keys))
                elif shared is not None:
                    # The ONE coalesced boundary pull: only the shared
                    # remainder waits here. barrier=False is safe — the
                    # shared rows were pushed synchronously as the
                    # priority slice of end_pass's write-back, and any
                    # still-queued bulk push holds only keys NOT in
                    # this pass.
                    part = (self.store.pull_for_pass(
                                keys, shared, pass_id=pid,
                                barrier=False, boundary=True) if mh
                            else self.store.pull_for_pass(keys[shared]))
                    for f, v in part.items():
                        vals[f][shared] = v
                table = build_pass_table_host(
                    vals, self.num_shards, self.config)
                if self.mesh is not None:
                    sharding = NamedSharding(self.mesh, P(self.table_axis))
                    table = jax.tree.map(
                        lambda x: jax.device_put(x, sharding), table)
                pending.keys = keys
                pending.table = table
                pending.keymap = KeyMap(keys, table.rows_per_shard,
                                        self.num_shards)
                monitor.add("pass/built", 1)
        except BaseException as e:  # propagate to the waiting begin_pass
            pending.error = e

    def _wait_boundary(self, pending: _PendingPass) -> None:
        """Park the builder until the active pass releases the store
        (end_pass/abort_pass), the fused boundary already produced our
        table, or the build is cancelled. The normal wake-up is the
        per-pending ``resume`` event (set by the boundary with the
        pending visible — feed_pass publishes ``_pending`` before the
        builder starts); the ``_no_active_pass`` check is both the
        no-active fast path and a poll-rate safety net."""
        faults.faultpoint("pass_engine/boundary")
        # Occupancy: the builder parked here is the boundary stage
        # blocked on its upstream (the active pass owning the store).
        # The per-pass verdict uses the engine's own boundary_ms deltas
        # as the authoritative numbers; this feed keeps the raw
        # occupancy view (trace_report) consistent with them.
        with self.timers.scope("feed_wait"), \
                pipeline_stats.GLOBAL.blocked_up("boundary"):
            while True:
                if pending.cancel.is_set():
                    raise PassBuildCancelled(
                        "pending pass build cancelled at the boundary "
                        "wait (cancel_pending)")
                if (pending.resume.is_set()
                        or self._no_active_pass.is_set()):
                    return
                pending.resume.wait(timeout=0.2)

    def feed_pass(self, pass_keys: np.ndarray, *, async_build: bool = False,
                  readonly: bool = False) -> None:
        """Register the next pass's key set and build its device table.

        ``async_build=True`` overlaps the build with current-pass training
        (role of PreLoadIntoMemory + WaitFeedPassDone). ``readonly=True``
        marks an eval-pass build: a device-tier store must not insert the
        pass's unseen keys (host-tier pulls never insert, so it is a no-op
        there).
        """
        self._pending_sem.acquire()
        pending = _PendingPass()
        # Publish BEFORE the builder runs: end_pass/cancel_pending find
        # the pending through self._pending to wake its boundary wait —
        # an invisible parked builder would sleep a poll interval (or,
        # pre-r08, deadlock against a failed pass).
        self._pending = pending
        if async_build:
            t = threading.Thread(target=self._build,
                                 args=(pass_keys, pending, readonly),
                                 daemon=True)
            pending.thread = t
            t.start()
        else:
            self._build(pass_keys, pending, readonly)

    def wait_feed_pass_done(self) -> None:
        p = self._pending
        if p is not None and p.thread is not None:
            p.thread.join()
        if p is not None and p.error is not None:
            raise p.error

    def cancel_pending(self) -> None:
        """Discard an un-begun pending build (error-path cleanup: a
        pipelined runner that fails mid-pass must not leave an orphaned
        build whose keymap a later retry would silently consume).

        Safe against a builder parked at the boundary: a pass that
        failed MID-training never runs end_pass, so the builder's wait
        would otherwise never release — the cancel event breaks it out
        (pre-r08 this join deadlocked)."""
        p = self._pending
        if p is None:
            return
        p.cancel.set()
        p.resume.set()
        if p.thread is not None:
            p.thread.join()
        if p.keymap is not None:
            p.keymap.close()
        self._pending = None
        self._pending_sem.release()

    # -- pass window -------------------------------------------------------

    def begin_pass(self) -> PassTable:
        """Swap in the pending pass's table (role of BeginPass)."""
        if self._table is not None:
            raise RuntimeError(
                "begin_pass while a pass is active — end_pass first "
                "(an async feed_pass build would deadlock waiting for it)")
        try:
            self.wait_feed_pass_done()
        except BaseException:
            # Failed build: release the pending slot so the caller can
            # retry with a fresh feed_pass instead of deadlocking.
            p = self._pending
            if p is not None and p.keymap is not None:
                p.keymap.close()
            self._pending = None
            self._pending_sem.release()
            raise
        if self._pending is None or self._pending.table is None:
            raise RuntimeError("begin_pass without a successful feed_pass")
        self._current_keys = self._pending.keys
        self._table = self._pending.table
        self._keymap = self._pending.keymap
        self._current_rows = self._pending.rows
        self._pending = None
        self._pass_id += 1
        # Order matters: mark the pass ACTIVE before releasing the
        # pending slot, or a queued async build could observe
        # no-active-pass in the gap, skip the split-pull sequencing, and
        # pull shared keys before this pass's write-back.
        self._no_active_pass.clear()
        self._pending_sem.release()
        log.vlog(1, "begin_pass %d: %d keys, %d shards", self._pass_id,
                 self._current_keys.shape[0], self.num_shards)
        return self._table

    @property
    def table(self) -> PassTable:
        if self._table is None:
            raise RuntimeError("no active pass")
        return self._table

    def update_table(self, table: PassTable) -> None:
        """Trainer hands back the latest device table after push steps."""
        self._table = table

    def lookup_rows(self, batch_keys: np.ndarray) -> np.ndarray:
        """Host map: batch feasigns → device row ids for the active pass
        (native hash lookup, role of CopyKeys' host side; numpy fallback)."""
        if self._current_keys is None or self._table is None:
            raise RuntimeError("no active pass")
        if self._keymap is not None:
            return self._keymap.lookup(batch_keys)
        return map_keys_to_rows(self._current_keys, batch_keys,
                                self._table.rows_per_shard, self.num_shards)

    def abort_if_active(self) -> None:
        """Error-path twin of :meth:`abort_pass`: drop the active pass if
        there is one, no-op otherwise — the pass-retry rollback cannot
        know whether the failure hit before or after begin_pass."""
        if self._table is not None:
            self.abort_pass()

    def abort_pass(self) -> None:
        """Drop the active pass WITHOUT writing back (role of the test
        mode, SetTestMode: eval passes must not dirty or grow the store)."""
        if self._table is None:
            raise RuntimeError("abort_pass without begin_pass")
        self._table = None
        self._current_keys = None
        self._current_rows = None
        if self._keymap is not None:
            self._keymap.close()
            self._keymap = None
        self._release_boundary()

    def _release_boundary(self) -> None:
        """Mark no-active and wake a parked pending builder (its
        ``resume`` event spares it the poll interval). Order matters:
        the shared event first, so a builder woken by either signal sees
        a consistent no-active state."""
        self._no_active_pass.set()
        p = self._pending
        if p is not None:
            p.resume.set()

    def _fuse_boundary(self) -> bool:
        """True when end_pass should run the fused scatter+gather
        program for a split build that is parked awaiting its shared
        remainder."""
        mode = str(flags.flag("pass_boundary_fuse")).lower()
        if mode == "off":
            return False
        p = self._pending
        return (p is not None and p.early_ready.is_set()
                and p.error is None and not p.cancel.is_set()
                and p.fused_table is None
                and hasattr(self.store, "push_and_pull_merge"))

    def end_pass(self) -> None:
        """Write the pass table back to the store (role of EndPass).

        When a split-built next pass is parked awaiting its shared-key
        remainder, the write-back scatter and that remainder gather run
        as ONE fused device program (FLAGS_pass_boundary_fuse): the
        boundary costs one dispatch over the host link instead of two,
        with identical sequencing (the gather reads the post-scatter
        store inside the program)."""
        if self._table is None or self._current_keys is None:
            raise RuntimeError("end_pass without begin_pass")
        faults.faultpoint("pass_engine/write_back")
        with self.timers.scope("end_pass"):
            if self._current_rows is not None and hasattr(
                    self.store, "push_pass_table"):
                # Device tier: one on-device scatter; nothing crosses to
                # the host (the r02 93s D2H+merge wall, VERDICT task 1).
                fused = False
                if self._fuse_boundary():
                    p = self._pending
                    p.fused_table = self.store.push_and_pull_merge(
                        self._current_keys, self._current_rows,
                        self._table, p.rows, p.early_table,
                        p.early_shared)
                    fused = True
                if not fused:
                    self.store.push_pass_table(self._current_keys,
                                               self._current_rows,
                                               self._table)
            else:
                vals = extract_pass_values_host(
                    self._table, self._current_keys.shape[0])
                if hasattr(self.store, "push_from_pass_async"):
                    # Priority split: rows the PENDING pass pulls back
                    # at its boundary push synchronously; the disjoint
                    # bulk remainder overlaps the next pass's training
                    # on the exchange worker. No pending keys yet (or
                    # overlap off) degrades to the serial push inside
                    # push_from_pass_async.
                    p = self._pending
                    nxt = p.keys if p is not None else None
                    pri = (shared_key_mask(nxt, self._current_keys)
                           if nxt is not None and nxt.size else None)
                    self.store.push_from_pass_async(
                        self._current_keys, vals, priority_select=pri,
                        pass_id=self._pass_id)
                else:
                    self.store.push_from_pass(self._current_keys, vals)
        self._table = None
        self._current_keys = None
        self._current_rows = None
        if self._keymap is not None:
            self._keymap.close()
            self._keymap = None
        self._release_boundary()
        monitor.add("pass/ended", 1)

    # -- boundary observability --------------------------------------------

    def boundary_ms(self) -> Dict[str, float]:
        """Cumulative boundary stage ms (delta them per pass): ``end_ms``
        the end_pass write-back (incl. a fused boundary program),
        ``build_ms`` the whole feed_pass build, ``feed_wait_ms`` the
        serial fraction of it — the time the builder sat blocked on the
        active pass. overlap_frac = 1 - feed_wait/build is computed by
        the per-pass reporter from these deltas.

        A store with a background exchange worker (MultiHostStore)
        contributes ``exchange_busy_ms``/``exchange_wait_ms`` — the
        reporter derives boundary.exchange_overlap_frac (1 -
        wait/busy) from their per-pass deltas."""
        snap = self.timers.snapshot_ms()
        out = {"end_ms": snap.get("end_pass", 0.0),
               "build_ms": snap.get("feed_pass", 0.0),
               "feed_wait_ms": snap.get("feed_wait", 0.0)}
        if hasattr(self.store, "exchange_stats"):
            out.update(self.store.exchange_stats())
        return out
