"""Pass lifecycle orchestration: feed_pass → begin_pass → train → end_pass.

Role of the BoxWrapper/BoxHelper pass driver (``box_wrapper.h:449-453,
1034-1301``): per-pass key registration (``FeedPass``), staged build of the
device table (``BeginFeedPass``/``EndFeedPass``; HeterPS ``PreBuildTask`` →
``BuildPull`` → ``BuildGPUTask``, ps_gpu_wrapper.cc:114,337,684), training
window between ``BeginPass``/``EndPass``, and write-back on ``EndPass``.

Double-buffering: ``feed_pass`` may run in a background thread while the
previous pass trains (role of PreLoadIntoMemory/WaitFeedPassDone overlap,
box_wrapper.h:1140,1161).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import log, monitor, timers
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import (PassTable, TableConfig,
                                           build_pass_table_host,
                                           extract_pass_values_host,
                                           map_keys_to_rows)


class _PendingPass:
    def __init__(self):
        self.keys: Optional[np.ndarray] = None
        self.table: Optional[PassTable] = None
        self.keymap = None
        self.rows: Optional[np.ndarray] = None   # device-store dense rows
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None


class PassEngine:
    """Owns the FeatureStore + the live per-pass device table."""

    def __init__(self, config: TableConfig, store: Optional[FeatureStore] = None,
                 *, mesh: Optional[Mesh] = None, table_axis: str = "dp"):
        self.config = config
        self.store = store or FeatureStore(config)
        self.mesh = mesh
        self.table_axis = table_axis
        self.num_shards = (
            int(mesh.shape[table_axis]) if mesh is not None else 1)
        self.timers = timers.TimerGroup()

        self._current_keys: Optional[np.ndarray] = None
        self._table: Optional[PassTable] = None
        self._keymap = None
        self._current_rows: Optional[np.ndarray] = None
        self._pending: Optional[_PendingPass] = None
        self._pass_id = -1
        # Sequencing for async builds: the store pull must happen AFTER the
        # previous pass's end_pass write-back, or updates to keys shared
        # between passes would be read stale and then overwritten (the
        # reference sequences BuildPull after EndPass the same way).
        self._no_active_pass = threading.Event()
        self._no_active_pass.set()
        # One pending-build slot: a feed_pass issued while an earlier
        # build is still waiting to be begun (pipelined day loops feeding
        # pass k+1 from a loader thread) blocks until begin_pass consumes
        # the earlier one. A semaphore (not an Event) so concurrent
        # feed_pass callers serialize atomically instead of both passing
        # a wait()+clear() window.
        self._pending_sem = threading.Semaphore(1)

    # -- build -------------------------------------------------------------

    def _build(self, pass_keys: np.ndarray, pending: _PendingPass,
               readonly: bool = False) -> None:
        try:
            with self.timers.scope("feed_pass"):
                # Key dedup can overlap the active pass... (native
                # multi-threaded dedup, role of PreBuildTask,
                # ps_gpu_wrapper.cc:114; numpy fallback inside)
                from paddlebox_tpu.native.keymap_py import KeyMap, dedup_keys
                keys = dedup_keys(np.asarray(pass_keys, np.uint64))
                if hasattr(self.store, "pull_pass_table"):
                    # Device-resident store tier: the build is an on-device
                    # gather — values never cross the host boundary. It
                    # must observe the previous pass's write-back, so wait
                    # for end_pass (the gather itself is cheap relative to
                    # the host pull it replaces).
                    with self.timers.scope("feed_wait"):
                        self._no_active_pass.wait()
                    table, rows = self.store.pull_pass_table(
                        keys, self.num_shards, readonly=readonly)
                    pending.keys = keys
                    pending.table = table
                    pending.rows = rows
                    pending.keymap = KeyMap(keys, table.rows_per_shard,
                                            self.num_shards)
                    monitor.add("pass/built", 1)
                    return
                # Split pull (role of the double-buffered build threads,
                # ps_gpu_wrapper.cc:907): the active pass's end_pass only
                # writes back ITS OWN keys, so values for keys NOT in the
                # active set can be pulled while it trains; only the
                # intersection must wait for write-back. Consecutive
                # online passes typically share a minority of keys, so
                # most of the pull overlaps training.
                active = self._current_keys  # snapshot; sorted or None
                vals = None
                shared = None
                if (active is not None and active.size and keys.size
                        and not self._no_active_pass.is_set()):
                    pos = np.minimum(np.searchsorted(active, keys),
                                     active.size - 1)
                    shared = active[pos] == keys
                    if shared.any() and not shared.all():
                        part = self.store.pull_for_pass(keys[~shared])
                        n = keys.shape[0]
                        vals = {f: np.empty((n,) + v.shape[1:], v.dtype)
                                for f, v in part.items()}
                        for f, v in part.items():
                            vals[f][~shared] = v
                    elif not shared.any():
                        vals = self.store.pull_for_pass(keys)
                        shared = None
                with self.timers.scope("feed_wait"):
                    self._no_active_pass.wait()
                if vals is None:
                    vals = self.store.pull_for_pass(keys)
                elif shared is not None:
                    part = self.store.pull_for_pass(keys[shared])
                    for f, v in part.items():
                        vals[f][shared] = v
                table = build_pass_table_host(
                    vals, self.num_shards, self.config)
                if self.mesh is not None:
                    sharding = NamedSharding(self.mesh, P(self.table_axis))
                    table = jax.tree.map(
                        lambda x: jax.device_put(x, sharding), table)
                pending.keys = keys
                pending.table = table
                pending.keymap = KeyMap(keys, table.rows_per_shard,
                                        self.num_shards)
                monitor.add("pass/built", 1)
        except BaseException as e:  # propagate to the waiting begin_pass
            pending.error = e

    def feed_pass(self, pass_keys: np.ndarray, *, async_build: bool = False,
                  readonly: bool = False) -> None:
        """Register the next pass's key set and build its device table.

        ``async_build=True`` overlaps the build with current-pass training
        (role of PreLoadIntoMemory + WaitFeedPassDone). ``readonly=True``
        marks an eval-pass build: a device-tier store must not insert the
        pass's unseen keys (host-tier pulls never insert, so it is a no-op
        there).
        """
        self._pending_sem.acquire()
        pending = _PendingPass()
        if async_build:
            t = threading.Thread(target=self._build,
                                 args=(pass_keys, pending, readonly),
                                 daemon=True)
            t.start()
            pending.thread = t
        else:
            self._build(pass_keys, pending, readonly)
        self._pending = pending

    def wait_feed_pass_done(self) -> None:
        p = self._pending
        if p is not None and p.thread is not None:
            p.thread.join()
        if p is not None and p.error is not None:
            raise p.error

    def cancel_pending(self) -> None:
        """Discard an un-begun pending build (error-path cleanup: a
        pipelined runner that fails mid-pass must not leave an orphaned
        build whose keymap a later retry would silently consume)."""
        p = self._pending
        if p is None:
            return
        if p.thread is not None:
            p.thread.join()
        if p.keymap is not None:
            p.keymap.close()
        self._pending = None
        self._pending_sem.release()

    # -- pass window -------------------------------------------------------

    def begin_pass(self) -> PassTable:
        """Swap in the pending pass's table (role of BeginPass)."""
        if self._table is not None:
            raise RuntimeError(
                "begin_pass while a pass is active — end_pass first "
                "(an async feed_pass build would deadlock waiting for it)")
        try:
            self.wait_feed_pass_done()
        except BaseException:
            # Failed build: release the pending slot so the caller can
            # retry with a fresh feed_pass instead of deadlocking.
            self._pending = None
            self._pending_sem.release()
            raise
        if self._pending is None or self._pending.table is None:
            raise RuntimeError("begin_pass without a successful feed_pass")
        self._current_keys = self._pending.keys
        self._table = self._pending.table
        self._keymap = self._pending.keymap
        self._current_rows = self._pending.rows
        self._pending = None
        self._pass_id += 1
        # Order matters: mark the pass ACTIVE before releasing the
        # pending slot, or a queued async build could observe
        # no-active-pass in the gap, skip the split-pull sequencing, and
        # pull shared keys before this pass's write-back.
        self._no_active_pass.clear()
        self._pending_sem.release()
        log.vlog(1, "begin_pass %d: %d keys, %d shards", self._pass_id,
                 self._current_keys.shape[0], self.num_shards)
        return self._table

    @property
    def table(self) -> PassTable:
        if self._table is None:
            raise RuntimeError("no active pass")
        return self._table

    def update_table(self, table: PassTable) -> None:
        """Trainer hands back the latest device table after push steps."""
        self._table = table

    def lookup_rows(self, batch_keys: np.ndarray) -> np.ndarray:
        """Host map: batch feasigns → device row ids for the active pass
        (native hash lookup, role of CopyKeys' host side; numpy fallback)."""
        if self._current_keys is None or self._table is None:
            raise RuntimeError("no active pass")
        if self._keymap is not None:
            return self._keymap.lookup(batch_keys)
        return map_keys_to_rows(self._current_keys, batch_keys,
                                self._table.rows_per_shard, self.num_shards)

    def abort_pass(self) -> None:
        """Drop the active pass WITHOUT writing back (role of the test
        mode, SetTestMode: eval passes must not dirty or grow the store)."""
        if self._table is None:
            raise RuntimeError("abort_pass without begin_pass")
        self._table = None
        self._current_keys = None
        self._current_rows = None
        if self._keymap is not None:
            self._keymap.close()
            self._keymap = None
        self._no_active_pass.set()

    def end_pass(self) -> None:
        """Write the pass table back to the store (role of EndPass)."""
        if self._table is None or self._current_keys is None:
            raise RuntimeError("end_pass without begin_pass")
        with self.timers.scope("end_pass"):
            if self._current_rows is not None and hasattr(
                    self.store, "push_pass_table"):
                # Device tier: one on-device scatter; nothing crosses to
                # the host (the r02 93s D2H+merge wall, VERDICT task 1).
                self.store.push_pass_table(self._current_keys,
                                           self._current_rows, self._table)
            else:
                vals = extract_pass_values_host(
                    self._table, self._current_keys.shape[0])
                self.store.push_from_pass(self._current_keys, vals)
        self._table = None
        self._current_keys = None
        self._current_rows = None
        if self._keymap is not None:
            self._keymap.close()
            self._keymap = None
        self._no_active_pass.set()
        monitor.add("pass/ended", 1)
