"""SSD/disk overflow tier: RAM-bounded feature store with cold spill.

Role of the SSD-backed sparse tables in the reference: ``SSDSparseTable``
(RocksDB-backed CPU table, ``ps/table/ssd_sparse_table.h``) and the BoxPS
SSD→mem staging (``LoadSSD2Mem``/``CheckNeedLimitMem``,
``box_wrapper.h:635,669``): the full trillion-feature table does not fit
in host RAM, so cold features live on disk and are staged in before the
pass that needs them.

TPU-first/host design: instead of an LSM keystore, features are bucketed
by key hash into npz shard files (columnar, one vectorized merge per
bucket — the access pattern is bulk pass-build reads, never point
lookups, so columnar beats rocksdb here). RAM and disk tiers are
exclusive: fetch moves rows RAM-ward, evict moves rows disk-ward, so a
key has exactly one authoritative copy.
"""

from __future__ import annotations

import glob
import os
import shutil
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import log, monitor
from paddlebox_tpu.embedding import lifecycle
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import TableConfig


class DiskShards:
    """Bucketed columnar key→row storage on disk."""

    def __init__(self, root: str, num_buckets: int = 64):
        self.root = root
        self.num_buckets = num_buckets
        os.makedirs(root, exist_ok=True)
        # Reclaim temps orphaned by a crash mid-save (they are dot-
        # prefixed so loads never see them, but they'd leak otherwise).
        for stale in glob.glob(os.path.join(root, ".*.tmp")):
            try:
                os.unlink(stale)
            except OSError:
                pass

    def _path(self, b: int) -> str:
        return os.path.join(self.root, f"bucket-{b:04d}.npz")

    def _bucket_of(self, keys: np.ndarray) -> np.ndarray:
        # Mix high bits so sequential feasign ranges spread across buckets.
        h = keys ^ (keys >> np.uint64(33))
        h = h * np.uint64(0xFF51AFD7ED558CCD)
        return (h % np.uint64(self.num_buckets)).astype(np.int64)

    def _load_bucket(self, b: int
                     ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        path = self._path(b)
        if not os.path.exists(path):
            return np.empty((0,), np.uint64), {}
        data = np.load(path)
        keys = data["keys"].astype(np.uint64)
        return keys, {f: data[f] for f in data.files if f != "keys"}

    def _save_bucket(self, b: int, keys: np.ndarray,
                     vals: Dict[str, np.ndarray]) -> None:
        path = self._path(b)
        if keys.size == 0:
            if os.path.exists(path):
                os.unlink(path)
            return
        # Dot-prefixed temp name so a crash mid-savez can never leave a
        # truncated file matching the 'bucket-*.npz' glob that
        # _load_bucket / restore_from scan.
        tmp = os.path.join(os.path.dirname(path),
                           "." + os.path.basename(path) + ".tmp")
        with open(tmp, "wb") as f:  # file object: savez can't append .npz
            np.savez(f, keys=keys, **vals)
        os.replace(tmp, path)

    def write(self, keys: np.ndarray, vals: Dict[str, np.ndarray]) -> None:
        """Upsert rows (sorted merge per bucket; new rows override)."""
        keys = np.asarray(keys, np.uint64)
        if keys.size == 0:
            return
        buckets = self._bucket_of(keys)
        for b in np.unique(buckets):
            sel = buckets == b
            bk = keys[sel]
            bv = {f: v[sel] for f, v in vals.items()}
            ok, ov = self._load_bucket(int(b))
            if ok.size:
                # Drop old copies of updated keys, then sorted-merge.
                keep = ~np.isin(ok, bk)
                merged_k = np.concatenate([ok[keep], bk])
                order = np.argsort(merged_k, kind="stable")
                merged_v = {f: np.concatenate([ov[f][keep], bv[f]])[order]
                            for f in bv}
                self._save_bucket(int(b), merged_k[order], merged_v)
            else:
                order = np.argsort(bk, kind="stable")
                self._save_bucket(int(b), bk[order],
                                  {f: v[order] for f, v in bv.items()})

    def read(self, keys: np.ndarray
             ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Bulk peek: (found [n], per-field values aligned to ``keys``,
        zeros where absent). Never moves rows — the serving cold tier's
        read path (:meth:`take` is the tier-moving variant; a predict
        miss must not mutate disk state on the request path)."""
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        found = np.zeros((n,), bool)
        out: Dict[str, np.ndarray] = {}
        if n == 0:
            return found, out
        buckets = self._bucket_of(keys)
        for b in np.unique(buckets):
            ok, ov = self._load_bucket(int(b))
            if ok.size == 0:
                continue
            sel = np.flatnonzero(buckets == b)
            # ok is sorted (write/take keep buckets sorted): one
            # searchsorted instead of an O(|bucket|*|keys|) isin.
            pos = np.searchsorted(ok, keys[sel])
            pos_c = np.minimum(pos, ok.size - 1)
            hit = ok[pos_c] == keys[sel]
            if not hit.any():
                continue
            if not out:
                out = {f: np.zeros((n,) + v.shape[1:], v.dtype)
                       for f, v in ov.items()}
            idx = sel[hit]
            found[idx] = True
            for f, v in ov.items():
                out[f][idx] = v[pos_c[hit]]
        return found, out

    def take(self, keys: np.ndarray
             ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Remove and return the present subset of ``keys``."""
        keys = np.unique(np.asarray(keys, np.uint64))
        if keys.size == 0:
            return keys, {}
        out_k = []
        out_v: Dict[str, list] = {}
        buckets = self._bucket_of(keys)
        for b in np.unique(buckets):
            ok, ov = self._load_bucket(int(b))
            if ok.size == 0:
                continue
            hit = np.isin(ok, keys[buckets == b])
            if not hit.any():
                continue
            out_k.append(ok[hit])
            for f, v in ov.items():
                out_v.setdefault(f, []).append(v[hit])
            self._save_bucket(int(b), ok[~hit],
                              {f: v[~hit] for f, v in ov.items()})
        if not out_k:
            return np.empty((0,), np.uint64), {}
        k = np.concatenate(out_k)
        v = {f: np.concatenate(parts) for f, parts in out_v.items()}
        order = np.argsort(k, kind="stable")
        return k[order], {f: a[order] for f, a in v.items()}

    @property
    def num_features(self) -> int:
        n = 0
        for path in glob.glob(os.path.join(self.root, "bucket-*.npz")):
            n += np.load(path)["keys"].shape[0]
        return n

    def copy_to(self, dst: str) -> None:
        os.makedirs(dst, exist_ok=True)
        for path in glob.glob(os.path.join(self.root, "bucket-*.npz")):
            shutil.copy(path, dst)

    def restore_from(self, src: str) -> None:
        for path in glob.glob(os.path.join(self.root, "bucket-*.npz")):
            os.unlink(path)
        for path in glob.glob(os.path.join(src, "bucket-*.npz")):
            shutil.copy(path, self.root)


class TieredFeatureStore:
    """FeatureStore bounded to ``max_ram_features`` with disk overflow.

    pull_for_pass stages any disk-resident pass keys into RAM first
    (LoadSSD2Mem role); push_from_pass writes to RAM and then evicts the
    coldest rows past the budget (CheckNeedLimitMem role). The wrapped
    store keeps the FeatureStore interface so the pass engine and PS
    server can use either interchangeably.
    """

    def __init__(self, config: TableConfig, disk_dir: str,
                 max_ram_features: Optional[int] = None,
                 num_buckets: int = 64, seed: int = 0):
        self.config = config
        self.ram = FeatureStore(config, seed=seed)
        self.disk = DiskShards(disk_dir, num_buckets)
        # Serializes TIER MOVEMENT against multi-step readers: the RAM
        # store's own lock only covers single calls, but stage-in/evict
        # move rows BETWEEN tiers — an export or pull interleaving with
        # a move would see a key in both tiers (or neither). RLock:
        # public methods nest (push_from_pass -> evict_to_budget).
        self._tier_lock = threading.RLock()
        self.max_ram_features = max_ram_features
        self.opt = self.ram.opt
        # Dirty keys that were evicted to disk since the last save_base:
        # they must be staged back for save_delta or their training
        # updates would silently vanish from the delta stream.
        self._evicted_dirty = np.empty((0,), np.uint64)
        # Unseen-days ages of DISK-resident rows (the RAM tier tracks
        # its own): recorded at spill time, bumped per shrink, handed
        # back on stage-in so a disk round-trip never resets a row's
        # TTL clock. In-memory beside the bucket files, like every
        # lifecycle age in this repo.
        self._disk_ages = lifecycle.RowAges()

    # -- tier movement -----------------------------------------------------

    def _stage_in(self, keys_sorted: np.ndarray) -> None:
        # callers hold _tier_lock
        missing = keys_sorted[~self.ram.contains(keys_sorted)]
        if missing.size == 0:
            return
        k, v = self.disk.take(missing)
        if k.size:
            # mark_dirty=False: staged rows are bit-identical to their
            # disk copies — a read-only pull must not bloat save_delta.
            # Ages travel with the rows (a stage-in is not a "seen").
            self.ram.push_from_pass(k, v, mark_dirty=False,
                                    unseen=self._disk_ages.ages_for(k))
            self._disk_ages.drop(k)
            monitor.add("ssd_tier/staged_in", int(k.size))

    def evict_to_budget(self) -> int:
        """Spill coldest rows until RAM is within budget."""
        with self._tier_lock:
            return self._evict_to_budget_locked()

    def _evict_to_budget_locked(self) -> int:
        if self.max_ram_features is None:
            return 0
        excess = self.ram.num_features - self.max_ram_features
        if excess <= 0:
            return 0
        cold = self.ram.rows_by_coldness()[:excess]
        self._evicted_dirty = np.union1d(
            self._evicted_dirty, np.intersect1d(cold, self.ram.dirty_keys()))
        ku = np.unique(cold)
        ages = self.ram.unseen_for(ku)
        k, v = self.ram.pop_rows(ku)
        # pop_rows returns the present subset in ku's (sorted) order, so
        # the age rows line up by searchsorted position.
        self._disk_ages.set(k, ages[np.searchsorted(ku, k)])
        self.disk.write(k, v)
        monitor.add("ssd_tier/evicted", int(k.size))
        log.vlog(1, "ssd_tier: evicted %d rows to disk", k.size)
        return int(k.size)

    # -- FeatureStore interface -------------------------------------------

    @property
    def num_features(self) -> int:
        return self.ram.num_features + self.disk.num_features

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        with self._tier_lock:
            self._stage_in(np.asarray(pass_keys_sorted, np.uint64))
            out = self.ram.pull_for_pass(pass_keys_sorted)
            # Pull-only traffic stages rows in too — without eviction
            # here a read-heavy client (serving-style pulls) would grow
            # RAM unboundedly past the budget the tier enforces.
            self._evict_to_budget_locked()
        return out

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray]) -> None:
        with self._tier_lock:
            # Disjoint-tiers invariant: any pushed key still on disk
            # (evicted between this RMW's pull and push, or pushed
            # without a pull — delta load) must leave the disk tier
            # before the RAM write, or it would exist in BOTH tiers
            # with the disk copy stale (duplicate keys in exports,
            # over-counted num_features).
            keys = np.asarray(pass_keys_sorted, np.uint64)
            not_in_ram = keys[~self.ram.contains(keys)]
            if not_in_ram.size:
                self.disk.take(not_in_ram)  # values discarded: overwritten
                self._disk_ages.drop(not_in_ram)
            self.ram.push_from_pass(pass_keys_sorted, values)
            self._evict_to_budget_locked()

    def contains(self, keys: np.ndarray) -> np.ndarray:
        with self._tier_lock:
            return self._contains_locked(keys)

    def _contains_locked(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        in_ram = self.ram.contains(keys)
        if in_ram.all():
            return in_ram
        # Disk check without moving rows: take+write-back would churn, so
        # peek via bucket loads.
        out = in_ram.copy()
        miss = keys[~in_ram]
        buckets = self.disk._bucket_of(miss)
        for b in np.unique(buckets):
            ok, _ = self.disk._load_bucket(int(b))
            if ok.size:
                sel = buckets == b
                hit = np.isin(miss[sel], ok)
                idx = np.flatnonzero(~in_ram)[sel]
                out[idx[hit]] = True
        return out

    def shrink(self, *, min_show: float = 0.0) -> int:
        with self._tier_lock:
            return self._shrink_locked(min_show=min_show)

    def _shrink_locked(self, *, min_show: float = 0.0) -> int:
        """Shrink both tiers: the RAM FeatureStore applies the full
        lifecycle itself; disk rows decay/age/evict in a bucket-by-
        bucket walk under the SAME policy (lifecycle.shrink_params), so
        a row's fate never depends on which tier it happens to sit in."""
        decay, ttl, eff_min_show = lifecycle.shrink_params(self.config,
                                                           min_show)
        evicted = self.ram.shrink(min_show=min_show)
        self._disk_ages.bump()
        for b in range(self.disk.num_buckets):
            k, v = self.disk._load_bucket(b)
            if k.size == 0:
                continue
            v["show"] = v["show"] * np.float32(decay)
            v["click"] = v["click"] * np.float32(decay)
            keep = np.ones(k.shape, bool)
            if eff_min_show > 0:
                keep &= v["show"] >= eff_min_show
            if ttl > 0:
                over = self._disk_ages.ages_for(k) > ttl
                monitor.add("store/ttl_evicted", int((keep & over).sum()))
                keep &= ~over
            if not keep.all():
                evicted += int((~keep).sum())
                self._disk_ages.drop(k[~keep])
                k = k[keep]
                v = {f: a[keep] for f, a in v.items()}
            self.disk._save_bucket(b, k, v)
        return evicted

    def save_base(self, path: str) -> None:
        with self._tier_lock:
            self._save_base_locked(path)

    def _save_base_locked(self, path: str) -> None:
        self.ram.save_base(path)   # writes the RAM tier's ages sidecar
        self._evicted_dirty = np.empty((0,), np.uint64)
        self.disk.copy_to(os.path.join(path,
                                       f"{self.config.name}.ssd"))
        # Disk-resident rows track their TTL ages in the in-memory
        # RowAges side table — persist it beside the copied buckets so
        # a restart restores disk rows' leases too (ONLINE.md).
        ages_final = os.path.join(path, f"{self.config.name}.ssd.ages.npz")
        ages_tmp = os.path.join(path, f".{self.config.name}.ssd.ages.tmp")
        with open(ages_tmp, "wb") as f:
            np.savez_compressed(f, keys=self._disk_ages._keys,
                                unseen=self._disk_ages._age)
        os.replace(ages_tmp, ages_final)

    def save_xbox(self, path: str) -> int:
        """Serving export across BOTH tiers (RAM ∪ disk — the tiers hold
        disjoint keys: eviction removes from RAM). Same artifact format
        as FeatureStore.save_xbox incl. the xbox_quant_bits flag.

        Under the tier lock: tier movement BETWEEN the RAM snapshot and
        the disk scan would export a moved key twice (or drop it)."""
        from paddlebox_tpu.embedding.store import quantize_xbox_vals
        with self._tier_lock:
            with self.ram._lock:
                keys = [self.ram._keys.copy()]
                embs = [self.ram._vals["emb"].copy()]
                ws = [self.ram._vals["w"].copy()]
            # Disk scan stays under the TIER lock: a concurrent eviction
            # between snapshot and scan would export a moved key twice;
            # a stage-in would drop it entirely.
            for b in range(self.disk.num_buckets):
                k, v = self.disk._load_bucket(b)
                if k.size:
                    keys.append(k)
                    embs.append(v["emb"])
                    ws.append(v["w"])
        k_all = np.concatenate(keys)
        order = np.argsort(k_all, kind="stable")
        vals = {"emb": np.concatenate(embs)[order],
                "w": np.concatenate(ws)[order]}
        self.ram._save_arrays(path, k_all[order],
                              quantize_xbox_vals(vals), "xbox")
        return int(k_all.shape[0])

    def save_delta(self, path: str) -> None:
        # Stage evicted-but-dirty rows back so the RAM delta set covers
        # every change since the last base (push_from_pass re-marks them
        # dirty), then re-evict to stay within budget.
        with self._tier_lock:
            self._save_delta_locked(path)

    def _save_delta_locked(self, path: str) -> None:
        if self._evicted_dirty.size:
            k, v = self.disk.take(self._evicted_dirty)
            if k.size:
                # Dirty rows were trained this day: mark_dirty resets
                # their age to 0, which is also the truth.
                self.ram.push_from_pass(k, v)
                self._disk_ages.drop(k)
            self._evicted_dirty = np.empty((0,), np.uint64)
        self.ram.save_delta(path)
        self.evict_to_budget()

    def load(self, path: str, kind: str = "base") -> None:
        with self._tier_lock:
            self._load_locked(path, kind)

    def unseen_for(self, keys: np.ndarray) -> np.ndarray:
        """Unseen-days ages aligned to ``keys``, whichever tier holds
        the row (0 where absent)."""
        k = np.asarray(keys, np.uint64)
        with self._tier_lock:
            out = self.ram.unseen_for(k)
            in_ram = self.ram.contains(k)
            if not in_ram.all():
                out[~in_ram] = self._disk_ages.ages_for(k[~in_ram])
        return out

    def _load_locked(self, path: str, kind: str) -> None:
        self.ram.load(path, kind)   # restores the RAM ages sidecar too
        if kind == "base":
            self._disk_ages.clear()
            ssd_src = os.path.join(path, f"{self.config.name}.ssd")
            if os.path.isdir(ssd_src):
                self.disk.restore_from(ssd_src)
            # Disk-tier ages sidecar (when present — a pre-sidecar
            # checkpoint's disk rows restart their TTL lease, the
            # documented legacy behavior).
            ages_f = os.path.join(path,
                                  f"{self.config.name}.ssd.ages.npz")
            if os.path.exists(ages_f):
                data = np.load(ages_f)
                self._disk_ages.set(data["keys"].astype(np.uint64),
                                    data["unseen"].astype(np.int32))
        else:
            # Disjoint-tiers invariant: the delta's keys are now
            # authoritative in RAM — purge any disk copies (a delta can
            # cover keys that were evicted since the base).
            data = np.load(os.path.join(
                path, f"{self.config.name}.delta.npz"))
            self.disk.take(data["keys"].astype(np.uint64))
