"""Sparse embedding engine — the TPU-native BoxPS/HeterPS equivalent.

The reference's differentiating capability is a GPU-resident sparse
parameter server (``fleet/box_wrapper.h``, ``fleet/heter_ps/`` — SURVEY.md
§2.2/2.3): trillion-feature embedding tables live sharded across device HBM,
training pulls/pushes only the current pass's working set, and a CPU/SSD
tier holds everything else between passes.

TPU-native re-design (SURVEY.md §7 step 4): BoxPS is *pass-based* — each
pass pre-registers its exact key set, so device-side "hashtable lookups"
become plain indexed gathers into a dense per-pass table:

- host: per-pass key dedup + sorted perfect index (role of PreBuildTask /
  PSAgent::AddKey), persistent host-RAM feature store between passes
  (role of the CPU PS tables / SSDSparseTable)
- device: pass table = contiguous arrays sharded over a mesh axis;
  pull = shard-bucketed all-to-all + gather (role of HeterComm::pull_sparse
  walk_to_dest/walk_to_src, heter_comm_inl.h:1628);
  push = sort + segment-merge dedup + all-to-all + exact fused sparse
  Adagrad/Adam applied in-place with buffer donation (role of
  dynamic_merge_grad + update_one_table, optimizer.cuh.h)
"""

from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import PassTable, TableConfig
from paddlebox_tpu.embedding.lookup import (
    pull_local,
    push_local,
    make_pull_fn,
    make_push_fn,
)
from paddlebox_tpu.embedding.optimizers import (SparseAdagrad, SparseAdam,
                                                SparseAdamShared,
                                                SparseFTRL,
                                                SparseOptimizer,
                                                make_sparse_optimizer)
from paddlebox_tpu.embedding.pass_engine import PassEngine
from paddlebox_tpu.embedding.grouped import GroupedEngine, GroupedStore
from paddlebox_tpu.embedding.sharded_store import ShardedFeatureStore
from paddlebox_tpu.embedding.device_store import DeviceFeatureStore

__all__ = [
    "FeatureStore",
    "GroupedEngine",
    "GroupedStore",
    "PassEngine",
    "ShardedFeatureStore",
    "DeviceFeatureStore",
    "PassTable",
    "SparseAdagrad",
    "SparseAdam",
    "SparseAdamShared",
    "SparseFTRL",
    "make_sparse_optimizer",
    "SparseOptimizer",
    "TableConfig",
    "make_pull_fn",
    "make_push_fn",
    "pull_local",
    "push_local",
]
