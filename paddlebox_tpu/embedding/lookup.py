"""Device-side sparse pull/push: bucketed all-to-all over the table axis.

Role of the HeterComm data path (``heter_comm_inl.h``):
- pull: ``split_input_to_shard`` → ``walk_to_dest`` → per-shard table get →
  ``walk_to_src`` (heter_comm_inl.h:1628; NVLink-staged P2P in the
  reference) → here one XLA ``all_to_all`` pair over the ICI mesh axis,
  serving ONE contiguous slice ``vals[:, :D+3]`` of the fused record.
- push: ``dynamic_merge_grad`` + ``update_one_table`` (cub sort +
  segment-reduce dedup then in-kernel optimizer, heter_comm.h:69,150) →
  here ONE scatter-add of the grad payload into a per-shard accumulator
  followed by a DENSE vectorized optimizer sweep over the local table
  block. Mathematically identical to dedup-then-update — the accumulator
  carries the per-row gradient SUM and the sweep applies the nonlinear
  optimizer once per touched row — but it lowers to one scatter plus
  streaming elementwise work instead of 3 sorts + 6 gathers + 6 scatters
  (XLA TPU scatter costs ~7 ns/element plus ~5 ms fixed per op; the r02
  layout paid that 6x per step — see tools/profile_step.py).

Everything is static-shape: per-destination buckets have fixed capacity
``C = ceil(n_unique/num_shards * slack)`` (flags
``embedding_shard_slack`` / ``embedding_unique_frac``); overflow entries
fall into the per-shard trash row. Bucketing is SORT-FREE (one-hot
cumsum ranks in original element order — zero sorts in the whole step),
DEDUPED (duplicate ids share one bucket cell, so pull/push transfer
unique rows only and duplicate grads merge sender-side before the
exchange — roles of dedup_keys_and_fillidx, heter_comm.h:192, and
dynamic_merge_grad, heter_comm.h:69-83, without their radix sorts), and
computed once per step, shared by pull and push (``compute_bucketing``,
which with ``axis=`` also runs the rows all_to_all ONCE for both sides —
3 collectives per width group, not 4 — and builds the one shared argsort
layout the Pallas sorted-stream pull gather / push scatter consume:
``sparse_gather_kernel`` / ``sparse_scatter_kernel``). All functions are
*per-device* bodies meant to run inside ``jax.shard_map`` with the table's
leading dim sharded over ``axis`` and id/grad batches sharded likewise.
With ``num_shards == 1`` (single-chip or replicated-table configs) the
bucketing + all_to_all pair is skipped entirely — pull is one gather and
push is one scatter-add + sweep.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddlebox_tpu.core import flags
from paddlebox_tpu.embedding.optimizers import SparseAdagrad, SparseOptimizer
from paddlebox_tpu.embedding.table import PassTable, TableConfig


def bucket_capacity(n: int, num_shards: int, slack: Optional[float] = None,
                    unique_frac: Optional[float] = None) -> int:
    """Static per-destination bucket size for n ids over num_shards.

    Mean + 4σ binomial headroom (keys hash ~uniformly across shards), scaled
    by the ``embedding_shard_slack`` flag: overflow probability per bucket is
    ~3e-5 at 4σ, and overflowing entries degrade to a dropped lookup (zeros)
    /dropped grad rather than corruption.

    With dedup enabled (``embedding_dedup``) a bucket cell holds a UNIQUE
    key, so capacity sizes to the expected unique-id count
    ``n * embedding_unique_frac`` instead of the occurrence count — this is
    where dedup turns into an all-to-all byte reduction (the reference gets
    the same effect from transferring d_merged_keys after
    dedup_keys_and_fillidx, heter_comm.h:192).
    """
    if slack is None:
        slack = flags.flag("embedding_shard_slack")
    if unique_frac is None:
        unique_frac = (flags.flag("embedding_unique_frac")
                       if flags.flag("embedding_dedup") else 1.0)
    n_eff = max(min(int(n * unique_frac + 0.999999), n), 1)
    mean = n_eff / num_shards
    c = int(slack * (mean + 4.0 * mean ** 0.5 + 8.0)) + 1
    c = min(max(c, 1), n)
    return -(-c // 8) * 8 if c >= 8 else c


def _bucket_by_shard(dev_rows: jax.Array, num_shards: int, block: int,
                     cap: int, dedup: Optional[bool] = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Assign ids to per-destination-shard buckets of static capacity.

    Role of split_input_to_shard + fill_shard_key (heter_comm_inl.h:273)
    plus — with ``dedup`` (flag ``embedding_dedup``, default on) —
    dedup_keys_and_fillidx (heter_comm.h:192): only the FIRST occurrence
    of each id consumes a bucket cell; later occurrences map to the same
    (shard, pos) cell, so the pull reply fans back out through the
    existing routing gather and push payloads for duplicates SUM into one
    cell via the existing bucket scatter-add — the pre-exchange merge the
    reference does with dynamic_merge_grad (heter_comm.h:69-83). A hot
    key therefore occupies exactly one cell and can never overflow a
    bucket by repetition; all-to-all bytes scale with UNIQUE ids.

    SORT-FREE, dedup included: destinations rank by one-hot cumsum (no
    argsort), and representatives are found by a scatter-min of the
    element index over the destination-row space (first occurrence = min
    index) — one [R]-scratch scatter-min + two [n] gathers, still zero
    sorts in the whole step (the reference's dedup is 2x cub radix sort,
    heter_comm.h:196-205).

    Returns (send_rows [num_shards, cap] dest-local rows with trash-row
    fill, slot_shard [n], slot_pos [n]) where (slot_shard[j],
    slot_pos[j]) locates element j's bucket cell; slot_pos >= cap marks
    overflow (dropped — reply reads are masked). With dedup, duplicate
    elements share a cell (same id -> same cell, by construction).
    """
    if dedup is None:
        dedup = bool(flags.flag("embedding_dedup"))
    n = dev_rows.shape[0]
    trash = block - 1  # last row of each shard block is the trash row
    shard_of = jnp.clip(dev_rows // block, 0, num_shards - 1
                        ).astype(jnp.int32)
    local_row = (dev_rows % block).astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    onehot = (shard_of[:, None]
              == jnp.arange(num_shards, dtype=jnp.int32)[None, :])
    if dedup:
        # Representative (first occurrence) per destination row: the row
        # space is exact (shard * block + local), so there are no hash
        # collisions and the merge is never wrong — the [R] int32 scratch
        # is small next to the [R, W] table it indexes into.
        key = shard_of * block + local_row
        buf = jnp.full((num_shards * block,), n, jnp.int32)
        buf = buf.at[key].min(idx, mode="drop")
        first_idx = buf[key]
        is_first = first_idx == idx
        # Only representatives consume bucket cells.
        onehot = onehot & is_first[:, None]
    ranks = jnp.cumsum(onehot.astype(jnp.int32), axis=0)
    pos = jnp.take_along_axis(ranks, shard_of[:, None], axis=1)[:, 0] - 1
    if dedup:
        # Every occurrence adopts its representative's bucket cell.
        pos = pos[first_idx]
    send_rows = jnp.full((num_shards, cap), trash, jnp.int32)
    # Overflow entries (pos >= cap) use an out-of-range column index so the
    # scatter drops them instead of clobbering cell 0. Under dedup,
    # duplicates write the SAME local_row into the same cell — idempotent.
    send_rows = send_rows.at[shard_of, pos].set(local_row, mode="drop")
    return send_rows, shard_of, pos


def _wire_mode() -> str:
    """Wire mode for the pull-reply / push-grad all_to_all payloads
    (``embedding_exchange_dtype``): 'f32' (exact — the default path
    must stay bit-identical, so it exchanges the payload untouched),
    'bf16' (cast sender-side, half the bytes, widened back BEFORE any
    accumulation), or 'int8' (symmetric per-block quantization with f32
    scales riding a second small all_to_all — quarter the payload
    bytes; EQuARX-style: quantize the wire, accumulate in full
    precision). Row/request exchanges are int32 and never cast."""
    mode = flags.flag("embedding_exchange_dtype")
    if mode in ("f32", "bf16", "int8"):
        return mode
    raise ValueError(
        f"unknown embedding_exchange_dtype {mode!r} "
        "(want 'f32'/'bf16'/'int8')")


def _exchange_payload(x: jax.Array, axis: str) -> jax.Array:
    """One f32 payload all_to_all under the configured wire mode.
    f32 mode is the UNTOUCHED pre-flag exchange (bit-exact); reduced
    modes encode sender-side and widen back to f32 receiver-side, so
    whatever the caller accumulates stays full precision."""
    mode = _wire_mode()
    if mode == "f32":
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    if mode == "bf16":
        return lax.all_to_all(
            x.astype(jnp.bfloat16), axis, split_axis=0, concat_axis=0,
            tiled=True).astype(jnp.float32)
    from paddlebox_tpu.multihost.quant import (dequantize_blocked,
                                               quantize_blocked)
    block = int(flags.flag("embedding_quant_block"))
    q, scales = quantize_blocked(x, block)
    recv_q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=True)
    recv_s = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                            tiled=True)
    return dequantize_blocked(recv_q, recv_s, x.shape[-1], block)


def _kernel_mode(flag_name: str) -> Optional[str]:
    """Resolve a sorted-stream kernel flag to 'pallas' / 'interpret' /
    None (XLA). One predicate so the gather and scatter sites — and the
    shared-layout builder that must know whether EITHER will consume a
    sort — can never disagree on what 'auto' means."""
    mode = flags.flag(flag_name)
    if mode in ("pallas", "interpret"):
        return mode
    if mode == "auto" and flags.pallas_kernels_enabled():
        return "pallas"
    return None


def _stream_layout_for(rows: jax.Array, block: int) -> Optional[Tuple]:
    """The shared sorted-stream layout (sorted_gather.sorted_stream_layout
    over the trash-remapped rows) for one width group's pull gather AND
    push scatter — or None when neither kernel is enabled (the argsort
    would be pure cost on the XLA paths). Trash rows (block - 1) are
    remapped past the row bound so both kernels DROP them — the trash
    row's pull columns are zero by contract, so the drop is
    value-identical to gathering it, and the scatter must not pay the
    concentrated padding run (see _accumulate)."""
    if (_kernel_mode("sparse_gather_kernel") is None
            and _kernel_mode("sparse_scatter_kernel") is None):
        return None
    from paddlebox_tpu.ops.pallas_kernels.sorted_gather import (
        sorted_stream_layout)
    trash = block - 1
    rows_k = jnp.where(rows == trash, block, rows).astype(jnp.int32)
    return sorted_stream_layout(rows_k, block)


def compute_bucketing(table: PassTable, dev_rows: jax.Array,
                      cap: Optional[int] = None, *,
                      axis: Optional[str] = None) -> Optional[Tuple]:
    """The bucket-by-shard layout for one (table, ids) pair — the ONE
    source of truth for block/cap so a caller sharing the layout between
    pull_local and push_local (both bucket the same dev_rows; computing
    it twice pays the one-hot cumsum + bucket scatter twice per step)
    can never drift from their internal fallback. None when the table is
    unsharded (single-shard paths never bucket) and no kernel layout
    applies.

    ``cap`` overrides the n-based capacity bound — the trainer's
    measured auto-capacity path (FLAGS_embedding_auto_capacity) sizes it
    from the pass data's actual per-shard unique-id maximum. The cap
    rides INSIDE the returned tuple, so pull_local/push_local consuming
    a shared layout always mask with the capacity it was built at —
    capacity cannot drift between the layout and its consumers.

    ``axis`` (the table mesh axis, when called inside shard_map) extends
    the tuple with the OWNER-SIDE shared state: the pull's request
    exchange and the push's row exchange move the SAME ``send_rows``, so
    the rows all_to_all runs ONCE here (3 collectives per width group
    instead of 4), and — when a sorted-stream kernel is enabled — the
    received rows' argsort layout is built ONCE and consumed by both the
    pull gather (CopyForPull) and the push scatter (CopyForPush), so the
    step pays one argsort instead of two. Tuple shapes:

        no axis:   (send_rows, slot_shard, slot_pos, cap)     — legacy
        axis:      (send_rows, slot_shard, slot_pos, cap,
                    recv_rows [S*C], stream_layout | None)
        axis, 1-shard: (None, None, None, None, dev_rows,
                    stream_layout)  — sort sharing only, or None when
                    no kernel is enabled (nothing to share)."""
    block = table.rows_per_shard + 1
    if table.num_shards == 1:
        if axis is None:
            return None
        layout = _stream_layout_for(dev_rows, block)
        if layout is None:
            return None
        return (None, None, None, None, dev_rows, layout)
    if cap is None:
        cap = bucket_capacity(dev_rows.shape[0], table.num_shards)
    bk = _bucket_by_shard(dev_rows, table.num_shards, block, cap)
    if axis is None:
        return bk + (cap,)
    recv_rows = lax.all_to_all(bk[0], axis, split_axis=0, concat_axis=0,
                               tiled=True).reshape(table.num_shards * cap)
    return bk + (cap, recv_rows, _stream_layout_for(recv_rows, block))


def exchange_bytes(table: PassTable, n: int,
                   cap: Optional[int] = None) -> int:
    """Static per-device all-to-all bytes for one pull+push round over
    ``n`` ids — the observable that dedup + ``embedding_unique_frac``
    (or a measured ``cap``) shrink (the reference transfers
    d_merged_keys/grads after dedup, heter_comm.h:192; here the byte
    count is a pure function of the static bucket capacity, so trainers
    can report it per step without touching the hot path)."""
    if table.num_shards == 1:
        return 0
    if cap is None:
        cap = bucket_capacity(n, table.num_shards)
    s = table.num_shards
    # Payload bytes follow the wire dtype (embedding_exchange_dtype);
    # the two row exchanges (pull requests shared with push dests via
    # compute_bucketing, so ONE exchange — but exchange_bytes predates
    # the sharing and deliberately reports the pull+push round as two
    # independent halves, each carrying its rows) stay int32. int8
    # payloads count padded values PLUS the per-block f32 scales.
    mode = _wire_mode()
    if mode == "int8":
        from paddlebox_tpu.multihost.quant import quantized_wire_bytes
        block = int(flags.flag("embedding_quant_block"))
        pull = s * cap * 4 + quantized_wire_bytes(
            s * cap, table.pull_width, block)
        push = s * cap * 4 + quantized_wire_bytes(
            s * cap, table.dim + 4, block)
        return pull + push
    esize = 2 if mode == "bf16" else 4
    pull = s * cap * 4 + s * cap * table.pull_width * esize
    push = s * cap * 4 + s * cap * (table.dim + 4) * esize
    return pull + push


def record_exchange_stats(tables, group_n, caps) -> int:
    """Per-pass exchange telemetry: total static per-device all-to-all
    bytes for one pull+push round across all width groups, published
    into the metric registry (``lookup/…``) and as a trace counter so
    the exchange shows up in the pass report AND the timeline. Pure
    host arithmetic over static shapes — never touches the hot path."""
    from paddlebox_tpu.core import monitor, trace
    total = int(sum(exchange_bytes(t, n, cap=c)
                    for t, n, c in zip(tables, group_n, caps)))
    monitor.set_stat("lookup/exchange_bytes_per_step", total)
    monitor.set_gauge("lookup/wire_bits",
                      {"f32": 32.0, "bf16": 16.0,
                       "int8": 8.0}[_wire_mode()])
    trace.counter("lookup/exchange_bytes", per_step=total)
    return total


def _gather_rows(vals: jax.Array, rows: jax.Array, width: int, block: int,
                 layout: Optional[Tuple] = None) -> jax.Array:
    """vals[rows, :width] by the configured backend
    (``sparse_gather_kernel`` flag): the Pallas sorted-stream gather
    (CopyForPull role — the XLA gather is the pull path's dominant op,
    PROFILE.md) or the XLA gather. On the kernel path trash rows
    (block - 1: padding/overflow requests) are DROPPED to zeros — the
    trash row's pull columns are zero by contract (apply_accumulated
    keeps them so), so the result is identical while the concentrated
    padding run stays off the kernel's per-block budget. ``layout`` is
    the shared sorted-stream layout from compute_bucketing (one argsort
    serves this gather and the push scatter)."""
    mode = _kernel_mode("sparse_gather_kernel")
    if mode is None or vals.shape[-1] > 128:
        # Fused records wider than one 128-lane tile cannot stream
        # through the kernel's VMEM blocks — serve them with XLA.
        return vals[rows, :width]
    from paddlebox_tpu.ops.pallas_kernels.sorted_gather import sorted_gather
    trash = block - 1
    rows_k = jnp.where(rows == trash, block, rows).astype(jnp.int32)
    return sorted_gather(rows_k, vals, width=width, layout=layout,
                         interpret=(mode == "interpret"))


def pull_local(table: PassTable, dev_rows: jax.Array, *, axis: str,
               bucketing: Optional[Tuple] = None,
               cap: Optional[int] = None) -> Dict[str, jax.Array]:
    """Per-device pull: ids [n] (device-row space) → {emb [n, D], w [n],
    show [n], click [n], overflow []}. Padding/overflow ids yield the
    trash row (zeros unless polluted — push keeps it zeroed).

    ``overflow`` counts THIS device's real (non-trash) ids that fell past
    their destination bucket's static capacity and degraded to a dropped
    lookup (zeros) — the same positions drop their grads in push_local.
    The capacity contract (`bucket_capacity`): with dedup (default) a
    cell holds a UNIQUE id, so repetition — the realistic skew in CTR
    data, where a hot key can be 30%+ of a batch — cannot overflow a
    bucket at all; what remains is uniform-hash spread of unique ids
    (~3e-5 per bucket at default slack, less any margin given away via
    ``embedding_unique_frac``). Overflows remain counted, honest drops
    (contrast: the reference's HeterComm never drops,
    heter_comm_inl.h:273 — it re-walks; we trade bounded drop odds for
    static shapes and expose the count). Single shard: one sliced
    gather, no collective, no possible overflow.
    """
    num_shards = table.num_shards
    block = table.rows_per_shard + 1
    d = table.dim
    pw = table.pull_width

    if num_shards == 1:
        # Shared sorted-stream layout (compute_bucketing with axis): the
        # push scatter sorts the same dev_rows — one argsort serves both.
        layout = (bucketing[5] if bucketing is not None
                  and len(bucketing) == 6 else None)
        picked = _gather_rows(table.vals, dev_rows, pw, block,
                              layout=layout)
        return {
            "emb": picked[:, :d],
            "w": picked[:, d],
            "show": picked[:, d + 1],
            "click": picked[:, d + 2],
            "overflow": jnp.zeros((1,), jnp.int32),
        }

    n = dev_rows.shape[0]
    trash = block - 1

    # ``bucketing``: the train step computes the bucket-by-shard layout
    # ONCE per width group (compute_bucketing) and shares it between
    # this pull and the matching push — both bucket the SAME dev_rows,
    # so recomputing would pay the layout twice per step for identical
    # results. The shared tuple CARRIES its capacity: masks below must
    # use the capacity the buckets were built at, never a local guess.
    # With axis-extended tuples it also carries the received rows (the
    # push exchanges the same send_rows — one collective, not two) and
    # the owner-side sorted-stream layout for the Pallas kernels.
    recv_rows = layout = None
    if bucketing is None:
        if cap is None:
            cap = bucket_capacity(n, num_shards)
        send_rows, slot_shard, slot_pos = _bucket_by_shard(
            dev_rows, num_shards, block, cap)
    else:
        send_rows, slot_shard, slot_pos, cap = bucketing[:4]
        if len(bucketing) == 6:
            recv_rows, layout = bucketing[4], bucketing[5]
    # Shape [1] (not scalar) so prefix out_specs like P(axis) remain
    # valid for the returned dict under shard_map.
    overflow = jnp.sum(((slot_pos >= cap)
                        & (dev_rows % block != trash)
                        ).astype(jnp.int32)).reshape(1)

    # Exchange requests: recv_req[s, c] = row requested by peer s.
    if recv_rows is None:
        recv_rows = lax.all_to_all(send_rows, axis, split_axis=0,
                                   concat_axis=0, tiled=True
                                   ).reshape(num_shards * cap)
    recv_req = recv_rows.reshape(num_shards, cap)
    # Serve from the local shard block: the fused record's pull payload
    # [emb | w | show | click] is one contiguous slice, so the reply path
    # is a single gather (or the Pallas sorted-stream kernel) + a single
    # collective.
    served = _gather_rows(table.vals, recv_rows, pw, block,
                          layout=layout).reshape(num_shards * cap, pw)
    # Reduced-precision wire (embedding_exchange_dtype): the reply
    # payload is encoded sender-side (bf16 cast / int8 per-block
    # quantize) and widened back to f32 receiver-side; f32 mode takes
    # the untouched path (bit-exact).
    reply = _exchange_payload(served, axis).reshape(num_shards, cap, pw)
    # Route replies back: (slot_shard, slot_pos) are in original element
    # order (sort-free bucketing), so one gather finishes the pull.
    in_cap = slot_pos < cap
    picked = reply[slot_shard, jnp.where(in_cap, slot_pos, 0)]
    picked = jnp.where(in_cap[:, None], picked, 0)
    return {
        "emb": picked[:, :d],
        "w": picked[:, d],
        "show": picked[:, d + 1],
        "click": picked[:, d + 2],
        "overflow": overflow,
    }


def _accumulate(rows: jax.Array, payload: jax.Array, block: int,
                layout: Optional[Tuple] = None) -> jax.Array:
    """zeros([block, AW]).at[rows].add(payload) by the configured backend
    (``sparse_scatter_kernel`` flag): the Pallas sorted-stream kernel
    (CopyForPush role — XLA TPU scatter is the step's dominant cost,
    PROFILE.md) or the XLA scatter. Trash-row entries (row == block-1:
    padding/overflow, all-zero or count-only payload) are dropped on the
    kernel path — apply_accumulated re-zeroes the trash row either way,
    and concentrating every padding lane on one row is exactly the skew
    the kernel's per-block budget must not pay for. ``layout`` is the
    shared sorted-stream layout from compute_bucketing (one argsort
    serves this scatter and the pull gather)."""
    mode = _kernel_mode("sparse_scatter_kernel")
    if mode is None:
        acc = jnp.zeros((block, payload.shape[-1]), payload.dtype)
        return acc.at[rows].add(payload)
    from paddlebox_tpu.ops.pallas_kernels.sorted_scatter import (
        sorted_scatter_accumulate)
    trash = block - 1
    rows_k = jnp.where(rows == trash, block, rows).astype(jnp.int32)
    acc = sorted_scatter_accumulate(rows_k, payload, block,
                                    interpret=(mode == "interpret"),
                                    layout=layout)
    return acc


def apply_accumulated(vals: jax.Array, acc: jax.Array, *, dim: int,
                      ke: int, block: int,
                      opt: SparseOptimizer) -> jax.Array:
    """Dense optimizer sweep: apply per-row accumulated grads to the fused
    local table block (role of update_one_table's in-kernel optimizer,
    heter_comm.h:150 / optimizer.cuh.h:31).

    ``vals [m, W]`` fused records; ``acc [m, D+4]`` accumulated
    [g_emb(D) | g_w | show | click | count]. Rows with count == 0 are
    untouched (their state — incl. adam beta-pows — must not advance);
    trash rows (local index block-1 of each shard block) keep zero value
    columns regardless.
    """
    m = vals.shape[0]
    g_emb = acc[:, :dim]
    g_w = acc[:, dim]
    touched = acc[:, dim + 3] > 0

    emb = vals[:, :dim]
    w = vals[:, dim]
    show = vals[:, dim + 1]
    click = vals[:, dim + 2]
    emb_state = vals[:, dim + 3:dim + 3 + ke]
    w_state = vals[:, dim + 3 + ke:]

    new_emb, new_emb_st = opt.update_vector(emb, emb_state, g_emb)
    new_w, new_w_st = opt.update_scalar(w, w_state, g_w)
    new_show = show + acc[:, dim + 1]
    new_click = click + acc[:, dim + 2]

    new_vals = jnp.concatenate([
        new_emb, new_w[:, None], new_show[:, None], new_click[:, None],
        new_emb_st, new_w_st], axis=1)
    out = jnp.where(touched[:, None], new_vals, vals)
    # Trash rows: padding/overflow grads land here; keep the PULL columns
    # zeroed so padding pulls keep returning zeros (optimizer state on the
    # trash row may drift — it is never read).
    is_trash = (jnp.arange(m) % block) == (block - 1)
    zero_pull = jnp.concatenate(
        [jnp.zeros((m, dim + 3), out.dtype), out[:, dim + 3:]], axis=1)
    return jnp.where(is_trash[:, None], zero_pull, out)


def push_local(table: PassTable, dev_rows: jax.Array, grad_emb: jax.Array,
               grad_w: jax.Array, shows: jax.Array, clicks: jax.Array, *,
               axis: str, opt: Optional[SparseOptimizer] = None,
               dcn_axis: Optional[str] = None,
               bucketing: Optional[Tuple] = None,
               cap: Optional[int] = None) -> PassTable:
    """Per-device push: scatter-accumulate + dense fused optimizer sweep.

    dev_rows [n]; grad_emb [n, D]; grad_w/shows/clicks [n]. Padding entries
    must carry zero grads (guaranteed upstream because padding ids map to
    the discard segment) — they land in the trash row regardless.

    ``dcn_axis`` (multi-slice): the pass table is sharded over ``axis``
    INSIDE each slice and replicated across slices, so the bucketed
    all_to_all stays on ICI; the per-shard grad accumulator is then
    psum'd once over the slice axis — the single DCN stage — before the
    optimizer sweep, so every slice applies the identical global update
    and replicas stay bit-equal (role of gather_multi_node_grad's
    inter-node allreduce of node-merged grads, ``heter_comm.h:156-172``,
    landed on the dense accumulator instead of a sorted key list because
    the accumulator has the same static shape on every slice).
    """
    if opt is None:
        opt = SparseAdagrad()
    ke = opt.emb_state_width(table.dim)
    kw = opt.w_state_width()
    if table.ke != ke or table.kw != kw:
        raise ValueError(
            f"optimizer {type(opt).__name__} expects state widths "
            f"({ke}, {kw}) but table carries ({table.ke}, {table.kw}) — "
            f"push opt must match the TableConfig.optimizer the table was "
            f"built with")
    num_shards = table.num_shards
    block = table.rows_per_shard + 1
    n = dev_rows.shape[0]
    d = table.dim
    aw = d + 4  # accumulator width: [g_emb | g_w | show | click | count]

    # Payload per id: grads + stats + a count of 1 (the count column marks
    # the row as touched; filler bucket cells carry 0 everywhere).
    payload = jnp.concatenate([
        grad_emb, grad_w[:, None], shows[:, None], clicks[:, None],
        jnp.ones((n, 1), grad_emb.dtype)], axis=-1)

    if num_shards == 1:
        # Shared sorted-stream layout (compute_bucketing with axis): the
        # pull gather sorted the same dev_rows — one argsort for both.
        layout = (bucketing[5] if bucketing is not None
                  and len(bucketing) == 6 else None)
        acc = _accumulate(dev_rows, payload, block, layout=layout)
        if dcn_axis is not None:
            acc = lax.psum(acc, dcn_axis)
        new_vals = apply_accumulated(table.vals, acc, dim=d, ke=ke,
                                     block=block, opt=opt)
        return PassTable(vals=new_vals, rows_per_shard=table.rows_per_shard,
                         num_shards=1, dim=d, ke=ke, kw=kw)

    recv_rows = layout = None
    if bucketing is None:
        if cap is None:
            cap = bucket_capacity(n, num_shards)
        send_rows, slot_shard, slot_pos = _bucket_by_shard(
            dev_rows, num_shards, block, cap)
    else:
        # Shared layout carries its own capacity (compute_bucketing) —
        # and, when axis-extended, the already-exchanged rows (the pull
        # moved the same send_rows) plus the owner-side sort layout.
        send_rows, slot_shard, slot_pos, cap = bucketing[:4]
        if len(bucketing) == 6:
            recv_rows, layout = bucketing[4], bucketing[5]
    send_payload = jnp.zeros((num_shards, cap, aw), payload.dtype)
    # (slot_shard, slot_pos) are in original element order — the payload
    # scatters straight into its bucket cells, no permutation gather.
    # Out-of-range positions (overflow) are dropped by the scatter.
    send_payload = send_payload.at[slot_shard, slot_pos].add(
        payload, mode="drop")

    if recv_rows is None:
        recv_rows = lax.all_to_all(send_rows, axis, split_axis=0,
                                   concat_axis=0, tiled=True
                                   ).reshape(num_shards * cap)
    # Reduced-precision wire (embedding_exchange_dtype): grads merged
    # sender-side in f32 (the bucket scatter-add above), encoded for
    # the exchange only (bf16 cast / int8 per-block quantize), widened
    # back before the owner-side accumulate — accumulation never
    # happens in reduced precision.
    send_flat = send_payload.reshape(num_shards * cap, aw)
    recv_payload = _exchange_payload(send_flat, axis)

    # Owner-side accumulate (role of dynamic_merge_grad): filler cells
    # point at the trash row with all-zero payload, so they are no-ops.
    acc = _accumulate(recv_rows, recv_payload, block, layout=layout)
    if dcn_axis is not None:
        # The one DCN stage: combine each shard's slice-local grad sums
        # across slices (table replicas) before the optimizer applies.
        acc = lax.psum(acc, dcn_axis)
    new_vals = apply_accumulated(table.vals, acc, dim=d, ke=ke,
                                 block=block, opt=opt)
    return PassTable(vals=new_vals, rows_per_shard=table.rows_per_shard,
                     num_shards=num_shards, dim=d, ke=ke, kw=kw)


# ---------------------------------------------------------------------------
# Standalone jitted wrappers (tests + simple trainers). Production train
# steps inline pull_local/push_local into their own shard_map body.
# ---------------------------------------------------------------------------

def make_pull_fn(mesh: Mesh, axis: str = "dp"):
    """Jitted (table, dev_rows) -> pulled dict, table/ids sharded on axis.

    ``P(axis)`` is a pytree prefix: it shards every PassTable leaf's
    leading dim over the table axis.
    """

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    def pull(table: PassTable, dev_rows: jax.Array):
        return pull_local(table, dev_rows, axis=axis)

    return pull


def make_push_fn(mesh: Mesh, axis: str = "dp",
                 opt: Optional[SparseOptimizer] = None):
    """Jitted sparse-grad apply with table donation."""

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    def push_sm(table, dev_rows, g_emb, g_w, shows, clicks):
        return push_local(table, dev_rows, g_emb, g_w, shows, clicks,
                          axis=axis, opt=opt)

    return jax.jit(push_sm, donate_argnums=(0,))
