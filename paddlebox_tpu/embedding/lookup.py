"""Device-side sparse pull/push: bucketed all-to-all over the table axis.

Role of the HeterComm data path (``heter_comm_inl.h``):
- pull: ``split_input_to_shard`` → ``walk_to_dest`` → per-shard table get →
  ``walk_to_src`` (heter_comm_inl.h:1628; NVLink-staged P2P in the
  reference) → here one XLA ``all_to_all`` pair over the ICI mesh axis.
- push: ``dynamic_merge_grad`` (cub sort + segment-reduce dedup,
  heter_comm.h:69) → shard scatter → ``update_one_table`` fused optimizer
  → here an on-owner sort + segment-sum exact merge + masked scatter
  update, donation-friendly.

Everything is static-shape: per-destination buckets have fixed capacity
``C = ceil(n/num_shards * slack)`` (slack flag ``embedding_shard_slack``);
overflow entries fall into the per-shard trash row. All functions are
*per-device* bodies meant to run inside ``jax.shard_map`` with the table's
leading dim sharded over ``axis`` and id/grad batches sharded likewise.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import flags
from paddlebox_tpu.embedding.optimizers import SparseAdagrad, SparseOptimizer
from paddlebox_tpu.embedding.table import PassTable, TableConfig


def bucket_capacity(n: int, num_shards: int, slack: Optional[float] = None) -> int:
    """Static per-destination bucket size for n ids over num_shards.

    Mean + 4σ binomial headroom (keys hash ~uniformly across shards), scaled
    by the ``embedding_shard_slack`` flag: overflow probability per bucket is
    ~3e-5 at 4σ, and overflowing entries degrade to a dropped lookup (zeros)
    /dropped grad rather than corruption.
    """
    if slack is None:
        slack = flags.flag("embedding_shard_slack")
    mean = n / num_shards
    c = int(slack * (mean + 4.0 * mean ** 0.5 + 8.0)) + 1
    c = min(max(c, 1), n)
    return -(-c // 8) * 8 if c >= 8 else c


def _bucket_by_shard(dev_rows: jax.Array, num_shards: int, block: int,
                     cap: int) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort ids into per-destination-shard buckets of static capacity.

    Role of split_input_to_shard + fill_shard_key (heter_comm_inl.h:273).

    Returns (send_rows [num_shards, cap] dest-local rows with trash-row
    fill, order [n] sort permutation, slot_shard [n], slot_pos [n]) where
    (slot_shard[j], slot_pos[j]) locates sorted element j's reply cell;
    slot_pos >= cap marks overflow (dropped — reply reads are masked).
    """
    n = dev_rows.shape[0]
    trash = block - 1  # last row of each shard block is the trash row
    shard_of = jnp.clip(dev_rows // block, 0, num_shards - 1)
    order = jnp.argsort(shard_of, stable=True)
    sorted_rows = dev_rows[order]
    sorted_shard = shard_of[order]
    starts = jnp.searchsorted(sorted_shard, jnp.arange(num_shards))
    pos = jnp.arange(n) - starts[sorted_shard]
    local_row = sorted_rows % block
    send_rows = jnp.full((num_shards, cap), trash, jnp.int32)
    # Overflow entries (pos >= cap) use an out-of-range column index so the
    # scatter drops them instead of clobbering cell 0.
    send_rows = send_rows.at[sorted_shard, pos].set(
        local_row.astype(jnp.int32), mode="drop")
    return send_rows, order, sorted_shard, pos


def pull_local(table: PassTable, dev_rows: jax.Array, *, axis: str
               ) -> Dict[str, jax.Array]:
    """Per-device pull: ids [n] (device-row space) → {emb [n, D], w [n],
    show [n], click [n], overflow []}. Padding/overflow ids yield the
    trash row (zeros unless polluted — push re-zeroes it).

    ``overflow`` counts THIS device's real (non-trash) ids that fell past
    their destination bucket's static capacity and degraded to a dropped
    lookup (zeros) — the same positions drop their grads in push_local.
    The capacity contract (`bucket_capacity`): keys hashing ~uniformly
    across shards overflow with probability ~3e-5 per bucket at the
    default slack; a skewed distribution (hot shard) CAN overflow
    materially, which is exactly what this counter surfaces (contrast:
    the reference's HeterComm never drops, heter_comm_inl.h:273 — it
    re-walks; we trade bounded drop odds for static shapes and expose
    the count)."""
    num_shards = table.num_shards
    block = table.rows_per_shard + 1
    n = dev_rows.shape[0]
    cap = bucket_capacity(n, num_shards)
    trash = block - 1

    send_rows, order, slot_shard, slot_pos = _bucket_by_shard(
        dev_rows, num_shards, block, cap)
    # Shape [1] (not scalar) so prefix out_specs like P(axis) remain
    # valid for the returned dict under shard_map.
    overflow = jnp.sum(((slot_pos >= cap)
                        & (dev_rows[order] % block != trash)
                        ).astype(jnp.int32)).reshape(1)

    # Exchange requests: recv_req[s, c] = row requested by peer s.
    recv_req = lax.all_to_all(send_rows, axis, split_axis=0, concat_axis=0,
                              tiled=True).reshape(num_shards, cap)
    # Serve from the local shard block: one fused [emb | w | show | click]
    # payload so the reply path is a single collective.
    d = table.dim
    served = jnp.concatenate([
        table.emb[recv_req],                  # [S, C, D]
        table.w[recv_req][..., None],
        table.show[recv_req][..., None],
        table.click[recv_req][..., None],
    ], axis=-1)                               # [S, C, D+3]
    reply = lax.all_to_all(
        served.reshape(num_shards * cap, d + 3), axis,
        split_axis=0, concat_axis=0, tiled=True
    ).reshape(num_shards, cap, d + 3)
    # Route replies back: reply[s, c] = value from shard s for my bucket c.
    unorder = jnp.argsort(order)
    in_cap = slot_pos < cap
    picked = reply[slot_shard, jnp.where(in_cap, slot_pos, 0)]
    picked = jnp.where(in_cap[:, None], picked, 0)[unorder]
    return {
        "emb": picked[:, :d],
        "w": picked[:, d],
        "show": picked[:, d + 1],
        "click": picked[:, d + 2],
        "overflow": overflow,
    }


def push_local(table: PassTable, dev_rows: jax.Array, grad_emb: jax.Array,
               grad_w: jax.Array, shows: jax.Array, clicks: jax.Array, *,
               axis: str, opt: Optional[SparseOptimizer] = None) -> PassTable:
    """Per-device push: exact dedup + fused sparse optimizer update.

    dev_rows [n]; grad_emb [n, D]; grad_w/shows/clicks [n]. Padding entries
    must carry zero grads (guaranteed upstream because padding ids map to
    the discard segment) — they land in the trash row regardless.
    """
    if opt is None:
        opt = SparseAdagrad()
    ke = opt.emb_state_width(table.dim)
    kw = opt.w_state_width()
    if table.emb_state.shape[-1] != ke or table.w_state.shape[-1] != kw:
        raise ValueError(
            f"optimizer {type(opt).__name__} expects state widths "
            f"({ke}, {kw}) but table carries "
            f"({table.emb_state.shape[-1]}, {table.w_state.shape[-1]}) — "
            f"push opt must match the TableConfig.optimizer the table was "
            f"built with")
    num_shards = table.num_shards
    block = table.rows_per_shard + 1
    n = dev_rows.shape[0]
    d = table.dim
    cap = bucket_capacity(n, num_shards)
    trash = block - 1

    send_rows, order, slot_shard, slot_pos = _bucket_by_shard(
        dev_rows, num_shards, block, cap)

    # Payload per bucket cell: [grad_emb D | grad_w | show | click].
    payload = jnp.concatenate([
        grad_emb, grad_w[:, None], shows[:, None], clicks[:, None]], axis=-1)
    sorted_payload = payload[order]
    send_payload = jnp.zeros((num_shards, cap, d + 3), payload.dtype)
    # Out-of-range positions (overflow) are dropped by the scatter.
    send_payload = send_payload.at[slot_shard, slot_pos].add(
        sorted_payload, mode="drop")

    recv_rows = lax.all_to_all(send_rows, axis, split_axis=0, concat_axis=0,
                               tiled=True).reshape(num_shards * cap)
    recv_payload = lax.all_to_all(
        send_payload.reshape(num_shards * cap, d + 3), axis,
        split_axis=0, concat_axis=0, tiled=True
    ).reshape(num_shards * cap, d + 3)

    # --- owner-side exact merge (role of dynamic_merge_grad) -------------
    m = num_shards * cap
    row_order = jnp.argsort(recv_rows)
    rows_s = recv_rows[row_order]
    pay_s = recv_payload[row_order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])
    seg_ids = jnp.cumsum(is_start) - 1
    merged = jax.ops.segment_sum(pay_s, seg_ids, num_segments=m)  # [m, d+3]
    merged_per_elem = merged[seg_ids]
    rep = is_start & (rows_s != trash)  # one update per real row

    g_emb = merged_per_elem[:, :d]
    g_w = merged_per_elem[:, d]
    g_show = merged_per_elem[:, d + 1]
    g_click = merged_per_elem[:, d + 2]

    # Gather current state at touched rows, apply optimizer, write deltas.
    cur_emb = table.emb[rows_s]
    cur_emb_st = table.emb_state[rows_s]
    cur_w = table.w[rows_s]
    cur_w_st = table.w_state[rows_s]

    new_emb, new_emb_st = opt.update_vector(cur_emb, cur_emb_st, g_emb)
    new_w, new_w_st = opt.update_scalar(cur_w, cur_w_st, g_w)

    repf = rep.astype(table.emb.dtype)
    emb = table.emb.at[rows_s].add(repf[:, None] * (new_emb - cur_emb))
    emb_st = table.emb_state.at[rows_s].add(
        repf[:, None] * (new_emb_st - cur_emb_st))
    w = table.w.at[rows_s].add(repf * (new_w - cur_w))
    w_st = table.w_state.at[rows_s].add(
        repf[:, None] * (new_w_st - cur_w_st))
    show = table.show.at[rows_s].add(repf * g_show)
    click = table.click.at[rows_s].add(repf * g_click)

    # Re-zero the trash row so padding pulls keep returning zeros (the
    # optimizer state keeps its init there; only value rows must be 0).
    zero_rows = jnp.arange(1) + trash
    emb = emb.at[zero_rows].set(0.0)
    w = w.at[zero_rows].set(0.0)
    show = show.at[zero_rows].set(0.0)
    click = click.at[zero_rows].set(0.0)

    return PassTable(emb=emb, emb_state=emb_st, w=w, w_state=w_st,
                     show=show, click=click,
                     rows_per_shard=table.rows_per_shard,
                     num_shards=table.num_shards)


# ---------------------------------------------------------------------------
# Standalone jitted wrappers (tests + simple trainers). Production train
# steps inline pull_local/push_local into their own shard_map body.
# ---------------------------------------------------------------------------

def make_pull_fn(mesh: Mesh, axis: str = "dp"):
    """Jitted (table, dev_rows) -> pulled dict, table/ids sharded on axis.

    ``P(axis)`` is a pytree prefix: it shards every PassTable leaf's
    leading dim over the table axis.
    """

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    def pull(table: PassTable, dev_rows: jax.Array):
        return pull_local(table, dev_rows, axis=axis)

    return pull


def make_push_fn(mesh: Mesh, axis: str = "dp",
                 opt: Optional[SparseOptimizer] = None):
    """Jitted sparse-grad apply with table donation."""

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)
    def push_sm(table, dev_rows, g_emb, g_w, shows, clicks):
        return push_local(table, dev_rows, g_emb, g_w, shows, clicks,
                          axis=axis, opt=opt)

    return jax.jit(push_sm, donate_argnums=(0,))
