"""Replicated small-table caches, host row cache, string-keyed input table.

Roles (SURVEY.md §2.2 "GpuReplicaCache / InputTable",
``fleet/box_wrapper.h:63-197``):
- ``ReplicaCache``: a small embedding table replicated in every device's
  HBM (reference: per-GPU copy filled by ``PullCacheValue``; consumed by
  the ``pull_cache_value`` op). TPU: one jnp array with replicated
  sharding — lookups are local gathers, no collective.
- ``HostRowCache``: the WARM tier of the hierarchical serving table — a
  bounded host-RAM row array with CLOCK eviction and batched
  ``get_rows``/``put_rows`` (role of the BoxPS mem-tier working set
  between the per-GPU HBM copies and the SSD table; "Dissecting
  Embedding Bag Performance in DLRM Inference" is the why: the gather
  path dominates inference, so misses must hit RAM, not disk).
- ``InputTable``: CPU-side string→index dictionary whose indices flow
  through the graph into a device aux table (reference ``lookup_input``
  op + ``InputTableDataset``): map raw string features (e.g. URLs) to
  dense rows at data-load time.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ReplicaCache:
    """Small dense table replicated across devices."""

    def __init__(self, values: np.ndarray, *, mesh: Optional[Mesh] = None):
        arr = jnp.asarray(values, jnp.float32)
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, P()))
        self.values = arr

    @property
    def num_rows(self) -> int:
        return int(self.values.shape[0])

    def pull(self, ids: jax.Array) -> jax.Array:
        """ids [...] int32 → [..., dim]; out-of-range ids give row 0
        (jnp clip semantics made explicit)."""
        safe = jnp.clip(ids, 0, self.num_rows - 1)
        out = self.values[safe]
        in_range = (ids >= 0) & (ids < self.num_rows)
        return jnp.where(in_range[..., None], out, 0.0)


class HostRowCache:
    """Bounded host-RAM row cache with CLOCK eviction, batched API.

    Fixed-width float32 rows keyed by uint64 feasign. ``capacity == 0``
    means unbounded (the backing arrays grow by doubling and nothing is
    ever evicted); a bounded cache evicts CLOCK-cold rows through the
    ``on_evict(keys, vals)`` callback (the spill hook the serving tier
    points at its disk shards) — one batched call per ``put_rows``, so a
    burst of inserts pays one disk write, not one per row.

    NOT internally locked: the owner (the tiered serving table, under
    the predictor lock) serializes every call — the same caller-
    serialized contract as the KeyIndex numpy fallback.
    """

    def __init__(self, width: int, capacity: int = 0,
                 on_evict: Optional[Callable[[np.ndarray, np.ndarray],
                                             None]] = None):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0: {capacity}")
        self.width = int(width)
        self.capacity = int(capacity)
        self.on_evict = on_evict
        size = min(capacity, 1024) if capacity else 1024
        size = max(size, 8)
        self._vals = np.zeros((size, self.width), np.float32)
        self._keys = np.zeros((size,), np.uint64)
        self._ref = np.zeros((size,), bool)     # CLOCK reference bits
        self._slot: Dict[int, int] = {}         # key -> slot
        self._free: List[int] = list(range(size - 1, -1, -1))
        self._hand = 0

    def __len__(self) -> int:
        return len(self._slot)

    def contains(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        return np.fromiter((int(k) in self._slot for k in keys), bool,
                           count=keys.shape[0])

    def get_rows(self, keys: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """(vals [n, width], hit [n]): values aligned to ``keys`` (zeros
        where absent). Hits get their CLOCK reference bit set."""
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        slots = np.fromiter((self._slot.get(int(k), -1) for k in keys),
                            np.int64, count=n)
        hit = slots >= 0
        vals = np.zeros((n, self.width), np.float32)
        if hit.any():
            s = slots[hit]
            vals[hit] = self._vals[s]
            self._ref[s] = True
        return vals, hit

    def _grow(self) -> None:
        old = self._vals.shape[0]
        new = old * 2
        if self.capacity:
            new = min(new, self.capacity)  # never overshoot the budget
        # graftlint: allow-lock(caller-serialized: every HostRowCache call runs under the owning predictor's lock)
        self._vals = np.concatenate(
            [self._vals, np.zeros((new - old, self.width), np.float32)])
        self._keys = np.concatenate(
            [self._keys, np.zeros((new - old,), np.uint64)])
        # graftlint: allow-lock(caller-serialized: every HostRowCache call runs under the owning predictor's lock)
        self._ref = np.concatenate(
            [self._ref, np.zeros((new - old,), bool)])
        self._free.extend(range(new - 1, old - 1, -1))

    def _evict_slots(self, n: int) -> List[int]:
        """CLOCK sweep: free ``n`` cold slots (second-chance — a set ref
        bit buys one lap). Evicted rows batch out through on_evict."""
        size = self._vals.shape[0]
        out: List[int] = []
        # <= 2 laps always suffice: the first lap clears every ref bit
        # it passes, so the second finds only cold slots.
        for _ in range(2 * size):
            if len(out) >= n:
                break
            s = self._hand
            self._hand = (self._hand + 1) % size
            k = int(self._keys[s])
            if k not in self._slot or self._slot[k] != s:
                continue  # free or stale slot
            if self._ref[s]:
                self._ref[s] = False
                continue
            out.append(s)
            del self._slot[k]
        if out and self.on_evict is not None:
            s = np.asarray(out, np.int64)
            self.on_evict(self._keys[s].copy(), self._vals[s].copy())
        return out

    def put_rows(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Insert/overwrite rows (last duplicate wins). Bounded caches
        evict cold rows (one batched on_evict) to make room."""
        keys = np.asarray(keys, np.uint64)
        vals = np.asarray(vals, np.float32)
        if keys.shape[0] != vals.shape[0] or (
                vals.ndim != 2 or vals.shape[1] != self.width):
            raise ValueError(
                f"put_rows shape mismatch: {keys.shape} keys vs "
                f"{vals.shape} vals (width {self.width})")
        for i in range(keys.shape[0]):
            k = int(keys[i])
            s = self._slot.get(k)
            if s is None:
                if not self._free:
                    if self.capacity == 0 or (
                            self._vals.shape[0] < self.capacity):
                        self._grow()
                    else:
                        self._free.extend(self._evict_slots(
                            max(1, keys.shape[0] - i)))
                        if not self._free:  # capacity smaller than batch
                            continue
                s = self._free.pop()
                self._slot[k] = s
                self._keys[s] = k
            self._vals[s] = vals[i]
            self._ref[s] = True

    def pop_rows(self, keys: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Remove and return (found [n], vals [n, width]) — the tier-
        promotion read (exclusive tiers: a row leaving RAM-ward must
        leave this tier)."""
        keys = np.asarray(keys, np.uint64)
        n = keys.shape[0]
        found = np.zeros((n,), bool)
        vals = np.zeros((n, self.width), np.float32)
        for i in range(n):
            k = int(keys[i])
            s = self._slot.pop(k, None)
            if s is None:
                continue
            found[i] = True
            vals[i] = self._vals[s]
            self._ref[s] = False
            self._free.append(s)
        return found, vals


class InputTable:
    """Append-only string→index table (role of BoxWrapper InputTable:
    lock-sharded insert at load time, frozen lookup at train time)."""

    def __init__(self):
        self._map: Dict[str, int] = {}
        self._keys: List[str] = []
        self._lock = threading.Lock()

    def add(self, key: str) -> int:
        with self._lock:
            idx = self._map.get(key)
            if idx is None:
                idx = len(self._keys)
                self._map[key] = idx
                self._keys.append(key)
            return idx

    def add_many(self, keys: Sequence[str]) -> np.ndarray:
        return np.fromiter((self.add(k) for k in keys), np.int32,
                           count=len(keys))

    def lookup(self, key: str) -> int:
        """-1 when absent (reference miss semantics)."""
        return self._map.get(key, -1)

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._keys)

    def key_at(self, idx: int) -> str:
        return self._keys[idx]
