"""Replicated small-table caches + string-keyed input table.

Roles (SURVEY.md §2.2 "GpuReplicaCache / InputTable",
``fleet/box_wrapper.h:63-197``):
- ``ReplicaCache``: a small embedding table replicated in every device's
  HBM (reference: per-GPU copy filled by ``PullCacheValue``; consumed by
  the ``pull_cache_value`` op). TPU: one jnp array with replicated
  sharding — lookups are local gathers, no collective.
- ``InputTable``: CPU-side string→index dictionary whose indices flow
  through the graph into a device aux table (reference ``lookup_input``
  op + ``InputTableDataset``): map raw string features (e.g. URLs) to
  dense rows at data-load time.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ReplicaCache:
    """Small dense table replicated across devices."""

    def __init__(self, values: np.ndarray, *, mesh: Optional[Mesh] = None):
        arr = jnp.asarray(values, jnp.float32)
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, P()))
        self.values = arr

    @property
    def num_rows(self) -> int:
        return int(self.values.shape[0])

    def pull(self, ids: jax.Array) -> jax.Array:
        """ids [...] int32 → [..., dim]; out-of-range ids give row 0
        (jnp clip semantics made explicit)."""
        safe = jnp.clip(ids, 0, self.num_rows - 1)
        out = self.values[safe]
        in_range = (ids >= 0) & (ids < self.num_rows)
        return jnp.where(in_range[..., None], out, 0.0)


class InputTable:
    """Append-only string→index table (role of BoxWrapper InputTable:
    lock-sharded insert at load time, frozen lookup at train time)."""

    def __init__(self):
        self._map: Dict[str, int] = {}
        self._keys: List[str] = []
        self._lock = threading.Lock()

    def add(self, key: str) -> int:
        with self._lock:
            idx = self._map.get(key)
            if idx is None:
                idx = len(self._keys)
                self._map[key] = idx
                self._keys.append(key)
            return idx

    def add_many(self, keys: Sequence[str]) -> np.ndarray:
        return np.fromiter((self.add(k) for k in keys), np.int32,
                           count=len(keys))

    def lookup(self, key: str) -> int:
        """-1 when absent (reference miss semantics)."""
        return self._map.get(key, -1)

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._keys)

    def key_at(self, idx: int) -> str:
        return self._keys[idx]
