"""Hash-bucketed host feature store: FeatureStore surface at 100M+ keys.

Role of the reference's sharded CPU-side pass build: ``PreBuildTask``
dedups pass keys into 16-way shard buckets processed by threads
(``ps_gpu_wrapper.cc:114``), and the brpc PS shards tables by key range.
The flat :class:`FeatureStore` re-sorts its ENTIRE key array on every
pass write-back (O(N log N) with N = total resident features) — fine at
10M keys, a wall at 1B. Here keys are split across ``num_buckets``
hash-range buckets (same splitmix-style mix as the SSD tier so sequential
feasign ranges spread); every operation touches only the buckets its keys
hash into, and independent buckets run on a thread pool (numpy releases
the GIL for the heavy merges).

Checkpoint layout: ``<path>/bucket-NNNN/`` per bucket plus a top-level
meta json. Flat FeatureStore dumps load transparently (scattered on
load), so single-store checkpoints migrate forward.
"""

from __future__ import annotations

import json
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import log
from paddlebox_tpu.embedding.store import _FIELDS, FeatureStore
from paddlebox_tpu.embedding.table import TableConfig


def _bucket_of(keys: np.ndarray, num_buckets: int) -> np.ndarray:
    h = keys ^ (keys >> np.uint64(33))
    with np.errstate(over="ignore"):
        h = h * np.uint64(0xFF51AFD7ED558CCD)
    return (h % np.uint64(num_buckets)).astype(np.int64)


class ShardedFeatureStore:
    """Drop-in FeatureStore replacement, bucketed for scale."""

    shared = False

    def __init__(self, config: TableConfig, num_buckets: int = 64,
                 seed: int = 0, num_threads: int = 8):
        self.config = config
        self.num_buckets = int(num_buckets)
        # Per-key deterministic init makes one seed safe across buckets.
        self._buckets: List[FeatureStore] = [
            FeatureStore(config, seed=seed) for _ in range(self.num_buckets)]
        self.opt = self._buckets[0].opt
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(num_threads, self.num_buckets)),
            thread_name_prefix="store-shard")

    # -- scatter/gather plumbing ------------------------------------------

    def _split(self, keys: np.ndarray
               ) -> List[Tuple[int, np.ndarray, np.ndarray]]:
        """[(bucket, indices_into_keys, keys[indices]), ...] for non-empty
        buckets. Index lists preserve input order, so sorted inputs stay
        sorted within each bucket."""
        b = _bucket_of(keys, self.num_buckets)
        order = np.argsort(b, kind="stable")
        sorted_b = b[order]
        starts = np.searchsorted(sorted_b, np.arange(self.num_buckets + 1))
        out = []
        for i in range(self.num_buckets):
            lo, hi = starts[i], starts[i + 1]
            if lo < hi:
                idx = order[lo:hi]
                out.append((i, idx, keys[idx]))
        return out

    def _map(self, fn, parts):
        if len(parts) <= 1:
            return [fn(*p) for p in parts]
        return list(self._pool.map(lambda p: fn(*p), parts))

    # -- size / membership -------------------------------------------------

    @property
    def num_features(self) -> int:
        return sum(s.num_features for s in self._buckets)

    def contains(self, keys: np.ndarray) -> np.ndarray:
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros((k.shape[0],), bool)
        parts = self._split(k)
        res = self._map(lambda b, idx, kk: self._buckets[b].contains(kk),
                        parts)
        for (b, idx, _), r in zip(parts, res):
            out[idx] = r
        return out

    def dirty_keys(self) -> np.ndarray:
        parts = [s.dirty_keys() for s in self._buckets]
        parts = [p for p in parts if p.size]
        return (np.concatenate(parts) if parts
                else np.empty((0,), np.uint64))

    def rows_by_coldness(self) -> np.ndarray:
        stats = [s.key_stats() for s in self._buckets]
        keys = np.concatenate([k for k, _ in stats]) if stats else \
            np.empty((0,), np.uint64)
        show = np.concatenate([v for _, v in stats]) if stats else \
            np.empty((0,), np.float32)
        return keys[np.argsort(show, kind="stable")]

    def pop_rows(self, keys: np.ndarray
                 ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        k = np.unique(np.ascontiguousarray(keys, np.uint64))
        parts = self._split(k)
        res = self._map(lambda b, idx, kk: self._buckets[b].pop_rows(kk),
                        parts)
        out_keys = [r[0] for r in res if r[0].size]
        if not out_keys:
            empty = self._buckets[0].pull_for_pass(
                np.empty((0,), np.uint64))
            return np.empty((0,), np.uint64), empty
        keys_cat = np.concatenate(out_keys)
        vals_cat = {f: np.concatenate([r[1][f] for r in res if r[0].size])
                    for f in _FIELDS}
        return keys_cat, vals_cat

    # -- pass build --------------------------------------------------------

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        n = k.shape[0]
        parts = self._split(k)
        res = self._map(
            lambda b, idx, kk: self._buckets[b].pull_for_pass(kk), parts)
        if not parts:
            return self._buckets[0].pull_for_pass(k)
        out = {f: np.empty((n,) + v.shape[1:], v.dtype)
               for f, v in res[0].items()}
        for (b, idx, _), r in zip(parts, res):
            for f, v in r.items():
                out[f][idx] = v
        return out

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray]) -> None:
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        parts = self._split(k)
        self._map(
            lambda b, idx, kk: self._buckets[b].push_from_pass(
                kk, {f: v[idx] for f, v in values.items()}),
            parts)

    # -- maintenance -------------------------------------------------------

    def unseen_for(self, keys: np.ndarray) -> np.ndarray:
        """Unseen-days ages aligned to ``keys`` (0 where absent)."""
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros(k.shape, np.int32)
        parts = self._split(k)
        res = self._map(lambda b, idx, kk: self._buckets[b].unseen_for(kk),
                        parts)
        for (b, idx, _), r in zip(parts, res):
            out[idx] = r
        return out

    def shrink(self, *, min_show: float = 0.0) -> int:
        # Lifecycle policy (FLAGS_table_* decay/TTL/min-show) resolves
        # inside each bucket's FeatureStore.shrink — per-bucket ages are
        # independent, so the bucketed shrink equals the flat one.
        return sum(self._pool.map(
            lambda s: s.shrink(min_show=min_show), self._buckets))

    # -- checkpoint --------------------------------------------------------

    def _bucket_dir(self, path: str, i: int) -> str:
        return os.path.join(path, f"bucket-{i:04d}")

    def _write_meta(self, path: str, kind: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path,
                               f"{self.config.name}.sharded.json"),
                  "w") as f:
            json.dump({"num_buckets": self.num_buckets, "kind": kind,
                       "table": self.config.name}, f)

    def save_base(self, path: str) -> None:
        self._write_meta(path, "base")
        list(self._pool.map(
            lambda i: self._buckets[i].save_base(self._bucket_dir(path, i)),
            range(self.num_buckets)))
        log.vlog(0, "sharded save_base: %d features x %d buckets -> %s",
                 self.num_features, self.num_buckets, path)

    def save_delta(self, path: str) -> None:
        self._write_meta(path, "delta")
        list(self._pool.map(
            lambda i: self._buckets[i].save_delta(self._bucket_dir(path, i)),
            range(self.num_buckets)))

    def save_xbox(self, path: str) -> int:
        self._write_meta(path, "xbox")
        return sum(self._pool.map(
            lambda i: self._buckets[i].save_xbox(self._bucket_dir(path, i)),
            range(self.num_buckets)))

    def load(self, path: str, kind: str = "base") -> None:
        meta_path = os.path.join(path, f"{self.config.name}.sharded.json")
        flat_npz = os.path.join(path, f"{self.config.name}.{kind}.npz")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta["num_buckets"] != self.num_buckets:
                raise ValueError(
                    f"checkpoint has {meta['num_buckets']} buckets, store "
                    f"has {self.num_buckets} — rebucketing not supported; "
                    f"construct the store with the matching count")
            list(self._pool.map(
                lambda i: self._buckets[i].load(self._bucket_dir(path, i),
                                                kind),
                range(self.num_buckets)))
            return
        if os.path.exists(flat_npz):
            # Migration path: a flat FeatureStore dump scatters in.
            data = np.load(flat_npz)
            keys = data["keys"].astype(np.uint64)
            vals = {f: data[f] for f in _FIELDS}
            if kind == "base":
                parts = self._split(keys)
                hit = set()
                for b, idx, kk in parts:
                    hit.add(b)
                    self._buckets[b].set_all(
                        kk, {f: v[idx] for f, v in vals.items()})
                empty_k = np.empty((0,), np.uint64)
                for i in range(self.num_buckets):
                    if i not in hit:
                        self._buckets[i].set_all(empty_k, {
                            f: np.empty((0,) + v.shape[1:], v.dtype)
                            for f, v in vals.items()})
            else:
                self.push_from_pass(keys, vals)
            return
        raise FileNotFoundError(
            f"no sharded meta or flat {kind} dump under {path}")
