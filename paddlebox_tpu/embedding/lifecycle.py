"""Feature lifecycle policy: show/click decay, unseen-day TTL, min-show.

Role of the table lifecycle the reference runs at every day boundary
(BoxPS ``ShrinkTable`` / pslib shrink driven by the CtrCommonAccessor's
``show_click_decay_rate``, ``delete_after_unseen_days`` and
``delete_threshold``): without it the feature store grows monotonically
forever under streaming traffic. Every store variant's ``shrink()``
resolves its effective policy through :func:`shrink_params`, so the
three ``FLAGS_table_*`` knobs act fleet-wide across the host, device,
sharded, grouped, SSD-tiered and multi-host tiers without touching any
call site.

``unseen_days`` semantics (matching ``delete_after_unseen_days``): each
row carries an integer age, reset to 0 by any training write-back of
its key and bumped by 1 at every ``shrink()``; a row whose bumped age
EXCEEDS ``FLAGS_table_ttl_days`` is evicted. Ages are tracked host-side
beside the key index (never inside the value record — the checkpoint
and wire formats are unchanged), so a process restart grants surviving
rows a fresh TTL lease; ONLINE.md documents the difference from the
reference's persisted accessor field.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from paddlebox_tpu.core import flags


def shrink_params(config, min_show: float) -> Tuple[float, int, float]:
    """Effective (decay, ttl_days, min_show) for one shrink() call:
    the flag overrides layered onto the table config and the caller's
    threshold. Every store variant calls this so the six shrink
    implementations can never drift apart on policy."""
    decay = float(flags.flag("table_decay_rate")) or float(
        config.show_click_decay)
    ttl = int(flags.flag("table_ttl_days"))
    eff_min_show = max(float(min_show), float(flags.flag("table_min_show")))
    return decay, ttl, eff_min_show


class RowAges:
    """Sorted-key → unseen-days side table for rows that live OUTSIDE a
    FeatureStore's aligned age array (the SSD tier's disk-resident
    rows): the tier wrapper records each row's age when it spills, bumps
    the whole table per shrink, and hands ages back on stage-in so a
    disk round-trip does not reset the TTL clock. Not thread-safe —
    callers hold their tier lock."""

    def __init__(self):
        self._keys = np.empty((0,), np.uint64)
        self._age = np.empty((0,), np.int32)

    def _locate(self, k: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        if self._keys.size == 0:
            return (np.zeros(k.shape, bool),
                    np.zeros(k.shape, np.int64))
        pos = np.minimum(np.searchsorted(self._keys, k),
                         self._keys.size - 1)
        return self._keys[pos] == k, pos

    def set(self, keys: np.ndarray, ages: np.ndarray) -> None:
        """Upsert ages for ``keys`` (any order)."""
        k = np.asarray(keys, np.uint64)
        if k.size == 0:
            return
        a = np.broadcast_to(np.asarray(ages, np.int32), k.shape)
        order = np.argsort(k, kind="stable")
        k, a = k[order], a[order]
        found, pos = self._locate(k)
        self._age[pos[found]] = a[found]
        new = ~found
        if new.any():
            self._keys = np.concatenate([self._keys, k[new]])
            self._age = np.concatenate([self._age, a[new]])
            order = np.argsort(self._keys, kind="stable")
            self._keys = self._keys[order]
            self._age = self._age[order]

    def drop(self, keys: np.ndarray) -> None:
        k = np.asarray(keys, np.uint64)
        if k.size == 0 or self._keys.size == 0:
            return
        keep = ~np.isin(self._keys, k)
        self._keys = self._keys[keep]
        self._age = self._age[keep]

    def bump(self) -> None:
        self._age += 1

    def ages_for(self, keys: np.ndarray) -> np.ndarray:
        """Ages aligned to ``keys`` (0 where untracked)."""
        k = np.asarray(keys, np.uint64)
        out = np.zeros(k.shape, np.int32)
        if k.size and self._keys.size:
            found, pos = self._locate(k)
            out[found] = self._age[pos[found]]
        return out

    def clear(self) -> None:
        self._keys = np.empty((0,), np.uint64)
        self._age = np.empty((0,), np.int32)
