"""Host-RAM persistent feature store — the between-passes tier.

Role of the CPU parameter-server tables that back the device cache between
passes: ``MemorySparseTable`` / ``SSDSparseTable``
(``distributed/ps/table/memory_sparse_table.h``, ``ssd_sparse_table.h``)
and the BoxPS SSD→mem staging (``LoadSSD2Mem``, ``box_wrapper.h:635``),
plus base/delta model save (``SaveBase/SaveDelta``, ``box_wrapper.h:628``).

TPU-first: no RPC server — the store is a sorted-key columnar structure in
host RAM (keys ascending; one numpy row per feature), accessed only at
pass boundaries (build / write-back). The hot loops (locate, row
gather/scatter, sorted merge, per-key init) run through the native store
engine (``native/store.cc``, role of the reference's C++ PreBuildTask/
BuildPull walk, ps_gpu_wrapper.cc:114,362) with exact numpy fallbacks.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import flags, log, monitor
from paddlebox_tpu.embedding import lifecycle
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.native import store_py as native_store


def quantize_xbox_vals(vals: Dict[str, np.ndarray]
                       ) -> Dict[str, np.ndarray]:
    """Apply the ``xbox_quant_bits`` flag to a serving export's value
    dict: symmetric per-row int8/int16 embeddings + f32 scales (4x/2x
    smaller artifacts shipping to serving every pass); w stays f32.
    The loader (serving.load_xbox_model) dequantizes transparently."""
    bits = int(flags.flag("xbox_quant_bits"))
    if not bits:
        return vals
    if bits not in (8, 16):
        raise ValueError(f"xbox_quant_bits must be 0, 8 or 16: {bits}")
    emb = np.asarray(vals["emb"], np.float32)
    qmax = (1 << (bits - 1)) - 1
    scale = (np.abs(emb).max(axis=1) / qmax if emb.size
             else np.zeros((0,), np.float32))
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.rint(emb / scale[:, None]), -qmax, qmax).astype(
        np.int8 if bits == 8 else np.int16)
    return {"emb_q": q, "emb_scale": scale, "w": vals["w"]}

_FIELDS = ("emb", "emb_state", "w", "w_state", "show", "click")


def _per_key_uniform(keys: np.ndarray, dim: int, seed: np.uint64,
                     scale: float) -> np.ndarray:
    """[n, dim] uniform(-scale, scale) from a murmur3-finalizer counter
    hash of (key's low 32 bits, column, seed) — order-independent init.

    Deliberately 32-bit: the device store tier initializes new rows ON
    DEVICE from a 4-byte-per-key transfer (device_store.py — uint64 is
    unavailable under default jax x64 config, and the narrow transfer is
    what keeps cold-start builds off the slow host↔device link). numpy,
    native C++ (pbx_init_uniform) and the jnp twin are bit-exact.
    """
    lo = (keys.astype(np.uint64) & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return _u32_uniform(lo, dim, np.uint32(np.uint64(seed)
                                           & np.uint64(0xFFFFFFFF)), scale)


def _u32_uniform(keys_lo: np.ndarray, dim: int, seed: np.uint32,
                 scale: float) -> np.ndarray:
    k = keys_lo.astype(np.uint32)[:, None]
    j = np.arange(1, dim + 1, dtype=np.uint32)[None, :]
    with np.errstate(over="ignore"):
        z = k + j * np.uint32(0x9E3779B9) + seed
        z ^= z >> np.uint32(16)
        z *= np.uint32(0x85EBCA6B)
        z ^= z >> np.uint32(13)
        z *= np.uint32(0xC2B2AE35)
        z ^= z >> np.uint32(16)
    u = (z >> np.uint32(8)).astype(np.float32) * np.float32(1.0 / (1 << 24))
    return ((np.float32(2.0) * u - np.float32(1.0))
            * np.float32(scale)).astype(np.float32)


class FeatureStore:
    """Sorted-key columnar feature store with base+delta checkpointing."""

    #: Per-process replica (each rank owns its own copy). Shared remote
    #: tiers (PSBackedStore) override this so day-end maintenance such as
    #: shrink runs once, not world_size times.
    shared = False

    def __init__(self, config: TableConfig, seed: int = 0):
        from paddlebox_tpu.embedding.optimizers import make_sparse_optimizer
        self.config = config
        self.opt = make_sparse_optimizer(config)
        d = config.dim
        self._ke = self.opt.emb_state_width(d)
        self._kw = self.opt.w_state_width()
        self._keys = np.empty((0,), np.uint64)
        self._vals: Dict[str, np.ndarray] = {
            "emb": np.empty((0, d), np.float32),
            "emb_state": np.empty((0, self._ke), np.float32),
            "w": np.empty((0,), np.float32),
            "w_state": np.empty((0, self._kw), np.float32),
            "show": np.empty((0,), np.float32),
            "click": np.empty((0,), np.float32),
        }
        self._seed = np.uint64(seed)
        # Per-row unseen-days age, aligned with _keys (lifecycle TTL:
        # bumped by shrink, reset by any training write-back; lives
        # beside the index, never in the value record — checkpoints
        # are unchanged and a restart grants a fresh TTL lease).
        self._unseen = np.empty((0,), np.int32)
        self._lock = threading.Lock()
        # Keys touched since the last save_base (delta set). Kept as a
        # list of per-push arrays, compacted lazily — a sorted union per
        # push was an O(N log N) tax on every pass write-back.
        self._dirty_parts: list = []
        # shrink() decays every row and may evict — states a delta cannot
        # express. Until the next save_base, save_delta must refuse.
        self._shrunk_since_base = False

    # -- size --------------------------------------------------------------

    @property
    def num_features(self) -> int:
        with self._lock:
            return int(self._keys.shape[0])

    def _locate(self, k: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(found mask, clipped positions) of keys k in the sorted store.
        Caller must hold the lock."""
        return native_store.ss_locate(self._keys, k)

    def _dirty_compact(self) -> np.ndarray:
        """Sorted unique dirty keys; caller must hold the lock."""
        if len(self._dirty_parts) > 1:
            # np.unique, not dedup_keys: key 0 is a legal dirty key here
            # (dedup_keys drops the null feasign by design).
            self._dirty_parts = [np.unique(
                np.concatenate(self._dirty_parts))]
        return (self._dirty_parts[0] if self._dirty_parts
                else np.empty((0,), np.uint64))

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """Membership mask for keys (any order)."""
        k = np.ascontiguousarray(keys, np.uint64)
        with self._lock:
            found, _ = self._locate(k)
        return found

    def pop_rows(self, keys: np.ndarray
                 ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Remove and return rows for the present subset of ``keys`` —
        the extraction half of spilling to the SSD tier (role of the
        mem→SSD movement in BoxPS CheckNeedLimitMem/ShrinkResource)."""
        k = np.unique(np.ascontiguousarray(keys, np.uint64))
        with self._lock:
            found, pos = self._locate(k)
            take = pos[found]
            out_keys = self._keys[take].copy()
            out_vals = {f: self._vals[f][take].copy() for f in _FIELDS}
            keep = np.ones(self._keys.shape[0], bool)
            keep[take] = False
            self._keys = self._keys[keep]
            self._unseen = self._unseen[keep]
            for f in _FIELDS:
                self._vals[f] = self._vals[f][keep]
            # Popped keys leave the delta set — they are no longer present
            # in RAM and the tiered wrapper snapshots disk separately.
            dirty = self._dirty_compact()
            if dirty.size:
                self._dirty_parts = [np.setdiff1d(dirty, out_keys,
                                                  assume_unique=True)]
        return out_keys, out_vals

    def dirty_keys(self) -> np.ndarray:
        """Keys touched since the last save_base (the delta set)."""
        with self._lock:
            return self._dirty_compact().copy()

    def rows_by_coldness(self) -> np.ndarray:
        """Keys sorted by ascending show (coldest first) for eviction."""
        with self._lock:
            order = np.argsort(self._vals["show"], kind="stable")
            return self._keys[order].copy()

    def key_stats(self) -> Tuple[np.ndarray, np.ndarray]:
        """(keys, show) copies — lets composing stores (sharded/tiered)
        merge eviction order globally without reaching into internals."""
        with self._lock:
            return self._keys.copy(), self._vals["show"].copy()

    # -- pass build --------------------------------------------------------

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        """Fetch values for a pass's sorted unique keys; unseen keys are
        initialized (role of BuildPull fetching value pointers from the CPU
        PS, ps_gpu_wrapper.cc:362; init ranges role of CtrCommonAccessor)."""
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        n = k.shape[0]
        d = self.config.dim
        out = {
            "emb": np.empty((n, d), np.float32),
            "emb_state": self.opt.init_emb_state(n, d),
            "w": np.zeros((n,), np.float32),
            "w_state": self.opt.init_w_state(n),
            "show": np.zeros((n,), np.float32),
            "click": np.zeros((n,), np.float32),
        }
        with self._lock:
            found, pos_c = self._locate(k)
            # New keys: small-uniform init for emb, zeros elsewhere.
            # Deterministic PER KEY (counter-based hash, not a sequential
            # rng stream): the same feasign inits identically regardless
            # of pull order, split-pull overlap chunking, or which rank
            # asks — required for reproducible pipelined builds and for
            # replica stores to agree without communication.
            out["emb"][:] = native_store.init_uniform(
                k, d, int(self._seed), self.config.init_scale)
            if found.any():
                for f in _FIELDS:
                    native_store.gather_rows(self._vals[f], pos_c,
                                             mask=found, out=out[f])
        monitor.add("store/pass_keys", n)
        monitor.add("store/new_keys", int(n - found.sum()) if n else 0)
        return out

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray], *,
                       mark_dirty: bool = True,
                       unseen: Optional[np.ndarray] = None) -> None:
        """Write a finished pass's values back (role of EndPass write-back,
        ps_gpu_wrapper.cc:983). Vectorized sorted merge of new keys.

        ``mark_dirty=False`` is for TIER MOVEMENT (ssd_tier stage-in):
        rows identical to their disk copies must not land in the next
        save_delta — only training updates are deltas. ``unseen`` (tier
        movement too) carries the rows' unseen-days ages across the
        move so a disk round-trip does not reset the TTL clock; without
        it a training push zeroes the pushed keys' ages (the row was
        just seen) and a tier move preserves existing ages."""
        k = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        if k.shape[0] == 0:
            return
        self._check_state_widths(values)
        if unseen is not None:
            unseen = np.ascontiguousarray(unseen, np.int32)
        with self._lock:
            found, pos_c = self._locate(k)
            # Update existing rows in place.
            for f in _FIELDS:
                native_store.scatter_rows(self._vals[f], pos_c, values[f],
                                          mask=found)
            if found.any():
                if unseen is not None:
                    self._unseen[pos_c[found]] = unseen[found]
                elif mark_dirty:
                    self._unseen[pos_c[found]] = 0
            # Merge new rows LINEARLY (two sorted runs -> O(N + n) scatter;
            # a concat + argsort here would cost O((N+n) log(N+n)) on
            # every pass write-back, the scaling wall the reference's
            # 16-way sharded PreBuildTask exists to avoid).
            new_mask = ~found
            if new_mask.any():
                new_k = k[new_mask]           # sorted (subset of sorted k)
                n_old = self._keys.shape[0]
                merged_keys, src = native_store.merge_sorted(
                    self._keys, new_k)
                is_new = src >= n_old
                dst_new = np.flatnonzero(is_new)
                old_pos = np.flatnonzero(~is_new)
                self._keys = merged_keys
                for f in _FIELDS:
                    shape = (merged_keys.shape[0],) + self._vals[f].shape[1:]
                    merged = np.empty(shape, self._vals[f].dtype)
                    native_store.scatter_rows(merged, dst_new,
                                              values[f][new_mask])
                    native_store.scatter_rows(merged, old_pos,
                                              self._vals[f])
                    self._vals[f] = merged
                merged_un = np.zeros((merged_keys.shape[0],), np.int32)
                if unseen is not None:
                    merged_un[dst_new] = unseen[new_mask]
                merged_un[old_pos] = self._unseen
                self._unseen = merged_un
            if mark_dirty:
                self._dirty_parts.append(k.copy())

    # -- lifecycle maintenance --------------------------------------------

    def unseen_for(self, keys: np.ndarray) -> np.ndarray:
        """Unseen-days ages aligned to ``keys`` (0 where absent) — the
        tier wrapper reads these before spilling rows disk-ward."""
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros(k.shape, np.int32)
        with self._lock:
            found, pos_c = self._locate(k)
            if found.any():
                out[found] = self._unseen[pos_c[found]]
        return out

    def shrink(self, *, min_show: float = 0.0,
               resolved: Optional[Tuple[float, int, float]] = None) -> int:
        """Day-level table shrink (role of BoxPS ShrinkTable / pslib
        shrink): decay show/click, bump every row's unseen_days, and
        evict rows past the TTL or under the show threshold — policy
        resolved through :func:`lifecycle.shrink_params` so the
        ``FLAGS_table_*`` lifecycle knobs apply uniformly across every
        store variant. ``resolved`` = pre-resolved (decay, ttl,
        min_show) from a REMOTE policy decision (a replicated shard's
        primary forwards its resolved numbers so a backup host with
        different flags applies the identical shrink)."""
        decay, ttl, min_show = (resolved if resolved is not None
                                else lifecycle.shrink_params(self.config,
                                                             min_show))
        with self._lock:
            self._shrunk_since_base = True
            self._vals["show"] *= np.float32(decay)
            self._vals["click"] *= np.float32(decay)
            self._unseen += 1
            keep = np.ones(self._keys.shape[0], bool)
            if min_show > 0:
                keep &= self._vals["show"] >= min_show
            if ttl > 0:
                over = self._unseen > ttl
                monitor.add("store/ttl_evicted", int((keep & over).sum()))
                keep &= ~over
            evicted = int((~keep).sum())
            if evicted:
                self._keys = self._keys[keep]
                self._unseen = self._unseen[keep]
                for f in _FIELDS:
                    self._vals[f] = self._vals[f][keep]
            return evicted

    # -- checkpoint: base + delta -----------------------------------------

    def _save_arrays(self, path: str, keys: np.ndarray,
                     vals: Dict[str, np.ndarray], kind: str,
                     unseen: Optional[np.ndarray] = None) -> None:
        os.makedirs(path, exist_ok=True)
        final = os.path.join(path, f"{self.config.name}.{kind}.npz")
        # Atomic write: a crash (or a concurrent writer) mid-savez must
        # not leave a truncated npz where recovery expects a model.
        tmp = os.path.join(path, f".{self.config.name}.{kind}.tmp")
        with open(tmp, "wb") as f:
            np.savez_compressed(f, keys=keys, **vals)
        os.replace(tmp, final)
        if unseen is not None:
            # Sidecar ages file ALIGNED to the main npz's key order
            # (ONLINE.md "persisted TTL ages"): kept out of the value
            # record so the checkpoint format and every wire stay
            # unchanged, and a pre-sidecar loader simply ignores it.
            ages_final = os.path.join(
                path, f"{self.config.name}.{kind}.ages.npz")
            ages_tmp = os.path.join(
                path, f".{self.config.name}.{kind}.ages.tmp")
            with open(ages_tmp, "wb") as f:
                np.savez_compressed(
                    f, unseen=np.ascontiguousarray(unseen, np.int32))
            os.replace(ages_tmp, ages_final)
        meta = {"kind": kind, "num_features": int(keys.shape[0]),
                "dim": self.config.dim, "table": self.config.name}
        with open(os.path.join(path, f"{self.config.name}.{kind}.meta.json"),
                  "w") as f:
            json.dump(meta, f)

    def _load_ages(self, path: str, kind: str, n: int
                   ) -> Optional[np.ndarray]:
        """The unseen-days sidecar beside a checkpoint npz (None for
        pre-sidecar checkpoints or a row-count mismatch — those rows
        restart their TTL lease, the documented legacy behavior)."""
        f = os.path.join(path, f"{self.config.name}.{kind}.ages.npz")
        if not os.path.exists(f):
            return None
        ages = np.load(f)["unseen"]
        if ages.shape[0] != n:
            log.warning("ages sidecar %s has %d rows, checkpoint has %d "
                        "— ignoring it", f, ages.shape[0], n)
            return None
        return ages.astype(np.int32)

    def save_base(self, path: str) -> None:
        """Full snapshot; resets the delta set (role of SaveBase,
        box_wrapper.h:628)."""
        with self._lock:
            keys = self._keys.copy()
            vals = {f: self._vals[f].copy() for f in _FIELDS}
            unseen = self._unseen.copy()
            self._dirty_parts = []
            self._shrunk_since_base = False
        self._save_arrays(path, keys, vals, "base", unseen=unseen)
        log.vlog(0, "save_base: %d features -> %s", keys.shape[0], path)

    def save_delta(self, path: str) -> None:
        """Snapshot of keys touched since last base (role of SaveDelta,
        box_wrapper.h:630)."""
        with self._lock:
            if self._shrunk_since_base:
                raise RuntimeError(
                    "save_delta after shrink(): decay/eviction cannot be "
                    "expressed as a delta — save_base first (the reference's "
                    "day boundary does the same: shrink, then base dump)")
            dirty = self._dirty_compact().copy()
            present, pos = self._locate(dirty)
            dirty = dirty[present]
            vals = {f: self._vals[f][pos[present]] for f in _FIELDS}
            unseen = self._unseen[pos[present]].copy()
        self._save_arrays(path, dirty, vals, "delta", unseen=unseen)
        log.vlog(0, "save_delta: %d features -> %s", dirty.shape[0], path)

    def save_xbox(self, path: str) -> int:
        """Serving-format export (role of the 'xbox' model dumps,
        ``save_xbox_base_model`` fleet_util.py:774): inference needs only
        {key → emb, w} — optimizer state, show/click stay behind — so the
        artifact is a fraction of the training checkpoint and can ship to
        online serving every pass. Returns rows written."""
        with self._lock:
            keys = self._keys.copy()
            vals = {"emb": self._vals["emb"].copy(),
                    "w": self._vals["w"].copy()}
        self._save_arrays(path, keys, quantize_xbox_vals(vals), "xbox")
        log.vlog(0, "save_xbox: %d features -> %s", keys.shape[0], path)
        return int(keys.shape[0])

    def _check_state_widths(self, vals: Dict[str, np.ndarray]) -> None:
        """Optimizer-state widths must match the configured optimizer — a
        silent numpy broadcast here would smear e.g. an adagrad g2sum into
        adam's beta-pow slots and train on garbage."""
        for f, want in (("emb_state", self._ke), ("w_state", self._kw)):
            got = vals[f].shape[-1] if vals[f].ndim > 1 else 1
            if got != want:
                raise ValueError(
                    f"{f} width {got} != {want} expected by optimizer "
                    f"{self.config.optimizer!r} — checkpoint/table was "
                    f"written with a different sparse optimizer")

    def set_all(self, keys_sorted: np.ndarray,
                vals: Dict[str, np.ndarray], *,
                unseen: Optional[np.ndarray] = None) -> None:
        """Replace the entire contents (base-load semantics: delta set
        cleared, shrink guard reset). Keys must be sorted unique.
        ``unseen`` restores per-row TTL ages (the checkpoint sidecar /
        a replica snapshot); None = every row starts a fresh lease."""
        self._check_state_widths(vals)
        with self._lock:
            self._keys = np.ascontiguousarray(keys_sorted, np.uint64)
            self._vals = {f: np.asarray(vals[f]) for f in _FIELDS}
            self._unseen = (np.ascontiguousarray(unseen, np.int32).copy()
                            if unseen is not None
                            else np.zeros(self._keys.shape, np.int32))
            self._dirty_parts = []
            self._shrunk_since_base = False

    def reset(self) -> None:
        """Drop everything (pass-retry rollback: a failed attempt's key
        insertions/write-backs are wiped before the recovery-chain
        reload replays the published state)."""
        d = self.config.dim
        self.set_all(np.empty((0,), np.uint64), {
            "emb": np.empty((0, d), np.float32),
            "emb_state": np.empty((0, self._ke), np.float32),
            "w": np.empty((0,), np.float32),
            "w_state": np.empty((0, self._kw), np.float32),
            "show": np.empty((0,), np.float32),
            "click": np.empty((0,), np.float32)})

    def load(self, path: str, kind: str = "base") -> None:
        """Load a base snapshot, or apply a delta on top. The ages
        sidecar (when present) restores each row's unseen-days TTL age
        so a restart no longer grants every row a fresh lease."""
        data = np.load(os.path.join(path, f"{self.config.name}.{kind}.npz"))
        keys = data["keys"].astype(np.uint64)
        vals = {f: data[f] for f in _FIELDS}
        ages = self._load_ages(path, kind, keys.shape[0])
        if kind == "base":
            self.set_all(keys, vals, unseen=ages)
        else:
            self._check_state_widths(vals)
            self.push_from_pass(keys, vals, unseen=ages)
