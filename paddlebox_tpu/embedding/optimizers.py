"""Fused sparse optimizers applied inside the push step.

Role of the in-kernel GPU sparse optimizers executed during push
(``heter_ps/optimizer.cuh.h``: SparseAdagradOptimizer:31,
SparseAdamOptimizer:148; bounds/decay config ``optimizer_conf.h``).

Each rule is a pure function over per-row (value, state, merged-grad)
vectors; the lookup layer guarantees the grad passed in is already the
EXACT per-row sum across all duplicates in the step (dedup happens owner-
side), so one rule application per touched row per step — matching the
reference's dedup-then-update contract (dynamic_merge_grad →
update_one_table, heter_comm_inl.h:1646).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.embedding.table import TableConfig


class SparseOptimizer:
    """Interface: update(value, g2sum, grad) -> (new_value, new_g2sum)."""

    def update_vector(self, value: jax.Array, g2sum: jax.Array,
                      grad: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def update_scalar(self, value: jax.Array, g2sum: jax.Array,
                      grad: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class SparseAdagrad(SparseOptimizer):
    """Per-row scalar-accumulator adagrad (reference optimizer.cuh.h:31-78):

      g2sum' = g2sum + mean(g^2)            (scalar per row)
      scale  = sqrt(initial_g2sum / (initial_g2sum + g2sum'))
      value' = clip(value - lr * scale * g, [min_bound, max_bound])
    """

    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    min_bound: float = -10.0
    max_bound: float = 10.0

    @classmethod
    def from_config(cls, cfg: TableConfig) -> "SparseAdagrad":
        return cls(learning_rate=cfg.learning_rate,
                   initial_g2sum=cfg.initial_g2sum,
                   min_bound=cfg.min_bound, max_bound=cfg.max_bound)

    def update_vector(self, value, g2sum, grad):
        # value/grad: [n, D]; g2sum: [n]
        add_g2 = jnp.mean(grad * grad, axis=-1)
        new_g2 = g2sum + add_g2
        scale = jnp.sqrt(self.initial_g2sum / (self.initial_g2sum + new_g2))
        new_v = value - self.learning_rate * scale[..., None] * grad
        return jnp.clip(new_v, self.min_bound, self.max_bound), new_g2

    def update_scalar(self, value, g2sum, grad):
        # value/grad/g2sum: [n]
        new_g2 = g2sum + grad * grad
        scale = jnp.sqrt(self.initial_g2sum / (self.initial_g2sum + new_g2))
        new_v = value - self.learning_rate * scale * grad
        return jnp.clip(new_v, self.min_bound, self.max_bound), new_g2
