"""Fused sparse optimizers applied inside the push step.

Role of the in-kernel GPU sparse optimizers executed during push
(``heter_ps/optimizer.cuh.h``: SparseAdagradOptimizer:31,
SparseAdamOptimizer:148, SparseAdamSharedOptimizer:330; bounds/decay
config ``optimizer_conf.h``).

Each rule is a pure function over per-row (value, state, merged-grad)
arrays; the lookup layer guarantees the grad passed in is already the
EXACT per-row sum across all duplicates in the step (dedup happens owner-
side), so one rule application per touched row per step — matching the
reference's dedup-then-update contract (dynamic_merge_grad →
update_one_table, heter_comm_inl.h:1646).

Optimizer state is a single per-row ``[n, K]`` float32 array whose width
and layout the optimizer defines — mirroring how the reference packs
per-optimizer state inline in the ``CommonFeatureValue`` record
(``feature_value.h:44``; e.g. adam appends [m1*, m2*, beta1_pow,
beta2_pow] after the weights, optimizer.cuh.h:306-327).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.embedding.table import TableConfig

_EPS = 1e-8


class SparseOptimizer:
    """Interface. State arrays: emb_state [n, emb_state_width(D)],
    w_state [n, w_state_width()]; update_* returns (new_value, new_state)."""

    def emb_state_width(self, dim: int) -> int:
        raise NotImplementedError

    def w_state_width(self) -> int:
        raise NotImplementedError

    def init_emb_state(self, n: int, dim: int) -> np.ndarray:
        return np.zeros((n, self.emb_state_width(dim)), np.float32)

    def init_w_state(self, n: int) -> np.ndarray:
        return np.zeros((n, self.w_state_width()), np.float32)

    def update_vector(self, value: jax.Array, state: jax.Array,
                      grad: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """value/grad [n, D]; state [n, emb_state_width(D)]."""
        raise NotImplementedError

    def update_scalar(self, value: jax.Array, state: jax.Array,
                      grad: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """value/grad [n]; state [n, w_state_width()]."""
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class SparseAdagrad(SparseOptimizer):
    """Per-row scalar-accumulator adagrad (reference optimizer.cuh.h:31-78):

      g2sum' = g2sum + mean(g^2)            (scalar per row)
      scale  = sqrt(initial_g2sum / (initial_g2sum + g2sum'))
      value' = clip(value - lr * scale * g, [min_bound, max_bound])

    State layout: [g2sum] (K=1).
    """

    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    min_bound: float = -10.0
    max_bound: float = 10.0

    @classmethod
    def from_config(cls, cfg: TableConfig) -> "SparseAdagrad":
        return cls(learning_rate=cfg.learning_rate,
                   initial_g2sum=cfg.initial_g2sum,
                   min_bound=cfg.min_bound, max_bound=cfg.max_bound)

    def emb_state_width(self, dim: int) -> int:
        return 1

    def w_state_width(self) -> int:
        return 1

    def update_vector(self, value, state, grad):
        g2sum = state[:, 0]
        new_g2 = g2sum + jnp.mean(grad * grad, axis=-1)
        scale = jnp.sqrt(self.initial_g2sum / (self.initial_g2sum + new_g2))
        new_v = value - self.learning_rate * scale[..., None] * grad
        return (jnp.clip(new_v, self.min_bound, self.max_bound),
                new_g2[:, None])

    def update_scalar(self, value, state, grad):
        g2sum = state[:, 0]
        new_g2 = g2sum + grad * grad
        scale = jnp.sqrt(self.initial_g2sum / (self.initial_g2sum + new_g2))
        new_v = value - self.learning_rate * scale * grad
        return (jnp.clip(new_v, self.min_bound, self.max_bound),
                new_g2[:, None])


@dataclasses.dataclass(frozen=True)
class SparseAdam(SparseOptimizer):
    """Per-dim-moment adam (reference optimizer.cuh.h:148-245):

      ratio = lr * sqrt(1 - beta2_pow) / (1 - beta1_pow)
      m1'   = beta1*m1 + (1-beta1)*g ; m2' = beta2*m2 + (1-beta2)*g^2
      value' = clip(value + ratio * m1'/(sqrt(m2') + eps), bounds)
      beta{1,2}_pow *= beta{1,2}

    (The reference ADDS the ratio term because its pushed grad already
    points down-hill; our push passes raw dL/dw, so we subtract.)

    State layout: [m1(D), m2(D), beta1_pow, beta2_pow] (K = 2D + 2) —
    the CommonFeatureValue adam packing (optimizer.cuh.h:306-327).
    """

    learning_rate: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.999
    min_bound: float = -10.0
    max_bound: float = 10.0

    @classmethod
    def from_config(cls, cfg: TableConfig) -> "SparseAdam":
        return cls(learning_rate=cfg.learning_rate, beta1=cfg.beta1,
                   beta2=cfg.beta2, min_bound=cfg.min_bound,
                   max_bound=cfg.max_bound)

    def emb_state_width(self, dim: int) -> int:
        return 2 * dim + 2

    def w_state_width(self) -> int:
        return 4

    def _init(self, n: int, k: int) -> np.ndarray:
        s = np.zeros((n, k), np.float32)
        # beta pows start at beta (the reference writes the decay rates on
        # state creation, optimizer.cuh.h:289-293).
        s[:, -2] = self.beta1
        s[:, -1] = self.beta2
        return s

    def init_emb_state(self, n: int, dim: int) -> np.ndarray:
        return self._init(n, self.emb_state_width(dim))

    def init_w_state(self, n: int) -> np.ndarray:
        return self._init(n, 4)

    def _apply(self, value, m1, m2, b1p, b2p, grad):
        ratio = (self.learning_rate * jnp.sqrt(1.0 - b2p) / (1.0 - b1p))
        new_m1 = self.beta1 * m1 + (1.0 - self.beta1) * grad
        new_m2 = self.beta2 * m2 + (1.0 - self.beta2) * grad * grad
        if value.ndim > 1:
            ratio = ratio[:, None]
        new_v = value - ratio * (new_m1 / (jnp.sqrt(new_m2) + _EPS))
        return (jnp.clip(new_v, self.min_bound, self.max_bound),
                new_m1, new_m2, b1p * self.beta1, b2p * self.beta2)

    def update_vector(self, value, state, grad):
        d = value.shape[-1]
        m1, m2 = state[:, :d], state[:, d:2 * d]
        b1p, b2p = state[:, 2 * d], state[:, 2 * d + 1]
        new_v, m1, m2, b1p, b2p = self._apply(value, m1, m2, b1p, b2p, grad)
        return new_v, jnp.concatenate(
            [m1, m2, b1p[:, None], b2p[:, None]], axis=-1)

    def update_scalar(self, value, state, grad):
        m1, m2, b1p, b2p = (state[:, 0], state[:, 1], state[:, 2],
                            state[:, 3])
        new_v, m1, m2, b1p, b2p = self._apply(value, m1, m2, b1p, b2p, grad)
        return new_v, jnp.stack([m1, m2, b1p, b2p], axis=-1)


@dataclasses.dataclass(frozen=True)
class SparseAdamShared(SparseOptimizer):
    """Shared-moment adam (reference optimizer.cuh.h:330-387): one scalar
    (m1, m2) pair per row shared by all dims — each dim's update uses the
    shared OLD moment with its own grad, and the stored moment becomes the
    mean of the per-dim new moments. Quarter the optimizer-state HBM of
    full adam at near-adam quality.

    State layout: [m1, m2, beta1_pow, beta2_pow] (K=4).
    """

    learning_rate: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.999
    min_bound: float = -10.0
    max_bound: float = 10.0

    @classmethod
    def from_config(cls, cfg: TableConfig) -> "SparseAdamShared":
        return cls(learning_rate=cfg.learning_rate, beta1=cfg.beta1,
                   beta2=cfg.beta2, min_bound=cfg.min_bound,
                   max_bound=cfg.max_bound)

    def emb_state_width(self, dim: int) -> int:
        return 4

    def w_state_width(self) -> int:
        return 4

    def _init(self, n: int) -> np.ndarray:
        s = np.zeros((n, 4), np.float32)
        s[:, 2] = self.beta1
        s[:, 3] = self.beta2
        return s

    def init_emb_state(self, n: int, dim: int) -> np.ndarray:
        return self._init(n)

    def init_w_state(self, n: int) -> np.ndarray:
        return self._init(n)

    def _apply(self, value, state, grad):
        m1, m2, b1p, b2p = (state[:, 0], state[:, 1], state[:, 2],
                            state[:, 3])
        ratio = self.learning_rate * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
        if value.ndim > 1:
            new_m1 = self.beta1 * m1[:, None] + (1.0 - self.beta1) * grad
            new_m2 = (self.beta2 * m2[:, None]
                      + (1.0 - self.beta2) * grad * grad)
            new_v = value - ratio[:, None] * (
                new_m1 / (jnp.sqrt(new_m2) + _EPS))
            store_m1, store_m2 = (jnp.mean(new_m1, axis=-1),
                                  jnp.mean(new_m2, axis=-1))
        else:
            new_m1 = self.beta1 * m1 + (1.0 - self.beta1) * grad
            new_m2 = self.beta2 * m2 + (1.0 - self.beta2) * grad * grad
            new_v = value - ratio * (new_m1 / (jnp.sqrt(new_m2) + _EPS))
            store_m1, store_m2 = new_m1, new_m2
        new_state = jnp.stack(
            [store_m1, store_m2, b1p * self.beta1, b2p * self.beta2],
            axis=-1)
        return jnp.clip(new_v, self.min_bound, self.max_bound), new_state

    def update_vector(self, value, state, grad):
        return self._apply(value, state, grad)

    def update_scalar(self, value, state, grad):
        return self._apply(value, state, grad)


@dataclasses.dataclass(frozen=True)
class SparseFTRL(SparseOptimizer):
    """FTRL-proximal — the classic sparse-CTR rule (reference
    ``operators/optimizers/ftrl_op.cc`` / ftrl_op.h FTRLOpKernel, at the
    standard lr_power = -1/2):

      n'     = n + g^2                       (per coordinate)
      sigma  = (sqrt(n') - sqrt(n)) / alpha
      z'     = z + g - sigma * value
      value' = 0                                     if |z'| <= l1
               -(z' - sign(z')*l1)
                 / ((beta + sqrt(n')) / alpha + l2)  otherwise

    The l1 threshold drives untouched-signal coordinates EXACTLY to
    zero — the sparsity-inducing behavior CTR systems run FTRL for.
    State layout: [z(D), n(D)] (K = 2D); scalar weights [z, n] (K = 2).
    Values are additionally clipped to the table bounds like every other
    sparse rule here.
    """

    learning_rate: float = 0.05         # alpha
    l1: float = 0.1
    l2: float = 1.0
    beta: float = 1.0
    min_bound: float = -10.0
    max_bound: float = 10.0

    @classmethod
    def from_config(cls, cfg: TableConfig) -> "SparseFTRL":
        return cls(learning_rate=cfg.learning_rate, l1=cfg.ftrl_l1,
                   l2=cfg.ftrl_l2, beta=cfg.ftrl_beta,
                   min_bound=cfg.min_bound, max_bound=cfg.max_bound)

    def emb_state_width(self, dim: int) -> int:
        return 2 * dim

    def w_state_width(self) -> int:
        return 2

    def _apply(self, value, z, n, grad):
        alpha = self.learning_rate
        new_n = n + grad * grad
        sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / alpha
        new_z = z + grad - sigma * value
        denom = (self.beta + jnp.sqrt(new_n)) / alpha + self.l2
        shrunk = -(new_z - jnp.sign(new_z) * self.l1) / denom
        new_v = jnp.where(jnp.abs(new_z) <= self.l1, 0.0, shrunk)
        return (jnp.clip(new_v, self.min_bound, self.max_bound),
                new_z, new_n)

    def update_vector(self, value, state, grad):
        d = value.shape[-1]
        new_v, z, n = self._apply(value, state[:, :d], state[:, d:], grad)
        return new_v, jnp.concatenate([z, n], axis=-1)

    def update_scalar(self, value, state, grad):
        new_v, z, n = self._apply(value, state[:, 0], state[:, 1], grad)
        return new_v, jnp.stack([z, n], axis=-1)


_OPTIMIZERS = {
    "adagrad": SparseAdagrad,
    "adam": SparseAdam,
    "adam_shared": SparseAdamShared,
    "ftrl": SparseFTRL,
}


def make_sparse_optimizer(cfg: TableConfig) -> SparseOptimizer:
    """Factory by ``cfg.optimizer`` (role of HeterPs' optimizer_type
    dispatch, heter_ps.cu:113-135)."""
    try:
        klass = _OPTIMIZERS[cfg.optimizer]
    except KeyError:
        raise ValueError(
            f"unknown sparse optimizer {cfg.optimizer!r}; "
            f"choose from {sorted(_OPTIMIZERS)}") from None
    return klass.from_config(cfg)
