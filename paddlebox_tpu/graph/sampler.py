"""Device-side graph sampling: neighbor sample + random walks in XLA.

Role of the reference's CUDA sample kernels (``graph_gpu_ps_table_inl.cu``
neighbor_sample / ``graph_sampler.h``, walk generation inside
``GraphDataGenerator``): warp-per-node gathers from GPU neighbor lists.

TPU-first: the padded DeviceGraph makes every primitive a batched gather
with static shapes — sample k neighbors = gather at ``rand % degree``
(with replacement; degree-0 nodes self-loop via the padding), random walk
= ``lax.scan`` of that gather. All functions are jittable and vmap/pjit
friendly (shard the node batch over dp).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.graph.table import DeviceGraph


def device_arrays(g: DeviceGraph) -> Tuple[jax.Array, jax.Array]:
    return jnp.asarray(g.nbrs), jnp.asarray(g.degree)


def device_cdf(g: DeviceGraph) -> jax.Array:
    """Device-resident per-neighbor weight CDF for weighted sampling."""
    if g.nbr_cdf is None:
        raise ValueError("graph has no edge weights — build the CSR with "
                         "weights= to sample by weight")
    return jnp.asarray(g.nbr_cdf)


@functools.partial(jax.jit, static_argnames=("k",))
def sample_neighbors_weighted(nbrs: jax.Array, cdf: jax.Array,
                              nodes: jax.Array, key: jax.Array,
                              k: int) -> jax.Array:
    """[B] nodes → [B, k] neighbor sample with replacement, each neighbor
    drawn ∝ its edge weight (role of the reference's weighted
    sample_neighbors over per-edge weight_arr,
    common_graph_table.h:128-152). Inverse-CDF draw as a compare+sum —
    static shapes, no alias table, fuses to one elementwise pass over
    [B, k, D]. Isolated nodes return themselves (their cdf row puts all
    mass on the self-loop padding column 0)."""
    u = jax.random.uniform(key, (nodes.shape[0], k))          # [B,k)
    row_cdf = cdf[nodes]                                      # [B,D]
    idx = jnp.sum(row_cdf[:, None, :] < u[:, :, None],
                  axis=-1).astype(jnp.int32)                  # [B,k]
    idx = jnp.minimum(idx, nbrs.shape[1] - 1)
    return jnp.take_along_axis(nbrs[nodes], idx, axis=1)


@functools.partial(jax.jit, static_argnames=("walk_len",))
def random_walk_weighted(nbrs: jax.Array, cdf: jax.Array,
                         starts: jax.Array, key: jax.Array,
                         walk_len: int) -> jax.Array:
    """[B] starts → [B, walk_len+1] weighted random walks (each hop draws
    ∝ edge weight — the node2vec/deepwalk-on-weighted-graph primitive)."""

    def step(cur, k):
        nxt = sample_neighbors_weighted(nbrs, cdf, cur, k, 1)[:, 0]
        return nxt, nxt

    keys = jax.random.split(key, walk_len)
    _, path = jax.lax.scan(step, starts, keys)
    return jnp.concatenate([starts[:, None], path.T], axis=1)


@functools.partial(jax.jit, static_argnames=("k",))
def sample_neighbors(nbrs: jax.Array, degree: jax.Array, nodes: jax.Array,
                     key: jax.Array, k: int) -> jax.Array:
    """[B] nodes → [B, k] uniform neighbor sample with replacement.
    Degree-0 nodes return themselves (self-loop padding)."""
    b = nodes.shape[0]
    deg = jnp.maximum(degree[nodes], 1)                       # [B]
    r = jax.random.randint(key, (b, k), 0, 1 << 30)
    idx = (r % deg[:, None]).astype(jnp.int32)                # [B,k]
    return jnp.take_along_axis(nbrs[nodes], idx, axis=1)


@functools.partial(jax.jit, static_argnames=("walk_len",))
def random_walk(nbrs: jax.Array, degree: jax.Array, starts: jax.Array,
                key: jax.Array, walk_len: int) -> jax.Array:
    """[B] start nodes → [B, walk_len+1] uniform random walks (role of the
    deepwalk walk generation in GraphDataGenerator)."""

    def step(cur, k):
        nxt = sample_neighbors(nbrs, degree, cur, k, 1)[:, 0]
        return nxt, nxt

    keys = jax.random.split(key, walk_len)
    _, path = jax.lax.scan(step, starts, keys)
    return jnp.concatenate([starts[:, None], path.T], axis=1)


def skip_gram_pairs(walks: jax.Array, window: int) -> jax.Array:
    """[B, L] walks → [B*P, 2] (center, context) pairs for all offsets
    within ``window`` (role of the pair generation in
    GraphDataGenerator::GenerateSampleBatch). Static shape: every
    (position, offset) combination is emitted; pairs that would cross the
    walk boundary repeat the center node (self-pair) so downstream loss
    can mask them with ``pair[:,0] != pair[:,1]``."""
    b, length = walks.shape
    centers = []
    contexts = []
    for off in range(1, window + 1):
        for sign in (1, -1):
            shift = off * sign
            ctx = jnp.roll(walks, -shift, axis=1)
            pos = jnp.arange(length)
            valid = ((pos + shift) >= 0) & ((pos + shift) < length)
            ctx = jnp.where(valid[None, :], ctx, walks)
            centers.append(walks)
            contexts.append(ctx)
    c = jnp.concatenate(centers, axis=1).reshape(-1)
    x = jnp.concatenate(contexts, axis=1).reshape(-1)
    return jnp.stack([c, x], axis=1)


def negative_samples(key: jax.Array, num_pairs: int, num_neg: int,
                     num_nodes: int) -> jax.Array:
    """[P, num_neg] uniform negatives (role of the negative table in the
    reference's graph trainer)."""
    return jax.random.randint(key, (num_pairs, num_neg), 0, num_nodes,
                              dtype=jnp.int32)


def stack_device_graphs(graphs) -> Tuple[jax.Array, jax.Array]:
    """Stack per-edge-type padded views into [T, N, Dmax] / [T, N] device
    arrays for metapath sampling. Types may have different max_degree —
    narrower ones pad with self-loops (their degree vector already stops
    the sampler from reading the padding). All types must share the node
    id space (same N), as the reference's typed graphs do
    (graph_gpu_wrapper.h:25 — one node space, per-type adjacency)."""
    n = {g.nbrs.shape[0] for g in graphs}
    if len(n) != 1:
        raise ValueError(f"edge types disagree on node count: {n}")
    dmax = max(g.max_degree for g in graphs)
    nbrs, degs = [], []
    for g in graphs:
        pad = dmax - g.nbrs.shape[1]
        a = g.nbrs
        if pad:
            self_col = np.arange(a.shape[0], dtype=a.dtype)[:, None]
            a = np.concatenate([a, np.repeat(self_col, pad, axis=1)],
                               axis=1)
        nbrs.append(a)
        degs.append(g.degree)
    return jnp.asarray(np.stack(nbrs)), jnp.asarray(np.stack(degs))


def stack_device_cdfs(graphs) -> jax.Array:
    """[T, N, Dmax] stacked weight CDFs aligned with stack_device_graphs'
    adjacency stack (narrower types pad with 1.0 — already past the last
    valid cdf value, so a draw never lands in the padding)."""
    if any(g.nbr_cdf is None for g in graphs):
        raise ValueError("all edge types need weights for a weighted "
                         "metapath — mixed weighted/uniform would "
                         "silently sample the uniform types wrong")
    dmax = max(g.max_degree for g in graphs)
    out = []
    for g in graphs:
        c = g.nbr_cdf
        pad = dmax - c.shape[1]
        if pad:
            c = np.concatenate(
                [c, np.ones((c.shape[0], pad), np.float32)], axis=1)
        out.append(c)
    return jnp.asarray(np.stack(out))


@functools.partial(jax.jit, static_argnames=("type_seq",))
def metapath_walk_weighted(nbrs_stack: jax.Array, cdf_stack: jax.Array,
                           starts: jax.Array, key: jax.Array,
                           type_seq: Tuple[int, ...]) -> jax.Array:
    """Weighted metapath walk: hop h draws from edge type type_seq[h]
    with per-edge weights (the weighted half of the reference's metapath
    machinery — typed adjacency + weight_arr sampling)."""
    ts = jnp.asarray(type_seq, jnp.int32)
    keys = jax.random.split(key, len(type_seq))

    def step(cur, inp):
        t, k = inp
        u = jax.random.uniform(k, cur.shape)
        row_cdf = cdf_stack[t, cur]                            # [B,D]
        idx = jnp.sum(row_cdf < u[:, None], axis=-1).astype(jnp.int32)
        idx = jnp.minimum(idx, nbrs_stack.shape[-1] - 1)
        nxt = nbrs_stack[t, cur, idx]
        return nxt, nxt

    _, path = jax.lax.scan(step, starts, (ts, keys))
    return jnp.concatenate([starts[:, None], path.T], axis=1)


@functools.partial(jax.jit, static_argnames=("type_seq",))
def metapath_walk(nbrs_stack: jax.Array, degree_stack: jax.Array,
                  starts: jax.Array, key: jax.Array,
                  type_seq: Tuple[int, ...]) -> jax.Array:
    """[B] starts → [B, len(type_seq)+1] walks where hop h samples from
    edge type ``type_seq[h]`` (role of the reference's meta-path walks —
    graph_gpu_wrapper.h:25 get_sage_keys/metapath config over typed
    adjacency, e.g. user→item→user): one lax.scan whose per-step gather
    indexes the stacked [T, N, D] adjacency by the hop's type id.
    Dead-end nodes (degree 0 in the hop's type) stay in place via the
    self-loop padding."""
    ts = jnp.asarray(type_seq, jnp.int32)
    keys = jax.random.split(key, len(type_seq))

    def step(cur, inp):
        t, k = inp
        deg = jnp.maximum(degree_stack[t, cur], 1)            # [B]
        r = jax.random.randint(k, cur.shape, 0, 1 << 30)
        idx = (r % deg).astype(jnp.int32)
        nxt = nbrs_stack[t, cur, idx]
        return nxt, nxt

    _, path = jax.lax.scan(step, starts, (ts, keys))
    return jnp.concatenate([starts[:, None], path.T], axis=1)


def degree_neg_cdf(degree: np.ndarray, power: float = 0.75) -> jax.Array:
    """Cumulative sampling table for degree-aware negatives: node i drawn
    ∝ degree_i^power (the word2vec unigram^0.75 discipline; role of the
    reference's degree-weighted negative table). Isolated nodes get a
    unit weight so every id stays reachable."""
    w = np.maximum(np.asarray(degree, np.float64), 1.0) ** power
    cdf = np.cumsum(w)
    return jnp.asarray((cdf / cdf[-1]).astype(np.float32))


@functools.partial(jax.jit, static_argnames=("num_pairs", "num_neg"))
def negative_samples_by_degree(key: jax.Array, cdf: jax.Array,
                               num_pairs: int, num_neg: int) -> jax.Array:
    """[P, num_neg] negatives drawn from the degree-weighted table —
    searchsorted on the cdf (one fused gather-free op on TPU)."""
    u = jax.random.uniform(key, (num_pairs, num_neg))
    return jnp.searchsorted(cdf, u).astype(jnp.int32)


def gather_node_feats(feats: jax.Array, nodes: jax.Array) -> jax.Array:
    """Device-side node-feature pull: [B, ...] rows for [B] node ids
    (role of the feature half of the graph PS — get_node_feat in
    graph_gpu_wrapper.h / common_graph_table.h feature columns — once
    the feature table is device-resident)."""
    return feats[nodes]
