"""Device-side graph sampling: neighbor sample + random walks in XLA.

Role of the reference's CUDA sample kernels (``graph_gpu_ps_table_inl.cu``
neighbor_sample / ``graph_sampler.h``, walk generation inside
``GraphDataGenerator``): warp-per-node gathers from GPU neighbor lists.

TPU-first: the padded DeviceGraph makes every primitive a batched gather
with static shapes — sample k neighbors = gather at ``rand % degree``
(with replacement; degree-0 nodes self-loop via the padding), random walk
= ``lax.scan`` of that gather. All functions are jittable and vmap/pjit
friendly (shard the node batch over dp).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.graph.table import DeviceGraph


def device_arrays(g: DeviceGraph) -> Tuple[jax.Array, jax.Array]:
    return jnp.asarray(g.nbrs), jnp.asarray(g.degree)


@functools.partial(jax.jit, static_argnames=("k",))
def sample_neighbors(nbrs: jax.Array, degree: jax.Array, nodes: jax.Array,
                     key: jax.Array, k: int) -> jax.Array:
    """[B] nodes → [B, k] uniform neighbor sample with replacement.
    Degree-0 nodes return themselves (self-loop padding)."""
    b = nodes.shape[0]
    deg = jnp.maximum(degree[nodes], 1)                       # [B]
    r = jax.random.randint(key, (b, k), 0, 1 << 30)
    idx = (r % deg[:, None]).astype(jnp.int32)                # [B,k]
    return jnp.take_along_axis(nbrs[nodes], idx, axis=1)


@functools.partial(jax.jit, static_argnames=("walk_len",))
def random_walk(nbrs: jax.Array, degree: jax.Array, starts: jax.Array,
                key: jax.Array, walk_len: int) -> jax.Array:
    """[B] start nodes → [B, walk_len+1] uniform random walks (role of the
    deepwalk walk generation in GraphDataGenerator)."""

    def step(cur, k):
        nxt = sample_neighbors(nbrs, degree, cur, k, 1)[:, 0]
        return nxt, nxt

    keys = jax.random.split(key, walk_len)
    _, path = jax.lax.scan(step, starts, keys)
    return jnp.concatenate([starts[:, None], path.T], axis=1)


def skip_gram_pairs(walks: jax.Array, window: int) -> jax.Array:
    """[B, L] walks → [B*P, 2] (center, context) pairs for all offsets
    within ``window`` (role of the pair generation in
    GraphDataGenerator::GenerateSampleBatch). Static shape: every
    (position, offset) combination is emitted; pairs that would cross the
    walk boundary repeat the center node (self-pair) so downstream loss
    can mask them with ``pair[:,0] != pair[:,1]``."""
    b, length = walks.shape
    centers = []
    contexts = []
    for off in range(1, window + 1):
        for sign in (1, -1):
            shift = off * sign
            ctx = jnp.roll(walks, -shift, axis=1)
            pos = jnp.arange(length)
            valid = ((pos + shift) >= 0) & ((pos + shift) < length)
            ctx = jnp.where(valid[None, :], ctx, walks)
            centers.append(walks)
            contexts.append(ctx)
    c = jnp.concatenate(centers, axis=1).reshape(-1)
    x = jnp.concatenate(contexts, axis=1).reshape(-1)
    return jnp.stack([c, x], axis=1)


def negative_samples(key: jax.Array, num_pairs: int, num_neg: int,
                     num_nodes: int) -> jax.Array:
    """[P, num_neg] uniform negatives (role of the negative table in the
    reference's graph trainer)."""
    return jax.random.randint(key, (num_pairs, num_neg), 0, num_nodes,
                              dtype=jnp.int32)
