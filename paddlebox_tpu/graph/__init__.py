"""Graph learning engine (role of the reference GPU graph engine §2.3:
GpuPsGraphTable + samplers + GraphGpuWrapper + GraphDataGenerator)."""

from paddlebox_tpu.graph.table import (CSRGraph, DeviceGraph, GraphTable,
                                       build_csr, load_edge_file)
from paddlebox_tpu.graph.sampler import (device_arrays, negative_samples,
                                         random_walk, sample_neighbors,
                                         skip_gram_pairs)
from paddlebox_tpu.graph.data_generator import (GraphDataGenerator,
                                                GraphGenConfig)

__all__ = [
    "CSRGraph", "DeviceGraph", "GraphTable", "build_csr", "load_edge_file",
    "device_arrays", "negative_samples", "random_walk", "sample_neighbors",
    "skip_gram_pairs", "GraphDataGenerator", "GraphGenConfig",
]
