"""Graph learning engine (role of the reference GPU graph engine §2.3:
GpuPsGraphTable + samplers + GraphGpuWrapper + GraphDataGenerator)."""

from paddlebox_tpu.graph.table import (CSRGraph, DeviceGraph, GraphTable,
                                       build_csr, load_edge_file)
from paddlebox_tpu.graph.sampler import (degree_neg_cdf, device_arrays,
                                         device_cdf, gather_node_feats,
                                         metapath_walk,
                                         metapath_walk_weighted,
                                         negative_samples,
                                         negative_samples_by_degree,
                                         random_walk, random_walk_weighted,
                                         sample_neighbors,
                                         sample_neighbors_weighted,
                                         skip_gram_pairs,
                                         stack_device_cdfs,
                                         stack_device_graphs)
from paddlebox_tpu.graph.data_generator import (GraphDataGenerator,
                                                GraphGenConfig)

__all__ = [
    "CSRGraph", "DeviceGraph", "GraphTable", "build_csr", "load_edge_file",
    "degree_neg_cdf", "device_arrays", "device_cdf", "gather_node_feats",
    "metapath_walk", "metapath_walk_weighted", "negative_samples",
    "negative_samples_by_degree", "random_walk", "random_walk_weighted",
    "sample_neighbors", "sample_neighbors_weighted", "skip_gram_pairs",
    "stack_device_cdfs", "stack_device_graphs", "GraphDataGenerator",
    "GraphGenConfig",
]
