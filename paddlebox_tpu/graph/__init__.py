"""Graph learning engine (role of the reference GPU graph engine §2.3:
GpuPsGraphTable + samplers + GraphGpuWrapper + GraphDataGenerator)."""

from paddlebox_tpu.graph.table import (CSRGraph, DeviceGraph, GraphTable,
                                       build_csr, load_edge_file)
from paddlebox_tpu.graph.sampler import (degree_neg_cdf, device_arrays,
                                         gather_node_feats, metapath_walk,
                                         negative_samples,
                                         negative_samples_by_degree,
                                         random_walk, sample_neighbors,
                                         skip_gram_pairs,
                                         stack_device_graphs)
from paddlebox_tpu.graph.data_generator import (GraphDataGenerator,
                                                GraphGenConfig)

__all__ = [
    "CSRGraph", "DeviceGraph", "GraphTable", "build_csr", "load_edge_file",
    "degree_neg_cdf", "device_arrays", "gather_node_feats",
    "metapath_walk", "negative_samples", "negative_samples_by_degree",
    "random_walk", "sample_neighbors", "skip_gram_pairs",
    "stack_device_graphs", "GraphDataGenerator", "GraphGenConfig",
]
