"""Graph store: host CSR shards + device-resident padded adjacency.

Role of the reference GPU graph engine storage (``heter_ps/
graph_gpu_ps_table.h`` GpuPsGraphTable keeping per-GPU node/edge shards,
``gpu_graph_node.h`` GpuPsGraphNode neighbor lists, ``GraphGpuWrapper``
facade ``heter_ps/graph_gpu_wrapper.h:25`` with load_edge_file /
upload_batch, and the brpc-served CPU ``common_graph_table.h``).

TPU-first: the host side is one vectorized CSR per edge type (numpy,
sharded by ``node % num_shards`` like the reference's key%n placement);
the device side is a **padded** CSR — neighbors dense-packed to
``max_degree`` with a sentinel, plus a degree vector — because XLA wants
static shapes: sampling then becomes pure gather + modular arithmetic,
no pointer chasing (the cuGraph-style warp gathers of
``graph_gpu_ps_table_inl.cu`` collapse into one batched gather).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import log


@dataclasses.dataclass
class CSRGraph:
    """Host compact adjacency: neighbors of node i are
    ``cols[indptr[i]:indptr[i+1]]``; ``weights`` (optional, aligned with
    ``cols``) carry per-edge sampling weights — the reference stores them
    next to each neighbor and samples by them when ``is_weighted``
    (common_graph_table.h:128-152 add_neighbor(id, dst, weight))."""

    indptr: np.ndarray     # [num_nodes+1] int64
    cols: np.ndarray       # [num_edges]  int64
    num_nodes: int
    weights: Optional[np.ndarray] = None   # [num_edges] float32
    # Lazy global weight cumsum (float64) for the host weighted sampler —
    # cached because the CSR is immutable between builds and an O(E)
    # cumsum per sample RPC would dominate the sampling cost.
    _cum_weights: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False)

    def cum_weights(self) -> np.ndarray:
        if self._cum_weights is None:
            self._cum_weights = np.cumsum(self.weights, dtype=np.float64)
        return self._cum_weights

    @property
    def num_edges(self) -> int:
        return int(self.cols.shape[0])

    @property
    def is_weighted(self) -> bool:
        return self.weights is not None

    def degrees(self) -> np.ndarray:
        return np.diff(self.indptr)

    def neighbors(self, node: int) -> np.ndarray:
        return self.cols[self.indptr[node]:self.indptr[node + 1]]

    def neighbor_weights(self, node: int) -> Optional[np.ndarray]:
        if self.weights is None:
            return None
        return self.weights[self.indptr[node]:self.indptr[node + 1]]


def build_csr(src: np.ndarray, dst: np.ndarray,
              num_nodes: Optional[int] = None,
              symmetrize: bool = False,
              weights: Optional[np.ndarray] = None) -> CSRGraph:
    """Vectorized edge-list → CSR (role of load_edge_file + upload_batch:
    the reference parses then bulk-copies shards; one argsort does it).
    ``weights`` ride the same permutation (symmetrize duplicates them
    with their edge)."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if weights is not None:
        weights = np.asarray(weights, np.float32)
        if weights.shape != src.shape:
            raise ValueError(
                f"weights shape {weights.shape} != edges {src.shape}")
        if weights.size and weights.min() < 0:
            raise ValueError("negative edge weights are not samplable")
    if symmetrize:
        src, dst = (np.concatenate([src, dst]), np.concatenate([dst, src]))
        if weights is not None:
            weights = np.concatenate([weights, weights])
    if num_nodes is None:
        num_nodes = int(max(src.max(initial=-1), dst.max(initial=-1))) + 1
    # Out-of-range ids would otherwise silently corrupt sampling (dst
    # flows into cols unchecked; a negative src is heap-corrupting UB on
    # the native path below) — validate on BOTH num_nodes branches.
    hi = max(src.max(initial=-1), dst.max(initial=-1))
    lo = min(src.min(initial=0), dst.min(initial=0))
    if hi >= num_nodes or lo < 0:
        raise ValueError(
            f"edge ids span [{lo}, {hi}] outside num_nodes={num_nodes}")
    # Large edge lists take the native parallel counting sort (O(E),
    # bit-identical layout to the stable argsort below — the role of the
    # reference's native graph load/build, graph_gpu_wrapper.h:25);
    # small ones stay in numpy where thread spawn would dominate.
    if src.size >= 100_000:
        from paddlebox_tpu.native.graph_py import build_csr_native
        built = build_csr_native(src, dst, weights, num_nodes)
        if built is not None:
            indptr_n, cols_n, w_n = built
            return CSRGraph(indptr=indptr_n, cols=cols_n,
                            num_nodes=num_nodes, weights=w_n)
    order = np.argsort(src, kind="stable")
    counts = np.bincount(src, minlength=num_nodes)
    indptr = np.zeros(num_nodes + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    return CSRGraph(indptr=indptr, cols=dst[order], num_nodes=num_nodes,
                    weights=None if weights is None else weights[order])


def load_edge_file(path: str, *, delimiter: Optional[str] = None,
                   symmetrize: bool = False,
                   num_nodes: Optional[int] = None) -> CSRGraph:
    """Parse a 'src dst [weight]'-per-line edge file (role of
    GraphGpuWrapper::load_edge_file; the optional third column is the
    reference's weighted-graph file format, common_graph_table.h
    is_weighted)."""
    # Sniff the column count (skipping the same '#' comments loadtxt
    # skips), then parse ONCE with a structured dtype: node ids must
    # parse as int64 (a float64 round-trip silently corrupts hash-style
    # ids above 2^53) while the optional weight column is float.
    ncols = 0
    with open(path) as f:
        for line in f:
            s = line.split("#", 1)[0]
            parts = [p for p in (s.split(delimiter) if delimiter
                                 else s.split()) if p.strip()]
            if parts:
                ncols = len(parts)
                break
    if ncols == 0:
        return build_csr(np.empty(0, np.int64), np.empty(0, np.int64),
                         num_nodes=num_nodes or 0)
    if ncols >= 3:
        dt = np.dtype([("src", np.int64), ("dst", np.int64),
                       ("w", np.float32)])
        data = np.atleast_1d(np.loadtxt(path, dtype=dt,
                                        delimiter=delimiter,
                                        usecols=(0, 1, 2)))
        return build_csr(data["src"], data["dst"], num_nodes=num_nodes,
                         symmetrize=symmetrize, weights=data["w"])
    ids = np.loadtxt(path, dtype=np.int64, delimiter=delimiter, ndmin=2)
    return build_csr(ids[:, 0], ids[:, 1], num_nodes=num_nodes,
                     symmetrize=symmetrize)


@dataclasses.dataclass
class DeviceGraph:
    """Padded adjacency ready for device sampling — static shapes.

    ``nbrs[i, j]`` = j-th neighbor of node i for j < degree[i], else the
    node itself (self-loop padding keeps walks inside the node id space
    without masks). For weighted graphs ``nbr_cdf[i]`` is the inclusive
    normalized weight CDF over the kept neighbors (padding columns sit at
    1.0), so a weighted draw is ``count(cdf < u)`` — one compare+sum, no
    alias table and no data-dependent control flow (role of the
    weight_arr the reference samples against, common_graph_table.h:152).
    """

    nbrs: np.ndarray       # [num_nodes, max_degree] int32
    degree: np.ndarray     # [num_nodes] int32
    max_degree: int
    nbr_cdf: Optional[np.ndarray] = None   # [num_nodes, max_degree] f32

    @property
    def is_weighted(self) -> bool:
        return self.nbr_cdf is not None

    @classmethod
    def from_csr(cls, g: CSRGraph, max_degree: Optional[int] = None,
                 seed: int = 0) -> "DeviceGraph":
        """Pack CSR to padded form. Nodes with degree > max_degree keep a
        subsample (uniform without replacement; weighted graphs keep a
        probability-proportional-to-weight sample via Efraimidis-Spirakis
        keys — the same grouped shuffle, keyed by -log(u)/w); degree-0
        nodes self-loop."""
        deg = g.degrees()
        md = int(max_degree or max(int(deg.max(initial=1)), 1))
        n = g.num_nodes
        nbrs = np.repeat(np.arange(n, dtype=np.int64)[:, None], md, axis=1)
        w_pad = (np.zeros((n, md), np.float32) if g.is_weighted else None)
        rng = np.random.default_rng(seed)
        eff_deg = np.minimum(deg, md).astype(np.int32)
        # Vectorized fill for nodes with degree <= md.
        small = np.flatnonzero((deg > 0) & (deg <= md))
        if small.size:
            # position matrix [k, md] valid where col < deg
            take = g.indptr[small][:, None] + np.arange(md)[None, :]
            valid = np.arange(md)[None, :] < deg[small][:, None]
            take = np.where(valid, take, g.indptr[small][:, None])
            take = np.minimum(take, g.num_edges - 1)
            vals = g.cols[take]
            nbrs[small] = np.where(valid, vals, nbrs[small])
            if w_pad is not None:
                w_pad[small] = np.where(valid, g.weights[take], 0.0)
        big = np.flatnonzero(deg > md)
        if big.size:
            # Vectorized without-replacement subsample for hub nodes (on
            # power-law graphs with a caller-capped max_degree these can
            # be a large fraction of nodes): assign a sort key per edge,
            # order edges by (owner, key), keep the first md of each
            # owner group — a grouped shuffle with no python loop. Keys:
            # uniform for unweighted truncation; -log(u)/w for weighted
            # (Efraimidis-Spirakis — keeps each edge with probability
            # proportional to its weight).
            bdeg = deg[big]
            owner = np.repeat(big, bdeg)
            # edge index ranges of the big nodes, concatenated
            offsets = np.repeat(g.indptr[big], bdeg)
            ends = np.cumsum(bdeg)
            starts = ends - bdeg
            edges = offsets + (np.arange(owner.shape[0])
                               - np.repeat(starts, bdeg))
            u = rng.random(edges.shape[0])
            if g.is_weighted:
                ew = np.maximum(g.weights[edges], 1e-30)
                keys = -np.log(np.maximum(u, 1e-300)) / ew
            else:
                keys = u
            order2 = np.lexsort((keys, owner))
            edges_s = edges[order2]
            within = np.arange(owner.shape[0]) - np.repeat(starts, bdeg)
            kept = edges_s[within < md]
            rows_idx = np.repeat(big, md)
            cols_idx = np.tile(np.arange(md), big.size)
            nbrs[rows_idx, cols_idx] = g.cols[kept]
            if w_pad is not None:
                w_pad[rows_idx, cols_idx] = g.weights[kept]
        cdf = None
        if w_pad is not None:
            # Rows whose kept weights sum to 0 (all-zero weights but
            # degree > 0, or isolated nodes) fall back to uniform over
            # the valid columns so every neighbor stays reachable.
            valid_cols = (np.arange(md)[None, :]
                          < np.maximum(eff_deg, 1)[:, None])
            totals = w_pad.sum(axis=1)
            w_eff = np.where((totals <= 0)[:, None] & valid_cols,
                             1.0, w_pad)
            cum = np.cumsum(w_eff, axis=1)
            cdf = (cum / cum[:, -1:]).astype(np.float32)
        return cls(nbrs=nbrs.astype(np.int32), degree=eff_deg,
                   max_degree=md, nbr_cdf=cdf)


class GraphTable:
    """Sharded multi-edge-type graph facade (role of GraphGpuWrapper +
    GpuPsGraphTable): named edge types, shard-local CSRs, padded device
    views, and node feature storage."""

    def __init__(self, num_shards: int = 1):
        self.num_shards = num_shards
        self._graphs: Dict[str, CSRGraph] = {}
        self._device: Dict[str, DeviceGraph] = {}
        self._feats: Dict[str, np.ndarray] = {}
        self._node_types: Optional[np.ndarray] = None

    def add_edges(self, edge_type: str, src: np.ndarray, dst: np.ndarray,
                  *, num_nodes: Optional[int] = None,
                  symmetrize: bool = False,
                  weights: Optional[np.ndarray] = None) -> CSRGraph:
        g = build_csr(src, dst, num_nodes=num_nodes, symmetrize=symmetrize,
                      weights=weights)
        self._graphs[edge_type] = g
        self._device.pop(edge_type, None)
        log.vlog(1, "graph[%s]: %d nodes %d edges", edge_type, g.num_nodes,
                 g.num_edges)
        return g

    def load_edge_file(self, edge_type: str, path: str, **kw) -> CSRGraph:
        g = load_edge_file(path, **kw)
        self._graphs[edge_type] = g
        self._device.pop(edge_type, None)
        return g

    def graph(self, edge_type: str) -> CSRGraph:
        return self._graphs[edge_type]

    def device_graph(self, edge_type: str,
                     max_degree: Optional[int] = None) -> DeviceGraph:
        """Padded device view, cached per edge type (role of
        upload_batch moving shards into HBM)."""
        if edge_type not in self._device:
            self._device[edge_type] = DeviceGraph.from_csr(
                self._graphs[edge_type], max_degree)
        return self._device[edge_type]

    # -- node features (role of the feature table half of the graph PS) --

    def set_node_feat(self, name: str, values: np.ndarray) -> None:
        self._feats[name] = np.asarray(values)

    def get_node_feat(self, name: str, nodes: np.ndarray) -> np.ndarray:
        return self._feats[name][np.asarray(nodes, np.int64)]

    def device_feats(self, name: str):
        """Device-resident feature column for jitted gathers
        (sampler.gather_node_feats)."""
        import jax.numpy as jnp
        return jnp.asarray(self._feats[name])

    # -- node types (role of load_node_file's typed node sets — metapath
    # walks start from a typed frontier, graph_gpu_wrapper.h:25) --------

    def set_node_types(self, types: np.ndarray) -> None:
        """types[i] = integer type id of node i."""
        self._node_types = np.asarray(types, np.int32)

    def nodes_of_type(self, t: int) -> np.ndarray:
        if self._node_types is None:
            raise RuntimeError("no node types loaded — call "
                               "set_node_types/load_node_file first")
        return np.flatnonzero(self._node_types == t).astype(np.int64)

    def load_node_file(self, path: str, type_ids: Dict[str, int],
                       num_nodes: int) -> np.ndarray:
        """Parse a '<type_name> <node_id>'-per-line node file (role of
        GraphGpuWrapper::load_node_file). Unlisted nodes get type -1."""
        types = np.full(num_nodes, -1, np.int32)
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    types[int(parts[1])] = type_ids[parts[0]]
        self.set_node_types(types)
        return types

    def shard_of(self, nodes: np.ndarray) -> np.ndarray:
        return (np.asarray(nodes, np.int64) % self.num_shards)
