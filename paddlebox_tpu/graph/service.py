"""Distributed graph service: CSR shards served over the typed wire.

Role of the brpc graph PS (``distributed/ps/service/graph_brpc_server.h:40``
+ ``graph_brpc_client``): nodes are sharded ``node % num_servers``; each
server holds the CSR rows of its nodes and answers upload/sample/feature
RPCs; the client fans requests out by owner and reassembles in request
order. Transport is the PS typed-frame protocol (``distributed/wire.py``
— no pickle, version-checked; trusted cluster network).

Sampling is DETERMINISTIC PER (seed, node, slot) via a counter hash, so
results are independent of the shard layout — a 2-shard cluster returns
bit-identical samples to a single-host table, which is what makes the
fake-cluster parity test (and cross-layout reproducibility in prod)
possible. The reference's GPU sampler draws from per-thread curand
states, which it pays for with run-to-run nondeterminism.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.distributed import rpc, wire
from paddlebox_tpu.distributed.transport import _recv_exact
from paddlebox_tpu.graph.table import CSRGraph, GraphTable, build_csr


def _mix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def sample_neighbors_host(g: CSRGraph, nodes: np.ndarray, k: int,
                          seed: int, *,
                          weighted: bool = False) -> np.ndarray:
    """[n, k] int64 neighbor samples (with replacement); -1 for isolated
    nodes. Deterministic per (seed, node, slot) — shard-layout invariant.

    ``weighted=True`` draws each neighbor ∝ its edge weight (role of the
    weighted sampling over common_graph_table.h:128-152 weight_arr): the
    counter hash becomes a uniform in [0, 1), and the pick is an
    inverse-CDF lookup on the GLOBAL weight cumsum — one vectorized
    searchsorted, no per-node python. Still deterministic per
    (seed, node, slot), so the layout invariance holds exactly."""
    nodes = np.asarray(nodes, np.int64)
    n = nodes.shape[0]
    out = np.full((n, k), -1, np.int64)
    in_range = (nodes >= 0) & (nodes < g.num_nodes)
    deg = np.zeros((n,), np.int64)
    safe = np.where(in_range, nodes, 0)
    deg[in_range] = (g.indptr[safe + 1] - g.indptr[safe])[in_range]
    has = deg > 0
    if not has.any():
        return out
    v = nodes[has].astype(np.uint64)
    with np.errstate(over="ignore"):
        base = _mix64(v * np.uint64(0x9DDFEA08EB382D69)
                      + np.uint64(seed))[:, None]
        slot = np.arange(k, dtype=np.uint64)[None, :]
        z = _mix64(base + slot * np.uint64(0xC2B2AE3D27D4EB4F))
    starts = g.indptr[nodes[has]].astype(np.int64)[:, None]
    if weighted and g.is_weighted:
        # Segment-local inverse CDF via the global cumsum (cached on the
        # CSR — immutable between builds): target = (prefix before the
        # node's segment) + u * (segment total).
        cw = g.cum_weights()
        seg_lo = starts.astype(np.int64)
        prefix = np.where(seg_lo > 0, cw[seg_lo - 1], 0.0)
        ends = g.indptr[nodes[has] + 1].astype(np.int64)[:, None]
        total = cw[ends - 1] - prefix
        u = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        # Zero-total segments (all weights 0) degrade to uniform.
        zero = total <= 0
        target = prefix + u * np.where(zero, 1.0, total)
        pos = np.searchsorted(cw, target, side="right")
        pos = np.clip(pos, seg_lo, ends - 1)
        if zero.any():
            idx_u = (z % deg[has].astype(np.uint64)[:, None]
                     ).astype(np.int64)
            pos = np.where(zero, seg_lo + idx_u, pos)
        out[has] = g.cols[pos]
        return out
    idx = (z % deg[has].astype(np.uint64)[:, None]).astype(np.int64)
    out[has] = g.cols[starts + idx]
    return out


class GraphServer(rpc.FramedRPCServer):
    """One graph shard: owns nodes with ``node % num_servers == index``
    (role of GraphBrpcServer holding its partition's adjacency +
    features). Service loop/framing from
    :class:`~paddlebox_tpu.distributed.rpc.FramedRPCServer`."""

    def __init__(self, endpoint: str, index: int, num_servers: int):
        self.index = index
        self.num_servers = num_servers
        self.table = GraphTable(num_shards=1)
        # Edge staging: upload_batch appends, build finalizes to CSR.
        self._pending: Dict[str, List] = {}
        self._num_nodes: Dict[str, int] = {}
        self._feat_rows: Dict[str, Dict[int, np.ndarray]] = {}
        self._lock = threading.Lock()
        self.service_name = f"graph[{index}]"
        rpc.FramedRPCServer.__init__(self, endpoint)

    # -- handlers ---------------------------------------------------------

    def _check_owned(self, nodes: np.ndarray) -> None:
        if nodes.size and not np.all(
                nodes % self.num_servers == self.index):
            raise ValueError(f"nodes not owned by graph shard {self.index}")

    def handle_upload_batch(self, req) -> int:
        """Append an edge batch whose SOURCE nodes this shard owns (role
        of GraphTable upload_batch / load into the partition). Optional
        per-edge ``weights`` ride along (common_graph_table.h
        add_neighbor(id, dst, weight))."""
        src = np.asarray(req["src"], np.int64)
        dst = np.asarray(req["dst"], np.int64)
        w = req.get("weights")
        w = None if w is None else np.asarray(w, np.float32)
        self._check_owned(src)
        with self._lock:
            self._pending.setdefault(req["edge_type"], []).append(
                (src, dst, w))
            self._num_nodes[req["edge_type"]] = max(
                self._num_nodes.get(req["edge_type"], 0),
                int(req["num_nodes"]))
        return int(src.size)

    def handle_build(self, req) -> int:
        """Finalize an edge type's pending batches into the local CSR."""
        et = req["edge_type"]
        with self._lock:
            parts = self._pending.pop(et, [])
            if not parts:
                return 0
            src = np.concatenate([p[0] for p in parts])
            dst = np.concatenate([p[1] for p in parts])
            ws = [p[2] for p in parts]
            if any(w is not None for w in ws):
                if any(w is None for w in ws):
                    raise ValueError(
                        f"edge type {et!r}: some batches carry weights "
                        f"and some do not — refusing to guess")
                weights = np.concatenate(ws)
            else:
                weights = None
            g = build_csr(src, dst, num_nodes=self._num_nodes[et],
                          weights=weights)
            self.table._graphs[et] = g
        monitor.add("graph/edges_built", int(src.size))
        return g.num_edges

    def _graph_or_empty(self, edge_type: str) -> CSRGraph:
        """A shard that received no edges for a type still answers — its
        owned nodes are simply all isolated."""
        g = self.table._graphs.get(edge_type)
        if g is None:
            n = max(self._num_nodes.get(edge_type, 0), 1)
            g = build_csr(np.empty(0, np.int64), np.empty(0, np.int64),
                          num_nodes=n)
        return g

    def handle_sample_neighbors(self, req) -> np.ndarray:
        nodes = np.asarray(req["nodes"], np.int64)
        self._check_owned(nodes)
        g = self._graph_or_empty(req["edge_type"])
        weighted = bool(req.get("weighted", False))
        if weighted and not g.is_weighted and g.num_edges:
            raise ValueError(
                f"edge type {req['edge_type']!r} has no weights on shard "
                f"{self.index} — upload with weights= to sample weighted")
        return sample_neighbors_host(g, nodes, int(req["k"]),
                                     int(req["seed"]), weighted=weighted)

    def handle_degrees(self, req) -> np.ndarray:
        nodes = np.asarray(req["nodes"], np.int64)
        self._check_owned(nodes)
        g = self._graph_or_empty(req["edge_type"])
        safe = np.clip(nodes, 0, g.num_nodes - 1)
        deg = g.indptr[safe + 1] - g.indptr[safe]
        return np.where((nodes >= 0) & (nodes < g.num_nodes), deg, 0)

    def handle_set_node_feat(self, req) -> bool:
        # Sharded feature rows: a per-name {node: row} map owned by the
        # SERVICE (GraphTable._feats is dense-array-schema'd; mixing
        # schemas would corrupt its own get/set API).
        nodes = np.asarray(req["nodes"], np.int64)
        self._check_owned(nodes)
        vals = np.asarray(req["values"])
        with self._lock:
            store = self._feat_rows.setdefault(req["name"], {})
            for nd, v in zip(nodes.tolist(), vals):
                store[nd] = v
        return True

    def handle_get_node_feat(self, req) -> Dict[str, np.ndarray]:
        """Rows for owned nodes; nodes (or whole names) this shard never
        saw serve zeros — consistent with the rest of the stack (unknown
        embedding keys, isolated graph nodes). ``width`` is -1 when the
        name is unknown here so the client can resolve the row shape from
        a shard that knows it."""
        nodes = np.asarray(req["nodes"], np.int64)
        self._check_owned(nodes)
        store = self._feat_rows.get(req["name"])
        if not store:
            return {"width": -1,
                    "rows": np.zeros((nodes.shape[0], 0), np.float32)}
        sample = next(iter(store.values()))
        out = np.zeros((nodes.shape[0],) + np.shape(sample),
                       np.asarray(sample).dtype)
        for i, nd in enumerate(nodes.tolist()):
            v = store.get(nd)
            if v is not None:
                out[i] = v
        return {"width": int(np.shape(sample)[0]) if np.ndim(sample)
                else 0, "rows": out}

    def handle_stats(self, req) -> Dict[str, int]:
        return {et: g.num_edges for et, g in self.table._graphs.items()}

    def handle_stop(self, req) -> bool:
        # Close the listener too — _running=False alone would leave the
        # port bound and accepting until process exit. (stop() from the
        # RPC base; this connection stays open for the acknowledgement.)
        self.stop()
        return True


class GraphClient:
    """Fan-out client (role of graph_brpc_client): requests shard by
    ``node % num_servers`` and reassemble in request order."""

    def __init__(self, endpoints: Sequence[str]):
        from concurrent.futures import ThreadPoolExecutor
        self.endpoints = list(endpoints)
        self.num_servers = len(self.endpoints)
        self._socks: List[Optional[socket.socket]] = \
            [None] * self.num_servers
        self._locks = [threading.Lock() for _ in self.endpoints]
        # Shard requests go out CONCURRENTLY (one in-flight RPC per
        # server, serialized per-connection by the lock) — the brpc
        # client's fan-out shape; a serial loop would pay num_servers
        # round-trips per op.
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, self.num_servers),
            thread_name_prefix="graph-client")

    def _fanout(self, calls):
        """calls: [(server, method, kwargs)] -> results in order."""
        if len(calls) <= 1:
            return [self._call(sv, m, **kw) for sv, m, kw in calls]
        futs = [self._pool.submit(self._call, sv, m, **kw)
                for sv, m, kw in calls]
        return [f.result() for f in futs]

    def _call(self, server: int, method: str, **kw):
        with self._locks[server]:
            if self._socks[server] is None:
                host, port = self.endpoints[server].rsplit(":", 1)
                self._socks[server] = socket.create_connection(
                    (host, int(port)), timeout=60)
            s = self._socks[server]
            try:
                s.sendall(wire.pack_frame({"method": method, **kw}))
                ln = wire.read_frame_header(
                    _recv_exact(s, wire.HEADER.size))
                resp = wire.loads(_recv_exact(s, ln))
            except (OSError, ConnectionError, wire.WireError):
                # A timed-out / half-read / desynced stream cannot be
                # reused — drop it so the next call reconnects cleanly.
                try:
                    s.close()
                except OSError:
                    pass
                self._socks[server] = None
                raise
        if not resp["ok"]:
            raise RuntimeError(f"graph[{server}].{method}: {resp['error']}")
        return resp["result"]

    def upload_batch(self, edge_type: str, src: np.ndarray,
                     dst: np.ndarray, *, num_nodes: int,
                     weights: Optional[np.ndarray] = None) -> int:
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if weights is not None:
            weights = np.asarray(weights, np.float32)
        total = 0
        # Empty subsets are still sent: they register num_nodes so a
        # shard owning only isolated nodes answers with -1 samples
        # instead of erroring on an unknown edge type.
        for sv in range(self.num_servers):
            sel = (src % self.num_servers) == sv
            total += self._call(
                sv, "upload_batch", edge_type=edge_type,
                src=src[sel], dst=dst[sel], num_nodes=int(num_nodes),
                weights=None if weights is None else weights[sel])
        return total

    def build(self, edge_type: str) -> int:
        return sum(self._call(sv, "build", edge_type=edge_type)
                   for sv in range(self.num_servers))

    def _shard_sel(self, nodes: np.ndarray):
        return [(sv, np.flatnonzero((nodes % self.num_servers) == sv))
                for sv in range(self.num_servers)]

    def sample_neighbors(self, edge_type: str, nodes: np.ndarray, k: int,
                         *, seed: int = 0,
                         weighted: bool = False) -> np.ndarray:
        nodes = np.asarray(nodes, np.int64)
        out = np.full((nodes.shape[0], k), -1, np.int64)
        shards = [(sv, sel) for sv, sel in self._shard_sel(nodes)
                  if sel.size]
        res = self._fanout([(sv, "sample_neighbors",
                             dict(edge_type=edge_type, nodes=nodes[sel],
                                  k=int(k), seed=int(seed),
                                  weighted=bool(weighted)))
                            for sv, sel in shards])
        for (sv, sel), r in zip(shards, res):
            out[sel] = r
        return out

    def degrees(self, edge_type: str, nodes: np.ndarray) -> np.ndarray:
        nodes = np.asarray(nodes, np.int64)
        out = np.zeros((nodes.shape[0],), np.int64)
        shards = [(sv, sel) for sv, sel in self._shard_sel(nodes)
                  if sel.size]
        res = self._fanout([(sv, "degrees",
                             dict(edge_type=edge_type, nodes=nodes[sel]))
                            for sv, sel in shards])
        for (sv, sel), r in zip(shards, res):
            out[sel] = r
        return out

    def set_node_feat(self, name: str, nodes: np.ndarray,
                      values: np.ndarray) -> None:
        nodes = np.asarray(nodes, np.int64)
        values = np.asarray(values)
        shards = [(sv, sel) for sv, sel in self._shard_sel(nodes)
                  if sel.size]
        self._fanout([(sv, "set_node_feat",
                       dict(name=name, nodes=nodes[sel],
                            values=values[sel]))
                      for sv, sel in shards])

    def get_node_feat(self, name: str, nodes: np.ndarray) -> np.ndarray:
        nodes = np.asarray(nodes, np.int64)
        if nodes.size == 0:
            return np.zeros((0,), np.float32)
        shards = [(sv, sel) for sv, sel in self._shard_sel(nodes)
                  if sel.size]
        res = self._fanout([(sv, "get_node_feat",
                             dict(name=name, nodes=nodes[sel]))
                            for sv, sel in shards])
        known = [r for r in res if r["width"] >= 0]
        if not known:
            raise KeyError(f"node feature {name!r} unknown on every shard")
        first = known[0]["rows"]
        out = np.zeros((nodes.shape[0],) + first.shape[1:], first.dtype)
        for (sv, sel), r in zip(shards, res):
            if r["width"] >= 0:
                out[sel] = r["rows"]
        return out

    def random_walk(self, edge_type: str, starts: np.ndarray, length: int,
                    *, seed: int = 0, weighted: bool = False) -> np.ndarray:
        """[n, length+1] walks via per-hop fan-out sampling (each hop's
        frontier may live on any shard — the client re-shards per hop,
        role of the graph client driving multi-hop sampling)."""
        return self.metapath_walk([edge_type] * length, starts, seed=seed,
                                  weighted=weighted)

    def metapath_walk(self, edge_types: Sequence[str], starts: np.ndarray,
                      *, seed: int = 0,
                      weighted: bool = False) -> np.ndarray:
        """[n, len(edge_types)+1] walks where hop h samples from
        ``edge_types[h]`` (role of the reference's meta-path walks over
        typed adjacency — graph_gpu_wrapper.h:25 metapath config, e.g.
        user2item → item2user): per hop the frontier re-shards by owner
        and the hop's edge type routes the sample. Deterministic per
        (seed, node, hop) exactly like single-type walks — shard-layout
        invariant. Dead ends stay in place."""
        starts = np.asarray(starts, np.int64)
        walk = np.empty((starts.shape[0], len(edge_types) + 1), np.int64)
        walk[:, 0] = starts
        cur = starts
        for h, et in enumerate(edge_types):
            nxt = self.sample_neighbors(et, cur, 1, seed=seed + 1 + h,
                                        weighted=weighted)[:, 0]
            # Dead ends stay in place (same convention as the device
            # sampler's isolated-node handling).
            nxt = np.where(nxt < 0, cur, nxt)
            walk[:, h + 1] = nxt
            cur = nxt
        return walk

    def stop_servers(self) -> None:
        for sv in range(self.num_servers):
            try:
                self._call(sv, "stop")
            except (RuntimeError, OSError, ConnectionError):
                pass

    def close(self) -> None:
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
