"""GraphDataGenerator: walks → skip-gram training batches.

Role of the reference ``GraphDataGenerator`` (``framework/data_feed.h:892``,
CUDA fill in ``data_feed.cu``): the graph-learning data feed that walks the
GPU-resident graph and emits (center, context, negatives) minibatches to
the trainer, double-buffered ahead of consumption.

TPU-first: batches have STATIC shapes — ``batch_pairs`` pairs per step with
``num_neg`` negatives each, masks instead of ragged drops — so the train
step jits once. Walk generation runs on device (sampler.random_walk);
iteration state is a host-side cursor over shuffled start nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.graph import sampler
from paddlebox_tpu.graph.table import DeviceGraph, GraphTable


@dataclasses.dataclass(frozen=True)
class GraphGenConfig:
    """Knobs mirroring the reference's graph_config fields in
    DataFeedDesc (``data_feed.proto`` graph_config: walk_len, walk_degree,
    window, batch_size, samples)."""

    walk_len: int = 8
    window: int = 3
    num_neg: int = 4
    batch_walks: int = 64       # start nodes per generated chunk
    seed: int = 0


class GraphDataGenerator:
    """Iterate (centers, contexts, negatives, mask) static-shape batches."""

    def __init__(self, table: GraphTable, edge_type: str,
                 config: GraphGenConfig = GraphGenConfig(),
                 max_degree: Optional[int] = None):
        self.config = config
        self.table = table
        g = table.device_graph(edge_type, max_degree)
        self._nbrs, self._deg = sampler.device_arrays(g)
        self._num_nodes = g.nbrs.shape[0]
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)

    def _next_key(self) -> jax.Array:
        self._key, k = jax.random.split(self._key)
        return k

    def batches(self, epochs: int = 1) -> Iterator[Dict[str, jax.Array]]:
        """Yield skip-gram batches covering every node's walks per epoch
        (role of DoWalkandSage/GenerateSampleBatch)."""
        cfg = self.config
        for _ in range(epochs):
            starts = self._rng.permutation(self._num_nodes)
            for i in range(0, len(starts), cfg.batch_walks):
                chunk = starts[i:i + cfg.batch_walks]
                if len(chunk) < cfg.batch_walks:  # pad to static shape
                    pad = self._rng.choice(starts, cfg.batch_walks
                                           - len(chunk))
                    chunk = np.concatenate([chunk, pad])
                walks = sampler.random_walk(
                    self._nbrs, self._deg, jnp.asarray(chunk, jnp.int32),
                    self._next_key(), cfg.walk_len)
                pairs = sampler.skip_gram_pairs(walks, cfg.window)
                negs = sampler.negative_samples(
                    self._next_key(), pairs.shape[0], cfg.num_neg,
                    self._num_nodes)
                yield {
                    "centers": pairs[:, 0],
                    "contexts": pairs[:, 1],
                    "negatives": negs,
                    # boundary-crossing pairs were emitted as self-pairs
                    "mask": (pairs[:, 0] != pairs[:, 1]),
                }
