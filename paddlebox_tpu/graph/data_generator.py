"""GraphDataGenerator: walks → skip-gram training batches.

Role of the reference ``GraphDataGenerator`` (``framework/data_feed.h:892``,
CUDA fill in ``data_feed.cu``): the graph-learning data feed that walks the
GPU-resident graph and emits (center, context, negatives) minibatches to
the trainer, double-buffered ahead of consumption.

TPU-first: batches have STATIC shapes — ``batch_pairs`` pairs per step with
``num_neg`` negatives each, masks instead of ragged drops — so the train
step jits once. Walk generation runs on device (sampler.random_walk);
iteration state is a host-side cursor over shuffled start nodes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.graph import sampler
from paddlebox_tpu.graph.table import DeviceGraph, GraphTable


@dataclasses.dataclass(frozen=True)
class GraphGenConfig:
    """Knobs mirroring the reference's graph_config fields in
    DataFeedDesc (``data_feed.proto`` graph_config: walk_len, walk_degree,
    window, batch_size, samples, meta_path).

    ``metapath``: when set (a tuple of edge-type names) walks alternate
    edge types per hop, cycling the tuple to ``walk_len`` hops (the
    reference's meta_path config). ``start_type``: restrict walk starts
    to nodes of that type (table.set_node_types/load_node_file — the
    reference's typed frontier: a user→item metapath starts from user
    nodes). ``degree_negatives``: draw negatives ∝ degree^0.75 instead
    of uniform. ``feat_name``: attach each batch's center-node feature
    rows (device gather from the table's feature column — the
    node-feature-pulling half of the graph engine)."""

    walk_len: int = 8
    window: int = 3
    num_neg: int = 4
    batch_walks: int = 64       # start nodes per generated chunk
    seed: int = 0
    metapath: Optional[tuple] = None
    start_type: Optional[int] = None
    degree_negatives: bool = False
    feat_name: Optional[str] = None
    # Hops draw neighbors proportional to per-edge weight (requires the
    # CSR built with weights= — the reference's is_weighted walk mode,
    # common_graph_table.h:128-152).
    weighted: bool = False


class GraphDataGenerator:
    """Iterate (centers, contexts, negatives, mask) static-shape batches."""

    def __init__(self, table: GraphTable, edge_type: str,
                 config: GraphGenConfig = GraphGenConfig(),
                 max_degree: Optional[int] = None):
        self.config = config
        self.table = table
        g = table.device_graph(edge_type, max_degree)
        self._nbrs, self._deg = sampler.device_arrays(g)
        # Metapath walks only ever read the stacked per-type CDFs — the
        # base type's CDF would be dead weight (and wrongly require the
        # base graph to be weighted).
        self._cdf = (sampler.device_cdf(g)
                     if config.weighted and not config.metapath else None)
        self._num_nodes = g.nbrs.shape[0]
        self._type_seq = None
        if config.metapath:
            views = [table.device_graph(et, max_degree)
                     for et in config.metapath]
            self._mp_nbrs, self._mp_deg = sampler.stack_device_graphs(views)
            self._mp_cdf = (sampler.stack_device_cdfs(views)
                            if config.weighted else None)
            self._type_seq = tuple(
                i % len(config.metapath) for i in range(config.walk_len))
        self._neg_cdf = None
        if config.degree_negatives:
            self._neg_cdf = sampler.degree_neg_cdf(g.degree)
        self._feats = (table.device_feats(config.feat_name)
                       if config.feat_name else None)
        if config.start_type is not None:
            self._start_pool = table.nodes_of_type(config.start_type)
            if self._start_pool.size == 0:
                raise ValueError(
                    f"no nodes of type {config.start_type} to start from")
            if int(self._start_pool.max()) >= self._num_nodes:
                # jnp's clamping gather would otherwise silently walk
                # from the wrong node when the node-type table is larger
                # than the walk graph.
                raise ValueError(
                    f"typed start pool has node "
                    f"{int(self._start_pool.max())} outside the walk "
                    f"graph's {self._num_nodes} nodes")
        else:
            self._start_pool = np.arange(self._num_nodes)
        self._rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)

    def _next_key(self) -> jax.Array:
        self._key, k = jax.random.split(self._key)
        return k

    def batches(self, epochs: int = 1) -> Iterator[Dict[str, jax.Array]]:
        """Yield skip-gram batches covering every start-pool node's walks
        per epoch (role of DoWalkandSage/GenerateSampleBatch)."""
        cfg = self.config
        for _ in range(epochs):
            starts = self._rng.permutation(self._start_pool)
            for i in range(0, len(starts), cfg.batch_walks):
                chunk = starts[i:i + cfg.batch_walks]
                if len(chunk) < cfg.batch_walks:  # pad to static shape
                    pad = self._rng.choice(starts, cfg.batch_walks
                                           - len(chunk))
                    chunk = np.concatenate([chunk, pad])
                if self._type_seq is not None:
                    if self._mp_cdf is not None:
                        walks = sampler.metapath_walk_weighted(
                            self._mp_nbrs, self._mp_cdf,
                            jnp.asarray(chunk, jnp.int32),
                            self._next_key(), self._type_seq)
                    else:
                        walks = sampler.metapath_walk(
                            self._mp_nbrs, self._mp_deg,
                            jnp.asarray(chunk, jnp.int32), self._next_key(),
                            self._type_seq)
                elif self._cdf is not None:
                    walks = sampler.random_walk_weighted(
                        self._nbrs, self._cdf,
                        jnp.asarray(chunk, jnp.int32),
                        self._next_key(), cfg.walk_len)
                else:
                    walks = sampler.random_walk(
                        self._nbrs, self._deg,
                        jnp.asarray(chunk, jnp.int32),
                        self._next_key(), cfg.walk_len)
                pairs = sampler.skip_gram_pairs(walks, cfg.window)
                if self._neg_cdf is not None:
                    negs = sampler.negative_samples_by_degree(
                        self._next_key(), self._neg_cdf,
                        int(pairs.shape[0]), cfg.num_neg)
                else:
                    negs = sampler.negative_samples(
                        self._next_key(), pairs.shape[0], cfg.num_neg,
                        self._num_nodes)
                out = {
                    "centers": pairs[:, 0],
                    "contexts": pairs[:, 1],
                    "negatives": negs,
                    # boundary-crossing pairs were emitted as self-pairs
                    "mask": (pairs[:, 0] != pairs[:, 1]),
                }
                if self._feats is not None:
                    out["center_feats"] = sampler.gather_node_feats(
                        self._feats, pairs[:, 0])
                yield out
