"""InputTableDataset: string-keyed slots mapped to dense row indices.

Role of ``InputTableDataset`` (``data_set.h:568``) + the BoxWrapper
``InputTable`` (``box_wrapper.h:124-197``) + the ``lookup_input`` op: raw
string features (URLs, app ids) are interned into a process-wide
string→index dictionary at LOAD time, the index flows through the graph
as an ordinary feasign, and at train time ``lookup_input`` gathers the
row from a replicated aux table.

TPU-first: the interned index + 1 is stored as the slot's feasign (0 is
the padding sentinel downstream, so real index i rides as i+1);
:func:`lookup_input` undoes the offset against a
:class:`~paddlebox_tpu.embedding.cache.ReplicaCache`, whose replicated
sharding makes the gather collective-free on every chip.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

import jax
import jax.numpy as jnp

from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.parser import get_parser
from paddlebox_tpu.data.slots import DataFeedConfig, Instance
from paddlebox_tpu.embedding.cache import InputTable, ReplicaCache


def make_input_table_parser(table: InputTable, string_slots: Set[str],
                            base_parser: str = "svm"):
    """Wrap a registered parser so tokens of ``string_slots`` are interned
    through ``table`` BEFORE the base parser sees them (the base parser
    then treats the interned index+1 as an ordinary feasign)."""
    def parse(lines, config: DataFeedConfig) -> List[Instance]:
        nl = config.num_labels
        rewritten = []
        for line in lines:
            toks = line.split()
            if len(toks) < nl:
                rewritten.append(line)
                continue
            out_toks = toks[:nl]
            for tok in toks[nl:]:
                slot, sep, val = tok.partition(":")
                # Empty values stay malformed: the plain path drops such
                # lines, and interning '' would silently train a phantom
                # empty-string feature instead.
                if sep and val and slot in string_slots:
                    idx = table.add(val)
                    out_toks.append(f"{slot}:{idx + 1}")  # 0 = padding
                    monitor.add("input_table/interned")
                else:
                    out_toks.append(tok)
            rewritten.append(" ".join(out_toks))
        return get_parser(base_parser)(rewritten, config)

    return parse


class InputTableDataset(Dataset):
    """Dataset whose ``string_slots`` are interned via an InputTable at
    load time (role of InputTableDataset, data_set.h:568)."""

    def __init__(self, config: DataFeedConfig,
                 string_slots: Sequence[str],
                 table: Optional[InputTable] = None, **kw):
        self.input_table = table if table is not None else InputTable()
        self.string_slots = set(string_slots)
        unknown = self.string_slots - {s.name for s in config.sparse_slots}
        if unknown:
            raise ValueError(
                f"string_slots {sorted(unknown)} are not sparse slots of "
                "the feed config")
        # Instance-scoped parser hook — registering a uniquely-named
        # closure in the global registry would leak one entry (pinning
        # this table) per dataset instance across day-over-day loops.
        super().__init__(config, parser_fn=make_input_table_parser(
            self.input_table, self.string_slots, config.parser), **kw)


def lookup_input(cache: ReplicaCache, ids: jax.Array) -> jax.Array:
    """Gather aux-table rows for interned slot feasigns (role of the
    ``lookup_input`` op): feasign i+1 → cache row i; padding feasign 0
    (and any unseen id past the cache) yields zeros."""
    return cache.pull(ids.astype(jnp.int32) - 1)
