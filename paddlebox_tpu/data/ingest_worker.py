"""Ingest worker process: parse file blocks → ColumnarChunk → shm frames.

The child half of the multi-process columnar ingest
(``FLAGS_ingest_workers``; role of the reference's reader thread pool,
``data_set.cc:2283``, moved across a process boundary so the parse runs
on real cores instead of GIL turns). Each worker pulls whole files from
a shared task queue, parses them block-by-block with the SAME
``_parse_block`` the thread path uses (native C++ → vectorized numpy →
exact per-line fallback), writes each chunk into a shared-memory frame
(``data/shm_channel.py``) and reports frames/progress over the message
queue. The parent commits a file's frames only after ``file_done`` — a
worker dying mid-file leaves no partial rows behind.

Message protocol (every tuple starts with the kind and worker id)::

    ("file_start", wid, path)
    ("chunk",      wid, path, seg_name, num_rows, nbytes)
    ("file_done",  wid, path, num_rows)
    ("file_error", wid, path, exc_type_name, exc_msg)
    ("exit",       wid)

Errors mirror the thread path: one failing file ends the worker (its
remaining queue files are drained by siblings), and the error surfaces
through ``Dataset._reader_errors``.
"""

from __future__ import annotations

import queue

from paddlebox_tpu.data import shm_channel
from paddlebox_tpu.data.slots import DataFeedConfig


def worker_main(worker_id: int, parent_pid: int, load_id: int, task_q,
                msg_q, config: DataFeedConfig) -> None:
    """Process entry point (spawn-safe: module-level, picklable args)."""
    # Imported here, not at module top: the spawn child pays the package
    # import either way, but keeping the entry's import surface explicit
    # documents what the worker actually needs.
    from paddlebox_tpu.data.dataset import _parse_block, _read_blocks
    serial = 0
    try:
        while True:
            try:
                path = task_q.get_nowait()
            except queue.Empty:
                return
            msg_q.put(("file_start", worker_id, path))
            n_rows = 0
            try:
                for block in _read_blocks(path, config.pipe_command):
                    chunk = _parse_block(block, config, None)
                    name = shm_channel.seg_name(parent_pid, load_id,
                                                worker_id, serial)
                    serial += 1
                    nbytes = shm_channel.write_chunk(chunk, name)
                    msg_q.put(("chunk", worker_id, path, name,
                               chunk.num_rows, nbytes))
                    n_rows += chunk.num_rows
            except BaseException as e:
                # Send (type name, message); the parent rebuilds the
                # closest builtin exception — pickling arbitrary
                # exception objects across the queue is not reliable.
                msg_q.put(("file_error", worker_id, path,
                           type(e).__name__, str(e)))
                return
            msg_q.put(("file_done", worker_id, path, n_rows))
    finally:
        try:
            msg_q.put(("exit", worker_id))
            msg_q.close()
            msg_q.join_thread()  # flush the feeder before the process dies
        except Exception:
            pass
