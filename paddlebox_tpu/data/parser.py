"""Line parsers: text → Instance, with a pluggable parser registry.

Role of the reference's reader parse paths
(``MultiSlotInMemoryDataFeed``/``SlotRecordInMemoryDataFeed`` text parsing,
``data_feed.cc:2142-2395``) and the ``CustomParser``/``DLManager`` dlopen
plugin interface (``data_feed.h:446,682``). TPU build: parsers are python
callables registered by name (a C-extension fast path can register under the
same name later); ``pipe_command`` preprocessing is handled by the Dataset.

Built-in ``svm`` format, one instance per line:

    <label...> <slot>:<feasign> <slot>:<feasign> ... <slot>:v1,v2,v3 ...

- the first ``num_labels`` whitespace tokens are float labels
- sparse slot tokens carry a uint64 feasign after the colon
- dense slot tokens carry a comma-separated float vector
- unknown slots are ignored (slot filtering = is_used in the reference)
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.slots import DataFeedConfig, Instance

Parser = Callable[[Iterable[str], DataFeedConfig], List[Instance]]

_REGISTRY: Dict[str, Parser] = {}


def register_parser(name: str, fn: Parser) -> None:
    _REGISTRY[name] = fn


def get_parser(name: str) -> Parser:
    if name not in _REGISTRY:
        raise KeyError(f"unknown parser {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def parse_lines(lines: Iterable[str], config: DataFeedConfig) -> List[Instance]:
    return get_parser(config.parser)(lines, config)


def _parse_svm(lines: Iterable[str], config: DataFeedConfig) -> List[Instance]:
    sparse_names = {s.name for s in config.sparse_slots}
    dense_names = {s.name for s in config.dense_slots}
    nl = config.num_labels
    out: List[Instance] = []
    for line in lines:
        toks = line.split()
        if len(toks) < nl:
            continue  # malformed line: skip, like the reference readers do
        try:
            labels = np.array([float(t) for t in toks[:nl]], np.float32)
            sparse: Dict[str, List[int]] = {}
            dense: Dict[str, np.ndarray] = {}
            for tok in toks[nl:]:
                slot, sep, val = tok.partition(":")
                if not sep:
                    raise ValueError(f"token without ':': {tok!r}")
                if slot in sparse_names:
                    sign = int(val)
                    if not 0 < sign < (1 << 64):
                        # 0 is the null/padding sentinel downstream — a
                        # real 0 feature would silently never train, so
                        # drop the token loudly (counter), keep the line.
                        monitor.add("parser/null_or_oob_feasign")
                        continue
                    sparse.setdefault(slot, []).append(sign)
                elif slot in dense_names:
                    dense[slot] = np.array(
                        [float(x) for x in val.split(",")], np.float32)
                # else: unused slot — ignore
            ins = Instance(
                labels=labels,
                sparse={k: np.array(v, np.uint64) for k, v in sparse.items()},
                dense=dense,
            )
        except (ValueError, OverflowError):
            monitor.add("parser/malformed_lines")
            continue
        out.append(ins)
    return out


register_parser("svm", _parse_svm)
