"""Line parsers: text → Instance, with a pluggable parser registry.

Role of the reference's reader parse paths
(``MultiSlotInMemoryDataFeed``/``SlotRecordInMemoryDataFeed`` text parsing,
``data_feed.cc:2142-2395``) and the ``CustomParser``/``DLManager`` dlopen
plugin interface (``data_feed.h:446,682``). TPU build: parsers are python
callables registered by name (a C-extension fast path can register under the
same name later); ``pipe_command`` preprocessing is handled by the Dataset.

Built-in ``svm`` format, one instance per line:

    <label...> <slot>:<feasign> <slot>:<feasign> ... <slot>:v1,v2,v3 ...

- the first ``num_labels`` whitespace tokens are float labels
- sparse slot tokens carry a uint64 feasign after the colon
- dense slot tokens carry a comma-separated float vector
- unknown slots are ignored (slot filtering = is_used in the reference)
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.slots import DataFeedConfig, Instance

Parser = Callable[[Iterable[str], DataFeedConfig], List[Instance]]

_REGISTRY: Dict[str, Parser] = {}


def register_parser(name: str, fn: Parser) -> None:
    _REGISTRY[name] = fn


def get_parser(name: str) -> Parser:
    if name not in _REGISTRY:
        raise KeyError(f"unknown parser {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def parse_lines(lines: Iterable[str], config: DataFeedConfig) -> List[Instance]:
    return get_parser(config.parser)(lines, config)


def _parse_svm(lines: Iterable[str], config: DataFeedConfig) -> List[Instance]:
    sparse_names = {s.name for s in config.sparse_slots}
    dense_names = {s.name for s in config.dense_slots}
    nl = config.num_labels
    out: List[Instance] = []
    for line in lines:
        toks = line.split()
        if len(toks) < nl:
            continue  # malformed line: skip, like the reference readers do
        try:
            labels = np.array([float(t) for t in toks[:nl]], np.float32)
            sparse: Dict[str, List[int]] = {}
            dense: Dict[str, np.ndarray] = {}
            for tok in toks[nl:]:
                slot, sep, val = tok.partition(":")
                if not sep:
                    raise ValueError(f"token without ':': {tok!r}")
                if slot in sparse_names:
                    sign = int(val)
                    if not 0 < sign < (1 << 64):
                        # 0 is the null/padding sentinel downstream — a
                        # real 0 feature would silently never train, so
                        # drop the token loudly (counter), keep the line.
                        monitor.add("parser/null_or_oob_feasign")
                        continue
                    sparse.setdefault(slot, []).append(sign)
                elif slot in dense_names:
                    dense[slot] = np.array(
                        [float(x) for x in val.split(",")], np.float32)
                # else: unused slot — ignore
            ins = Instance(
                labels=labels,
                sparse={k: np.array(v, np.uint64) for k, v in sparse.items()},
                dense=dense,
            )
        except (ValueError, OverflowError):
            monitor.add("parser/malformed_lines")
            continue
        out.append(ins)
    return out


register_parser("svm", _parse_svm)


# -- vectorized bulk svm parse (the no-native fast fallback) ----------------
#
# Parses a whole newline-framed byte block into a ColumnarChunk with numpy
# bulk string→numeric casts (numpy parses S-dtype arrays to uint64/float32
# in C) instead of the per-line/per-token python loop above. Bit-identical
# to ``instances_to_chunk(_parse_svm(lines))`` on well-formed input; any
# input the bulk path cannot prove it handles identically (malformed
# labels, missing ':', exotic whitespace, negative/huge feasigns, ragged
# dense vectors) returns None and the caller falls back to the exact
# per-line parser — semantics are never approximated, only accelerated.

_WS_ODD = (9, 11, 12, 13)  # \t \v \f \r: str.split() treats as separators


def _extract(u8: np.ndarray, starts: np.ndarray, lens: np.ndarray,
             width: int) -> np.ndarray:
    """Gather variable-length byte slices into one null-padded [n, width]
    matrix viewed as an S-dtype array — numpy then parses the whole
    column to a numeric dtype in C (S→uint64/float32 casts)."""
    if starts.size == 0 or width == 0:
        return np.empty((starts.size,), f"S{max(width, 1)}")
    idx = starts[:, None] + np.arange(width)
    mat = np.where(np.arange(width) < lens[:, None],
                   u8[np.minimum(idx, u8.size - 1)], 0).astype(np.uint8)
    return np.ascontiguousarray(mat).view(f"S{width}").ravel()


def parse_block_numpy(block: bytes, config: DataFeedConfig):
    """Bulk-parse an svm text block into a ColumnarChunk (None = input
    needs the exact per-line fallback). Works directly on the byte
    buffer: token/line/colon boundaries come from vectorized delimiter
    scans, values parse via numpy's C-level S→numeric casts."""
    from paddlebox_tpu.data.columnar import ColumnarChunk
    nl = config.num_labels
    if nl == 0 or not block:
        return None  # degenerate label config: empty lines become rows
    # Any non-space whitespace, non-ascii byte (utf-8 multibyte or the
    # decode-replace path), double/leading/trailing spaces → slow path.
    if not block.endswith(b"\n"):
        block = block + b"\n"
    u8 = np.frombuffer(block, np.uint8)
    if int(u8.max()) > 127:
        return None
    if np.isin(u8, np.array(_WS_ODD, np.uint8)).any():
        return None
    if b"  " in block or block.startswith(b" ") or b" \n" in block \
            or b"\n " in block:
        return None

    # -- token geometry: every delimiter ends exactly one (possibly
    # empty) token; empty tokens are the empty lines.
    nlpos = np.flatnonzero(u8 == 10)
    dpos = np.flatnonzero((u8 == 10) | (u8 == 32))
    starts = np.empty_like(dpos)
    starts[0] = 0
    starts[1:] = dpos[:-1] + 1
    tlens = dpos - starts
    tok = tlens > 0
    starts, ends = starts[tok], dpos[tok]
    n_lines = nlpos.size
    line_of_tok = np.searchsorted(nlpos, starts)
    counts = np.bincount(line_of_tok, minlength=n_lines)
    offs = np.zeros(n_lines + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    rank = np.arange(starts.size) - offs[line_of_tok]

    # Lines with fewer tokens than labels are skipped (exact-path rule).
    keep_line = counts >= nl
    n = int(keep_line.sum())
    row_of_line = np.cumsum(keep_line) - 1
    keep_tok = keep_line[line_of_tok]
    starts, ends = starts[keep_tok], ends[keep_tok]
    row_of_tok = row_of_line[line_of_tok[keep_tok]]
    rank = rank[keep_tok]

    lab = rank < nl
    try:
        lens_l = ends[lab] - starts[lab]
        labels = _extract(u8, starts[lab], lens_l,
                          int(lens_l.max()) if lens_l.size else 0
                          ).astype(np.float32).reshape(n, nl)
    except ValueError:
        return None

    # -- feature tokens: first ':' inside the token splits name from
    # value; a feature token without one is a malformed line upstream.
    feat = np.flatnonzero(~lab)
    fstart, fend, frow = starts[feat], ends[feat], row_of_tok[feat]
    cpos = np.flatnonzero(u8 == 58)
    ci = np.minimum(np.searchsorted(cpos, fstart), max(cpos.size - 1, 0))
    colon = cpos[ci] if cpos.size else np.full(fstart.shape, -1)
    if fstart.size and (cpos.size == 0 or not (
            (colon >= fstart) & (colon < fend)).all()):
        return None
    nlen = colon - fstart
    vstart = colon + 1
    vlen = fend - vstart
    if fstart.size and int(vlen.min()) == 0:
        return None  # "slot:" empty value → malformed line upstream

    # One 8-byte name key per token (null-padded S8), so each slot match
    # is a single vectorized compare instead of a per-slot byte gather —
    # at 26 slots the gather-per-slot walk dominated the whole parse.
    nkey = _extract(u8, fstart, np.minimum(nlen, 8), 8)

    def slot_tokens(name: str):
        nb = name.encode()
        if not nb:
            return np.empty((0,), np.int64)
        m = np.flatnonzero((nkey == np.bytes_(nb[:8]))
                           & (nlen == len(nb)))
        if len(nb) > 8 and m.size:
            tail = np.frombuffer(nb[8:], np.uint8)
            eq = (u8[(fstart[m] + 8)[:, None] + np.arange(tail.size)]
                  == tail).all(axis=1)
            m = m[eq]
        return m

    ids: Dict[str, np.ndarray] = {}
    offsets: Dict[str, np.ndarray] = {}
    for slot in config.sparse_slots:
        m = slot_tokens(slot.name)
        vl = vlen[m]
        # ≥ 20 digits may exceed uint64 — the exact path's range check
        # decides drop-vs-keep there.
        if m.size and int(vl.max()) >= 20:
            return None
        try:
            signs = _extract(u8, vstart[m], vl,
                             int(vl.max()) if m.size else 0
                             ).astype(np.uint64)
        except (ValueError, OverflowError):
            return None  # negative / junk → exact path decides drop-vs-skip
        r = frow[m]
        nz = signs != 0
        if not nz.all():
            monitor.add("parser/null_or_oob_feasign", int((~nz).sum()))
            signs, r = signs[nz], r[nz]
        lens = np.bincount(r, minlength=n).astype(np.int64)
        o = np.zeros(n + 1, np.int64)
        np.cumsum(lens, out=o[1:])
        ids[slot.name] = signs
        offsets[slot.name] = o

    dense: Dict[str, np.ndarray] = {}
    for slot in config.dense_slots:
        d = np.zeros((n, slot.dim), np.float32)
        m = slot_tokens(slot.name)
        if m.size:
            vl = vlen[m]
            vals = _extract(u8, vstart[m], vl, int(vl.max()))
            # The per-line parser keeps the LAST token per row (dict
            # overwrite); ragged widths go to the exact path.
            ncommas = np.char.count(vals, b",")
            if ncommas.min() != ncommas.max():
                return None
            width = int(ncommas[0]) + 1
            flat = b",".join(vals.tolist()).split(b",")
            try:
                dv = np.array(flat).astype(np.float32).reshape(
                    m.size, width)
            except ValueError:
                return None
            w = min(width, slot.dim)
            d[frow[m], :w] = dv[:, :w]  # later tokens overwrite dups
        dense[slot.name] = d

    return ColumnarChunk(labels=labels, sparse_ids=ids,
                         sparse_offsets=offsets, dense=dense)
