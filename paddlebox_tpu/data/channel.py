"""Bounded MPMC channel — the pipe between pipeline stages.

Role of ``paddle/fluid/framework/channel.h`` (``Channel<T>``/``MakeChannel``):
the universal bounded queue connecting read → merge → shuffle → train stages,
with close semantics so consumers drain and exit cleanly.
"""

from __future__ import annotations

import collections
import threading
from typing import Generic, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")


class ClosedChannelError(Exception):
    pass


class Channel(Generic[T]):
    """Bounded blocking channel with close semantics.

    ``put`` blocks when full; ``get`` blocks when empty and raises
    ``ClosedChannelError`` once the channel is closed AND drained —
    mirroring the reference channel's read-returns-false-on-closed-empty.
    """

    def __init__(self, capacity: int = 0):
        self._cap = capacity  # 0 = unbounded
        self._q: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False

    def put(self, item: T) -> None:
        with self._lock:
            while self._cap and len(self._q) >= self._cap and not self._closed:
                self._not_full.wait()
            if self._closed:
                raise ClosedChannelError("put on closed channel")
            self._q.append(item)
            self._not_empty.notify()

    def put_many(self, items: Iterable[T]) -> None:
        """Bulk put: appends in capacity-sized runs under one lock
        acquisition each (hot path for reader threads)."""
        pending = list(items)
        i = 0
        while i < len(pending):
            with self._lock:
                while self._cap and len(self._q) >= self._cap \
                        and not self._closed:
                    self._not_full.wait()
                if self._closed:
                    raise ClosedChannelError("put on closed channel")
                room = (self._cap - len(self._q)) if self._cap \
                    else len(pending) - i
                take = max(1, room)
                self._q.extend(pending[i:i + take])
                i += take
                self._not_empty.notify_all()

    def get(self, timeout: Optional[float] = None) -> T:
        with self._lock:
            while not self._q:
                if self._closed:
                    raise ClosedChannelError("channel closed and drained")
                if not self._not_empty.wait(timeout=timeout):
                    raise TimeoutError("channel get timed out")
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def get_many(self, n: int) -> List[T]:
        """Take up to n items; returns fewer at end-of-stream (>=1), raises
        when closed-and-drained."""
        out: List[T] = []
        with self._lock:
            while not self._q:
                if self._closed:
                    raise ClosedChannelError("channel closed and drained")
                self._not_empty.wait()
            while self._q and len(out) < n:
                out.append(self._q.popleft())
            self._not_full.notify_all()
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def __iter__(self) -> Iterator[T]:
        while True:
            try:
                yield self.get()
            except ClosedChannelError:
                return
