"""Slot configuration and static-shape columnar batches.

Role of the reference's slot machinery:
- ``DataFeedDesc`` proto (``data_feed.proto:17-57``): slot name/type/
  is_dense/is_used/shape + batch size + pipe command → here a dataclass.
- ``SlotRecordObject``/``SlotValues`` (``data_feed.h:97,202``): per-instance
  ragged slot storage → here instances live as parsed numpy fragments and
  are packed straight into columnar batches.
- ``BuildSlotBatchGPU``/``CopyForTensor`` CUDA packing (``data_feed.cc:2713``,
  ``data_feed.cu:161``) → here :meth:`SlotBatch.pack`, a vectorized host
  pack into STATIC shapes (padded CSR) so XLA compiles the train step once.

Static-shape discipline (replaces LoD): each sparse slot gets a fixed
per-batch value capacity ``cap = batch_size * avg_len * slack`` rounded up
to a multiple of 8. Overflow values are dropped with a monitor count
(CTR slot data is heavy-tailed; the reference's enable_pv_merge path makes
the same kind of truncation trade elsewhere).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import monitor


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class SlotConf:
    """One input slot (role of ``Slot`` in data_feed.proto:24-33)."""

    name: str
    is_dense: bool = False
    # Dense: feature dim. Sparse: ignored (ids are scalar feasigns).
    dim: int = 1
    # Sparse only: expected average #ids per instance (capacity planning).
    avg_len: float = 1.0
    # Sparse only: hard cap of ids kept per instance (0 = unlimited).
    max_len: int = 0
    is_used: bool = True
    # Sparse only: mf embedding width for this slot; None = the table's
    # default dim. Role of the per-slot dynamic mf dim in the reference
    # (CtrDymfAccessor, ps/table/ctr_dymf_accessor.h; mf_dim in the HBM
    # value record, heter_ps/feature_value.h:44-120) — production CTR
    # models mix e.g. 8/16/64-wide slots in one model.
    emb_dim: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class DataFeedConfig:
    """Reader configuration (role of DataFeedDesc, data_feed.proto:43-57)."""

    slots: Tuple[SlotConf, ...]
    batch_size: int = 64
    num_labels: int = 1
    pipe_command: str = ""            # shell filter per file ("" = plain read)
    slot_capacity_slack: float = 1.3  # headroom over batch*avg_len
    parser: str = "svm"               # registered parser name

    def __post_init__(self):
        names = [s.name for s in self.slots]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slot names in {names}")

    @property
    def sparse_slots(self) -> List[SlotConf]:
        return [s for s in self.slots if not s.is_dense and s.is_used]

    @property
    def dense_slots(self) -> List[SlotConf]:
        return [s for s in self.slots if s.is_dense and s.is_used]

    def sparse_capacity(self, slot: SlotConf,
                        batch_size: Optional[int] = None,
                        num_shards: int = 1) -> int:
        """Per-batch value capacity for a sparse slot; always a multiple of
        ``num_shards`` (so the array shards evenly over a dp mesh axis) and
        of 8 per shard."""
        bs = batch_size or self.batch_size
        cap = int(bs * slot.avg_len * self.slot_capacity_slack)
        cap_local = -(-max(cap, bs, 1) // num_shards)
        return _round_up(cap_local, 8) * num_shards


@dataclasses.dataclass
class Instance:
    """One parsed example: labels + ragged sparse ids + dense values.

    The in-flight record between parser and batch pack (role of
    SlotRecordObject). Kept deliberately thin — numpy arrays, no pooling;
    CPython refcounting plays the role of the reference's SlotObjPool.
    """

    labels: np.ndarray                       # [num_labels] float32
    sparse: Dict[str, np.ndarray]            # slot -> [n] uint64 feasigns
    dense: Dict[str, np.ndarray]             # slot -> [dim] float32


@dataclasses.dataclass
class SlotBatch:
    """A static-shape columnar minibatch (the device-feedable pytree).

    For each sparse slot ``s``:
      ids[s]      [cap]  uint64 — feasigns, zero-padded
      segments[s] [cap]  int32  — row index per id; ``batch_size`` for pads
                                  (so segment_sum with num_segments=B+1
                                  accumulates pads into a discard row)
      lengths[s]  [B]    int32  — per-row id counts
    Dense slot ``d``: dense[d]  [B, dim] float32.
    labels: [B, num_labels] float32.  valid: [B] bool (False = pad row).
    """

    labels: np.ndarray
    valid: np.ndarray
    ids: Dict[str, np.ndarray]
    segments: Dict[str, np.ndarray]
    lengths: Dict[str, np.ndarray]
    dense: Dict[str, np.ndarray]

    @property
    def batch_size(self) -> int:
        return int(self.labels.shape[0])

    @property
    def num_valid(self) -> int:
        return int(self.valid.sum())

    def all_sparse_ids(self) -> np.ndarray:
        """All (possibly duplicate) feasigns in this batch — pass-key feed.

        Role of ``MergeInsKeys``/``PSAgent::AddKey`` (data_set.cc:2289).
        """
        parts = [v[:int(l.sum())] for v, l in
                 ((self.ids[s], self.lengths[s]) for s in self.ids)]
        if not parts:
            return np.empty((0,), np.uint64)
        return np.concatenate(parts)

    @staticmethod
    def pack(instances: Sequence[Instance], config: DataFeedConfig,
             batch_size: Optional[int] = None,
             capacities: Optional[Dict[str, int]] = None) -> "SlotBatch":
        """Pack instances into one static-shape batch, padding short batches
        with invalid rows (role of BuildSlotBatchGPU, vectorized on host).

        ``capacities`` overrides the per-slot value capacity (used by
        pack_sharded so every sub-batch shares one static shape)."""
        bs = batch_size or config.batch_size
        n = len(instances)
        if n > bs:
            raise ValueError(f"{n} instances > batch_size {bs}")
        labels = np.zeros((bs, config.num_labels), np.float32)
        valid = np.zeros((bs,), bool)
        for i, ins in enumerate(instances):
            labels[i] = ins.labels
            valid[i] = True

        ids: Dict[str, np.ndarray] = {}
        segments: Dict[str, np.ndarray] = {}
        lengths: Dict[str, np.ndarray] = {}
        for slot in config.sparse_slots:
            cap = (capacities[slot.name] if capacities is not None
                   else config.sparse_capacity(slot, bs))
            vals = np.zeros((cap,), np.uint64)
            segs = np.full((cap,), bs, np.int32)
            lens = np.zeros((bs,), np.int32)
            off = 0
            for i, ins in enumerate(instances):
                v = ins.sparse.get(slot.name)
                if v is None or v.size == 0:
                    continue
                if slot.max_len and v.size > slot.max_len:
                    v = v[:slot.max_len]
                take = min(v.size, cap - off)
                if take < v.size:
                    monitor.add(f"slot_overflow/{slot.name}", v.size - take)
                if take <= 0:
                    continue
                vals[off:off + take] = v[:take]
                segs[off:off + take] = i
                lens[i] = take
                off += take
            ids[slot.name] = vals
            segments[slot.name] = segs
            lengths[slot.name] = lens

        dense: Dict[str, np.ndarray] = {}
        for slot in config.dense_slots:
            d = np.zeros((bs, slot.dim), np.float32)
            for i, ins in enumerate(instances):
                v = ins.dense.get(slot.name)
                if v is not None:
                    d[i, :v.size] = v[:slot.dim]
            dense[slot.name] = d

        return SlotBatch(labels=labels, valid=valid, ids=ids,
                         segments=segments, lengths=lengths, dense=dense)

    @staticmethod
    def pack_sharded(instances: Sequence[Instance], config: DataFeedConfig,
                     num_shards: int,
                     batch_size: Optional[int] = None) -> "SlotBatch":
        """Pack into ``num_shards`` self-contained per-device sub-batches,
        concatenated. Each device's slice of every array is a complete
        local batch: segments index LOCAL rows [0, B/num_shards], so the
        arrays can be sharded over a dp mesh axis directly (the reference
        feeds each device worker its own MiniBatchGpuPack for the same
        reason, data_feed.h:519).
        """
        bs = batch_size or config.batch_size
        if bs % num_shards:
            raise ValueError(f"batch_size {bs} not divisible by {num_shards}")
        bs_local = bs // num_shards
        # Per-device capacity = sharded full-batch capacity / num_shards,
        # so the concatenated arrays match what a trainer derives from
        # sparse_capacity(slot, bs, num_shards).
        caps_local = {
            slot.name: config.sparse_capacity(slot, bs, num_shards)
            // num_shards
            for slot in config.sparse_slots}
        subs = []
        for s in range(num_shards):
            chunk = list(instances[s * bs_local:(s + 1) * bs_local])
            subs.append(SlotBatch.pack(chunk, config, bs_local, caps_local))
        return SlotBatch(
            labels=np.concatenate([b.labels for b in subs]),
            valid=np.concatenate([b.valid for b in subs]),
            ids={k: np.concatenate([b.ids[k] for b in subs])
                 for k in subs[0].ids},
            segments={k: np.concatenate([b.segments[k] for b in subs])
                      for k in subs[0].segments},
            lengths={k: np.concatenate([b.lengths[k] for b in subs])
                     for k in subs[0].lengths},
            dense={k: np.concatenate([b.dense[k] for b in subs])
                   for k in subs[0].dense},
        )
