"""Data pipeline: channels, slot records, parsers, datasets.

Role of the reference's L5 data layer (SURVEY.md §2.4):
``framework/channel.h`` (bounded MPMC channel), ``data_feed.{h,cc,cu}``
(SlotRecord readers + GPU batch packing), ``data_set.{h,cc}``
(Dataset load/shuffle/pass lifecycle), ``data_feed.proto`` (slot config).

TPU-first differences: ragged slot data is packed host-side into
STATIC-shape CSR batches (values + row lengths padded to per-slot
capacity) so every train step compiles once — replacing LoD tensors and
the CUDA ``BuildSlotBatchGPU`` path with one vectorized pack.
"""

from paddlebox_tpu.data.channel import Channel, ClosedChannelError
from paddlebox_tpu.data.slots import (
    DataFeedConfig,
    SlotBatch,
    SlotConf,
)
from paddlebox_tpu.data.parser import parse_lines, register_parser, get_parser
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.proto_desc import (data_feed_config_from_desc,
                                           graph_gen_config_from_desc,
                                           parse_proto_text,
                                           table_config_from_desc)

__all__ = [
    "Channel",
    "ClosedChannelError",
    "DataFeedConfig",
    "Dataset",
    "SlotBatch",
    "SlotConf",
    "data_feed_config_from_desc",
    "get_parser",
    "graph_gen_config_from_desc",
    "parse_lines",
    "parse_proto_text",
    "register_parser",
    "table_config_from_desc",
]
