"""DataFeedDesc proto-text compatibility: load reference configs as-is.

The reference configures its readers with protobuf TEXT files
(``data_feed.proto:43-57`` DataFeedDesc — slots, batch size, pipe
command, graph walk config), and a migrating user has a directory of
them. This module parses that text format directly into
:class:`~paddlebox_tpu.data.slots.DataFeedConfig` /
:class:`~paddlebox_tpu.graph.data_generator.GraphGenConfig` — no
protobuf runtime, no generated bindings: the grammar is only
``key: value`` scalars and ``key { ... }`` blocks with repetition, so a
small recursive reader covers every DataFeedDesc in the reference's
tests. Unknown fields are preserved in the returned extras dict rather
than dropped, so nothing silently disappears in migration.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

from paddlebox_tpu.core import log
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf

_GRAPH_FIELDS = {"walk_degree", "walk_len", "window",
                 "once_sample_startid_len", "sample_times_one_chunk",
                 "batch_size", "debug_mode", "first_node_type",
                 "meta_path", "gpu_graph_training"}

_TOKEN = re.compile(
    r"""\s*(?:(?P<comment>\#[^\n]*)
          |(?P<brace>[{}])
          |(?P<ident>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?
          |(?P<string>"(?:[^"\\]|\\.)*")
          |(?P<scalar>[^\s{}"]+))""",
    re.VERBOSE)


def _tokens(text: str):
    """Yields (kind, value): kind 'key' only for ``ident:`` (or a bare
    ident that a '{' follows — block names may omit the colon); an
    identifier WITHOUT a colon in value position is a scalar (true/false
    /enum values lex as identifiers too)."""
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None or m.end() == pos:
            if text[pos:].strip():
                raise ValueError(
                    f"unparseable proto text at: {text[pos:pos + 40]!r}")
            return
        pos = m.end()
        kind = m.lastgroup if m.lastgroup != "colon" else "ident"
        if kind == "comment":
            continue
        if kind == "ident":
            if m.group("colon"):
                yield "key", m.group("ident")
            else:
                nxt = _TOKEN.match(text, pos)
                if nxt and nxt.lastgroup == "brace" \
                        and nxt.group("brace") == "{":
                    yield "key", m.group("ident")
                else:
                    yield "scalar", m.group("ident")
        else:
            yield kind, m.group(kind)


def _coerce(raw: str) -> Any:
    if raw.startswith('"'):
        s = raw[1:-1]
        if "\\" not in s:
            return s          # no escapes: keep UTF-8 intact
        # Escape decoding without mangling non-ASCII: unicode_escape is
        # latin-1-based, so round-trip the result back through UTF-8.
        return (s.encode("latin-1", "backslashreplace")
                .decode("unicode_escape")
                .encode("latin-1", "replace").decode("utf-8", "replace"))
    low = raw.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


def parse_proto_text(text: str) -> Dict[str, Any]:
    """proto-text → dict; repeated fields become lists (a field seen once
    stays scalar — callers use :func:`_as_list` where repetition is
    legal, so both spellings work)."""
    root: Dict[str, Any] = {}
    stack: List[Dict[str, Any]] = [root]
    pending_key = None
    for kind, value in _tokens(text):
        if kind == "brace":
            if value == "{":
                if pending_key is None:
                    raise ValueError("'{' without a field name")
                child: Dict[str, Any] = {}
                _store(stack[-1], pending_key, child)
                stack.append(child)
                pending_key = None
            else:
                if len(stack) == 1:
                    raise ValueError("unbalanced '}'")
                stack.pop()
        elif kind == "key":
            if pending_key is not None:
                # Two bare keys in a row: the first had no value.
                raise ValueError(f"field {pending_key!r} has no value")
            pending_key = value
        else:
            if pending_key is None:
                raise ValueError(f"value {value!r} without a field name")
            _store(stack[-1], pending_key, _coerce(value))
            pending_key = None
    if len(stack) != 1:
        raise ValueError("unbalanced '{' — missing closing brace")
    if pending_key is not None:
        raise ValueError(f"field {pending_key!r} has no value")
    return root


def _store(d: Dict[str, Any], key: str, value: Any) -> None:
    if key in d:
        if not isinstance(d[key], list):
            d[key] = [d[key]]
        d[key].append(value)
    else:
        d[key] = value


def _as_list(v: Any) -> List[Any]:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


_FEED_FIELDS = {"name", "batch_size", "multi_slot_desc", "pipe_command",
                "thread_num", "rank_offset", "pv_batch_size", "input_type",
                "so_parser_name", "graph_config", "sample_rate",
                "index_parser"}


def data_feed_config_from_desc(text: str, *, num_labels: int = 1
                               ) -> Tuple[DataFeedConfig, Dict[str, Any]]:
    """(DataFeedConfig, extras) from a DataFeedDesc text config.

    Slots map 1:1 (name / is_dense / is_used; a dense slot's dim is the
    product of its ``shape``). Fields DataFeedConfig has no seat for —
    thread_num, pv_batch_size, graph_config, sample_rate, ... — come
    back verbatim in ``extras`` so the caller can route them (thread
    counts go to Dataset, graph_config to
    :func:`graph_gen_config_from_desc`)."""
    d = parse_proto_text(text)
    if not set(d) & _FEED_FIELDS:
        raise ValueError(
            f"no DataFeedDesc fields found in {sorted(d)} — not a "
            f"data_feed.proto text config?")
    unknown = set(d) - _FEED_FIELDS
    if unknown:
        # Newer-reference fields ride along in extras (the as-is load
        # promise) — surfaced, not silently dropped, not fatal.
        log.vlog(0, "DataFeedDesc: passing unknown fields %s through to "
                 "extras", sorted(unknown))
    slots = []
    msd = d.get("multi_slot_desc") or {}
    for s in _as_list(msd.get("slots")):
        is_dense = bool(s.get("is_dense", False))
        shape = _as_list(s.get("shape"))
        dim = 1
        for x in shape:
            dim *= int(x)
        slots.append(SlotConf(
            name=str(s["name"]), is_dense=is_dense,
            dim=dim if is_dense else 1,
            is_used=bool(s.get("is_used", False))))
    cfg = DataFeedConfig(
        slots=tuple(slots),
        batch_size=int(d.get("batch_size", 32)),
        num_labels=num_labels,
        pipe_command=str(d.get("pipe_command", "")))
    extras = {k: v for k, v in d.items()
              if k not in ("batch_size", "multi_slot_desc", "pipe_command")}
    return cfg, extras


def table_config_from_desc(text: str):
    """(TableConfig, extras) from a TableParameter proto-text config
    (``the_one_ps.proto:109`` — the reference's sparse-table/accessor
    declaration). Maps the fields with seats here:

    - ``accessor.embedx_dim`` → ``dim`` (the mf embedding width);
    - the embedx SGD rule (falling back to the embed rule) → optimizer
      selection + hyperparameters: SparseAdaGradSGDRule → "adagrad"
      (learning_rate, initial_g2sum), SparseAdamSGDRule → "adam"
      (learning_rate, beta1/2), SparseNaiveSGDRule → "adagrad" with its
      learning_rate; ``weight_bounds`` → min/max_bound;
    - ``ctr_accessor_param.show_click_decay_rate`` → show_click_decay.

    ``shard_num`` deliberately does NOT map: table placement here is the
    mesh axis size, not a config constant. Everything else (thresholds,
    cache knobs, save params) returns in ``extras``."""
    from paddlebox_tpu.embedding.table import TableConfig

    d = parse_proto_text(text)
    acc = d.get("accessor")
    if not isinstance(acc, dict):
        raise ValueError("no accessor block — not a TableParameter "
                         "proto-text config?")
    kw: Dict[str, Any] = {"name": str(d.get("table_class", "embedding"))}
    if "embedx_dim" in acc:
        kw["dim"] = int(acc["embedx_dim"])
    rule_key = ("embedx_sgd_param" if "embedx_sgd_param" in acc
                else "embed_sgd_param")
    rule = acc.get(rule_key) or {}
    name = str(rule.get("name", "")).lower()
    if "adam" in name:
        a = rule.get("adam") or {}
        # SparseSharedAdamSGDRule -> the shared-moment rule, NOT plain
        # adam (different update semantics and state layout).
        kw["optimizer"] = "adam_shared" if "shared" in name else "adam"
        kw["learning_rate"] = float(a.get("learning_rate", 0.001))
        kw["beta1"] = float(a.get("beta1_decay_rate", 0.9))
        kw["beta2"] = float(a.get("beta2_decay_rate", 0.999))
        bounds = _as_list(a.get("weight_bounds"))
    elif "naive" in name:
        a = rule.get("naive") or {}
        kw["optimizer"] = "adagrad"
        kw["learning_rate"] = float(a.get("learning_rate", 0.05))
        bounds = _as_list(a.get("weight_bounds"))
    else:  # adagrad family is the reference default
        a = rule.get("adagrad") or {}
        kw["optimizer"] = "adagrad"
        kw["learning_rate"] = float(a.get("learning_rate", 0.05))
        kw["initial_g2sum"] = float(a.get("initial_g2sum", 3.0))
        bounds = _as_list(a.get("weight_bounds"))
    if len(bounds) == 2:
        kw["min_bound"] = float(bounds[0])
        kw["max_bound"] = float(bounds[1])
    ctr = acc.get("ctr_accessor_param") or {}
    if "show_click_decay_rate" in ctr:
        kw["show_click_decay"] = float(ctr["show_click_decay_rate"])
    # Unmapped accessor subfields ride along under extras["accessor"]
    # (the module's no-silent-drop promise): consumed keys removed, the
    # rest — thresholds, coefficients, save params — preserved.
    acc_rest = {k: v for k, v in acc.items()
                if k not in ("embedx_dim", rule_key)}
    ctr_rest = {k: v for k, v in ctr.items()
                if k != "show_click_decay_rate"}
    if ctr_rest:
        acc_rest["ctr_accessor_param"] = ctr_rest
    else:
        acc_rest.pop("ctr_accessor_param", None)
    extras = {k: v for k, v in d.items()
              if k not in ("table_class", "accessor")}
    if acc_rest:
        extras["accessor"] = acc_rest
    return TableConfig(**kw), extras


def graph_gen_config_from_desc(text: str):
    """GraphGenConfig from the DataFeedDesc's graph_config block (role of
    the reference's graph walk knobs, data_feed.proto GraphConfig:
    walk_len / window / batch_size / meta_path)."""
    from paddlebox_tpu.graph.data_generator import GraphGenConfig

    d = parse_proto_text(text)
    g = d.get("graph_config")
    if g is None:
        # Accept a BARE graph-config block, but a graph-less
        # DataFeedDesc must fail loudly — defaulted walk knobs would
        # silently train wrong.
        if set(d) & _GRAPH_FIELDS:
            g = d
        else:
            raise ValueError(
                "no graph_config block (and no graph fields) in this "
                "desc — nothing to build a GraphGenConfig from")
    meta = g.get("meta_path")
    if isinstance(meta, list):
        meta = meta[-1]   # proto2 optional semantics: last value wins
    kw: Dict[str, Any] = dict(
        walk_len=int(g.get("walk_len", 20)),
        window=int(g.get("window", 5)),
        batch_walks=int(g.get("batch_size", 1)))
    if meta:
        # reference meta_path spelling: semicolon-separated alternative
        # paths, each a hyphenated edge-type chain
        # ("u2i-i2u;u2c-c2u", data_feed.h:1080). GraphGenConfig walks
        # one metapath per generator — this maps the FIRST; build one
        # generator per path for the multi-path training mix.
        first = str(meta).split(";")[0]
        kw["metapath"] = tuple(first.split("-"))
    return GraphGenConfig(**kw)
