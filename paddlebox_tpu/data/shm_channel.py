"""Shared-memory chunk handoff between ingest worker processes and the
parent (role of the reference's zero-copy channel between the reader
thread pool and the dataset merge, ``data_set.cc:2283`` — here across a
PROCESS boundary so the GIL-bound parse runs on real cores).

Frame layout inside one ``multiprocessing.shared_memory`` segment::

    [0:4)    magic  b'PBXC'
    [4:8)    u32 version (1)
    [8:16)   u64 header length H
    [16:16+H) json header: [{"key", "dtype", "shape", "offset"}, ...]
    ...      arrays at 64-byte-aligned offsets

``write_chunk`` serializes a :class:`ColumnarChunk` into a fresh segment
(one memcpy on the worker side); ``read_chunk`` reconstructs the chunk
as zero-copy numpy VIEWS over the mapped buffer — the parent never
copies the arrays again.

Unlink protocol: exactly one process owns each segment's name at a
time. The worker creates the segment, immediately *untracks* it from
its resource tracker (else the tracker unlinks it when the worker
exits — possibly before the parent attached) and sends the name over
the message queue. The parent attaches, untracks its own side, and
pins segment lifetime to the chunk object: a ``weakref.finalize`` on
the chunk unlinks the name as soon as the chunk is garbage-collected
(``Dataset.clear()``, merge, error paths), so ``/dev/shm`` can never
accumulate segments while the process lives. ``sweep_orphans`` is the
belt-and-braces pass for worker-crash windows where a segment was
created but its name never reached the parent.
"""

from __future__ import annotations

import json
import os
import weakref
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import log, monitor

_MAGIC = b"PBXC"
_VERSION = 1
_ALIGN = 64

#: Segment-name prefix: ``pbx-ing-<parent pid>-<load>-...`` — scoping
#: names to the parent process AND the load lets sweep_orphans clean up
#: a dead worker's leftovers without touching segments a previous load's
#: still-referenced chunks own.
NAME_PREFIX = "pbx-ing"

_load_counter = [0]
_load_lock = None  # created lazily; module import must stay cheap


def next_load_id() -> int:
    """Monotone per-process load sequence number — segment names embed
    it so two loads in one parent can never collide."""
    global _load_lock
    if _load_lock is None:
        import threading
        _load_lock = threading.Lock()
    with _load_lock:
        _load_counter[0] += 1
        return _load_counter[0]


def seg_name(parent_pid: int, load_id: int, worker_id: int,
             serial: int) -> str:
    return f"{NAME_PREFIX}-{parent_pid}-{load_id}-{worker_id}-{serial}"


def untrack(shm: shared_memory.SharedMemory) -> None:
    """Public alias of :func:`_untrack` for other explicit-unlink
    protocols (the RPC plane's one-shot FLAG_SHM frames hand unlink
    ownership to the receiving process the same way)."""
    _untrack(shm)


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Remove a CREATED segment from this process's resource_tracker:
    lifetime is managed by the explicit unlink protocol above, and the
    tracker would otherwise unlink a live segment when the creating
    worker exits (before the parent consumed the tail frames). Attach
    paths never call this — CPython only registers on create."""
    try:  # CPython < 3.13 has no track=False; reach into the tracker
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # pragma: no cover - tracker internals shifted
        pass


def _align(off: int) -> int:
    return (off + _ALIGN - 1) // _ALIGN * _ALIGN


def _chunk_arrays(chunk) -> List[Tuple[str, np.ndarray]]:
    out = [("labels", chunk.labels)]
    for s, v in chunk.sparse_ids.items():
        out.append((f"sid:{s}", v))
        out.append((f"soff:{s}", chunk.sparse_offsets[s]))
    for s, v in chunk.dense.items():
        out.append((f"dense:{s}", v))
    return out


def write_chunk(chunk, name: str) -> int:
    """Serialize a ColumnarChunk into a fresh named segment. Returns the
    segment's byte size. The caller (worker) sends ``name`` to the
    parent; the segment is already untracked here."""
    arrays = [(k, np.ascontiguousarray(v)) for k, v in _chunk_arrays(chunk)]
    # Header size depends on the offsets' digit counts — size it with a
    # worst-case 16-digit placeholder, then pad the real (never longer)
    # json with trailing spaces to the sized length.
    header = [{"key": k, "dtype": a.dtype.str, "shape": list(a.shape),
               "offset": 9_999_999_999_999_999} for k, a in arrays]
    hcap = len(json.dumps(header).encode())
    base = _align(16 + hcap)
    off = base
    for h, (_, a) in zip(header, arrays):
        h["offset"] = off
        off = _align(off + a.nbytes)
    hb = json.dumps(header).encode().ljust(hcap)
    # (off >= chunk.nbytes + header: per-array alignment padding only)
    shm = shared_memory.SharedMemory(create=True, name=name, size=max(off, 1))
    try:
        _untrack(shm)
        buf = shm.buf
        buf[0:4] = _MAGIC
        buf[4:8] = np.uint32(_VERSION).tobytes()
        buf[8:16] = np.uint64(len(hb)).tobytes()
        buf[16:16 + len(hb)] = hb
        for h, (_, a) in zip(header, arrays):
            if a.nbytes:
                dst = np.ndarray(a.shape, a.dtype, buffer=buf,
                                 offset=h["offset"])
                dst[...] = a
        monitor.add("ingest/shm_bytes", int(off))
        return int(off)
    finally:
        shm.close()


def read_chunk(name: str):
    """Attach a segment and rebuild the chunk as zero-copy views. The
    returned chunk OWNS the segment: a finalizer unlinks the name when
    the chunk is collected. Returns (chunk, release_fn) — release_fn
    force-unlinks early (error paths discarding a staged frame)."""
    from paddlebox_tpu.data.columnar import ColumnarChunk
    shm = shared_memory.SharedMemory(name=name)  # attach: not tracked
    buf = shm.buf
    if bytes(buf[0:4]) != _MAGIC:
        shm.close()
        shm.unlink()
        raise ValueError(f"shm segment {name!r}: bad magic")
    hlen = int(np.frombuffer(buf, np.uint64, count=1, offset=8)[0])
    header = json.loads(bytes(buf[16:16 + hlen]).decode())
    labels = None
    ids, offs, dense = {}, {}, {}
    for h in header:
        a = np.ndarray(tuple(h["shape"]), np.dtype(h["dtype"]),
                       buffer=buf, offset=h["offset"])
        k = h["key"]
        if k == "labels":
            labels = a
        elif k.startswith("sid:"):
            ids[k[4:]] = a
        elif k.startswith("soff:"):
            offs[k[5:]] = a
        elif k.startswith("dense:"):
            dense[k[6:]] = a
    chunk = ColumnarChunk(labels=labels, sparse_ids=ids,
                          sparse_offsets=offs, dense=dense)
    release = _make_release(shm)
    weakref.finalize(chunk, release)
    return chunk, release


def _make_release(shm: shared_memory.SharedMemory):
    done = [False]

    def release() -> None:
        if done[0]:
            return
        done[0] = True
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception as e:  # pragma: no cover - platform quirks
            log.warning("shm unlink failed: %r", e)
        try:
            # Views may still be alive (a caller kept an array ref after
            # dropping the chunk): the name is gone either way, and the
            # mapping is freed when the last view dies.
            shm.close()
        except BufferError:
            pass

    return release


def unlink_by_name(name: str) -> bool:
    """Best-effort unlink of a segment the parent never attached (a
    staged frame discarded on worker death)."""
    try:
        shm = shared_memory.SharedMemory(name=name)  # attach: not tracked
    except FileNotFoundError:
        return False
    shm.close()
    try:
        shm.unlink()
    except FileNotFoundError:
        return False
    return True


def sweep_orphans(parent_pid: Optional[int] = None,
                  load_id: Optional[int] = None,
                  worker_id: Optional[int] = None,
                  exclude=()) -> int:
    """Unlink leftover ``pbx-ing-<pid>-<load>[-<wid>]-*`` segments —
    covers the window where a killed worker created a segment whose name
    never reached the parent. ``exclude`` names segments that DID reach
    the parent and are owned by live chunks (their finalizers unlink
    them). Linux-only (/dev/shm listing); a no-op elsewhere. Returns the
    number of segments removed."""
    d = "/dev/shm"
    if not os.path.isdir(d):
        return 0
    pid = parent_pid if parent_pid is not None else os.getpid()
    prefix = f"{NAME_PREFIX}-{pid}-"
    if load_id is not None:
        prefix += f"{load_id}-"
        if worker_id is not None:
            prefix += f"{worker_id}-"
    skip = set(exclude)
    n = 0
    try:
        entries = os.listdir(d)
    except OSError:
        return 0
    for e in entries:
        if e.startswith(prefix) and e not in skip and unlink_by_name(e):
            n += 1
    if n:
        monitor.add("ingest/shm_orphans_swept", n)
    return n
