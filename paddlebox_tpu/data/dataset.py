"""Dataset: file list → threaded load → shuffle → static-shape batches.

Role of the reference's dataset hierarchy (``data_set.{h,cc}``, SURVEY.md
§2.4): ``PadBoxSlotDataset::LoadIntoMemory`` (reader thread pool feeding a
channel + pass-key merge, ``data_set.cc:2283-2289``), preload/wait
(``box_wrapper.h:1140,1161``), local & cross-node shuffle
(``ShuffleData``/``ReceiveSuffleData``, ``data_set.cc:2436,2544``), and the
python ``BoxPSDataset`` API (``python/paddle/fluid/dataset.py:1225``).

TPU-first shape: records live as columnar CSR chunks
(:class:`ColumnarChunk`) parsed by the native C++ parser when available
(``native/parser.cc``) — every downstream operation (shuffle, partition,
batch pack) is a vectorized numpy gather, no per-record python objects.
Batches are packed host-side to STATIC shapes (:class:`SlotBatch`) so the
jitted train step never recompiles; per-pass unique keys are collected
during load (role of ``MergeInsKeys`` → ``PSAgent::AddKey``).
"""

from __future__ import annotations

import builtins
import os
import queue
import subprocess
import threading
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import faults, flags, log, monitor
from paddlebox_tpu.data.channel import Channel, ClosedChannelError
from paddlebox_tpu.data.columnar import ColumnarChunk, instances_to_chunk
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch

_READ_BLOCK = 4 << 20  # bytes per parse chunk

# File-list entries may name a BYTE RANGE of a file still being appended
# (the streaming tier's tail-consume cursor, stream/source.py):
# "<path>@@<start>-<end>" reads [start, end) — always cut at a newline
# boundary by the producer, so the slice parses like a whole file.
BYTE_RANGE_SEP = "@@"


def split_byte_range(spec: str):
    """``'p@@100-200'`` -> ``('p', 100, 200)``; plain path ->
    ``(path, None, None)``. A malformed suffix is treated as a literal
    path (``@@`` is no legal byte in this repo's day layouts)."""
    if BYTE_RANGE_SEP not in spec:
        return spec, None, None
    path, _, rng = spec.rpartition(BYTE_RANGE_SEP)
    a, dash, b = rng.partition("-")
    try:
        start, end = int(a), int(b)
    except ValueError:
        return spec, None, None
    if not dash or start < 0 or end < start:
        return spec, None, None
    return path, start, end


class _ByteSlice:
    """Read-only [start, end) window of an open binary file."""

    def __init__(self, f, start: int, end: int):
        f.seek(start)
        self._f = f
        self._left = end - start

    def read(self, n: int = -1) -> bytes:
        if self._left <= 0:
            return b""
        n = self._left if n is None or n < 0 else min(n, self._left)
        b = self._f.read(n)
        self._left -= len(b)
        return b

    def close(self) -> None:
        self._f.close()


def _open_stream(path: str, pipe_command: str):
    """Open a byte stream, optionally through a shell filter (role of
    pipe_command in data_feed.proto:47 / shell_popen io/fs.cc:69).
    Byte-range specs open the base file windowed to [start, end)."""
    base, start, end = split_byte_range(path)
    if start is not None:
        if pipe_command:
            # A shell filter consumes the raw stream start-to-finish —
            # a mid-file window through it would re-decompress the
            # whole prefix per range (and gzip members don't align to
            # carve cuts). Loud, not silent-wrong.
            raise ValueError(
                f"byte-range spec {path!r} cannot combine with "
                f"pipe_command {pipe_command!r} — tail-consume plain "
                "text logs only (ONLINE.md)")
        return None, _ByteSlice(open(base, "rb"), start, end)
    if pipe_command:
        f = open(path, "rb")
        proc = subprocess.Popen(pipe_command, shell=True, stdin=f,
                                stdout=subprocess.PIPE, bufsize=1 << 20)
        return proc, proc.stdout
    return None, open(path, "rb")


def _read_blocks(path: str, pipe_command: str) -> Iterator[bytes]:
    """Yield newline-aligned byte blocks of ~_READ_BLOCK size."""
    proc, stream = _open_stream(path, pipe_command)
    try:
        carry = b""
        while True:
            block = stream.read(_READ_BLOCK)
            if not block:
                break
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            carry = block[cut + 1:]
            yield block[:cut + 1]
        if carry:
            yield carry
    finally:
        stream.close()
        if proc is not None:
            ret = proc.wait()
            if ret != 0:
                # A failing filter (typo'd decompressor, truncated file)
                # must not silently produce an empty pass.
                raise RuntimeError(
                    f"pipe_command {pipe_command!r} exited {ret} on {path}")


def _parse_block(block: bytes, config: DataFeedConfig,
                 parser_fn=None) -> ColumnarChunk:
    """Native C++ parse when available, python fallback otherwise.
    ``parser_fn`` overrides the registry lookup (instance-scoped custom
    parser — the DLManager plugin role without global registration)."""
    if parser_fn is None and config.parser == "svm":
        from paddlebox_tpu.native.parser_py import parse_chunk_native
        chunk = parse_chunk_native(block, config)
        if chunk is not None:
            return chunk
        # No native library: the vectorized numpy bulk parse (C-level
        # S→numeric casts over the whole block) before the per-line
        # loop; it returns None on any input it cannot prove it handles
        # bit-identically, so semantics never change.
        from paddlebox_tpu.data.parser import parse_block_numpy
        chunk = parse_block_numpy(block, config)
        if chunk is not None:
            return chunk
    # Split on '\n' only — matching the block framing and the native
    # parser; str.splitlines would also break on NEL/FF/LS etc. and make
    # the two parser paths disagree on exotic bytes.
    lines = block.decode("utf-8", "replace").split("\n")
    instances = (parser_fn(lines, config) if parser_fn is not None
                 else parse_lines(lines, config))
    return instances_to_chunk(instances, config)


class Dataset:
    """In-memory columnar slot dataset with pass lifecycle.

    Typical CTR pass loop (mirrors BoxPSDataset usage, dataset.py:1225):

        ds = Dataset(config, num_reader_threads=8)
        ds.set_filelist(shards)
        ds.load_into_memory()          # or preload_into_memory + wait
        ds.local_shuffle(seed)
        for batch in ds.batches_sharded(ndev):
            ...
        ds.clear()
    """

    def __init__(self, config: DataFeedConfig, *, num_reader_threads: int = 4,
                 channel_capacity: int = 64, parser_fn=None):
        self.config = config
        # Instance-scoped parser override (DLManager custom-parser role
        # without mutating the global registry): fn(lines, config) ->
        # List[Instance].
        self.parser_fn = parser_fn
        self.num_reader_threads = max(1, num_reader_threads)
        self._channel_capacity = channel_capacity
        self._filelist: List[str] = []
        self._chunks: List[ColumnarChunk] = []
        self._merged: Optional[ColumnarChunk] = None
        self._preload_threads: List[threading.Thread] = []
        self._reader_errors: List[BaseException] = []
        self._lock = threading.Lock()
        # Sorted-run pass-key collection (round 13): per-slot sorted
        # unique key runs, one per loaded chunk, deduped DURING ingest so
        # pass_keys() is a linear k-way merge instead of one giant
        # end-of-load sort. Valid only while every loaded chunk passed
        # through _drain and no key-set-changing op ran.
        self._key_runs: Dict[str, List[np.ndarray]] = {}
        self._key_zero: Dict[str, bool] = {}
        self._key_runs_valid = True
        # Live ingest worker processes (multi-process path) — exposed so
        # tests/drills can kill one mid-load.
        self._ingest_procs: List = []
        # Hook invoked with each loaded chunk's keys at load time — wired
        # to the embedding engine's pass-key collector (role of
        # PSAgent::AddKey threading in MergeInsKeys, data_set.cc:2289).
        self.key_sink: Optional[Callable[[np.ndarray], None]] = None
        # Per-load data-health collector (FLAGS_quality_collect, core/
        # quality.py): fed each chunk in _drain; the trainer reads the
        # finalized per-slot health at pass time (quality_health()).
        self._quality = None

    # -- file list ---------------------------------------------------------

    def set_filelist(self, files: Sequence[str]) -> None:
        missing = [f for f in files
                   if not os.path.exists(split_byte_range(f)[0])]
        if missing:
            raise FileNotFoundError(f"dataset files missing: {missing[:3]}")
        # The pipelined day loop calls this from its preload thread while
        # the training thread may inspect filelist — swap under the lock.
        with self._lock:
            self._filelist = list(files)

    @property
    def filelist(self) -> List[str]:
        with self._lock:
            return list(self._filelist)

    # -- load --------------------------------------------------------------

    def _reader_worker(self, file_q: "queue.Queue[str]", out: Channel) -> None:
        try:
            while True:
                try:
                    path = file_q.get_nowait()
                except queue.Empty:
                    return
                n = 0
                for block in _read_blocks(path, self.config.pipe_command):
                    chunk = _parse_block(block, self.config, self.parser_fn)
                    n += chunk.num_rows
                    out.put(chunk)
                monitor.add("dataset/ins_loaded", n)
                log.vlog(1, "loaded %d instances from %s", n, path)
        except BaseException as e:  # surfaced by load_into_memory/wait
            with self._lock:
                self._reader_errors.append(e)

    def _start_load(self) -> Channel:
        if int(flags.flag("ingest_workers")) > 0 and self.parser_fn is None:
            return self._start_load_mp(int(flags.flag("ingest_workers")))
        file_q: "queue.Queue[str]" = queue.Queue()
        for f in self._filelist:
            file_q.put(f)
        out: Channel = Channel(self._channel_capacity)
        threads = []
        nthreads = min(self.num_reader_threads, max(1, len(self._filelist)))
        for _ in range(nthreads):
            t = threading.Thread(target=self._reader_worker,
                                 args=(file_q, out), daemon=True)
            t.start()
            threads.append(t)

        def closer():
            for t in threads:
                t.join()
            out.close()

        threading.Thread(target=closer, daemon=True).start()
        return out

    def _start_load_mp(self, num_workers: int) -> Channel:
        """Multi-process columnar ingest (FLAGS_ingest_workers; role of
        the reference's multithreaded LoadIntoMemory, data_set.cc:2283,
        which parallelizes for real because it is C++ — here the python
        parse escapes the GIL by running in worker PROCESSES that hand
        chunks back through zero-copy shared-memory frames).

        Same Channel contract as the thread path, so load/preload/dump
        and ``_drain`` (key_sink included) are unchanged. A worker death
        mid-file is detected by the pump, its staged frames are
        discarded (commit happens only on ``file_done``, so no partial
        rows), the file is requeued up to ``FLAGS_ingest_file_retries``
        times on a fresh worker, and an exhausted retry budget surfaces
        through ``_reader_errors`` like any reader failure."""
        import multiprocessing as mp

        from paddlebox_tpu.data import shm_channel
        from paddlebox_tpu.data.ingest_worker import worker_main

        # spawn, not fork: the parent holds jax state and live threads
        # (preload/trainer); forking either is undefined behavior.
        ctx = mp.get_context("spawn")
        with self._lock:
            files = list(self._filelist)
        out: Channel = Channel(self._channel_capacity)
        parent_pid = os.getpid()
        load_id = shm_channel.next_load_id()
        task_q = ctx.Queue()
        for f in files:
            task_q.put(f)
        msg_q = ctx.Queue()
        n_workers = min(num_workers, max(1, len(files)))
        max_file_retries = int(flags.flag("ingest_file_retries"))
        # Runaway-respawn backstop (a replacement that itself keeps
        # dying must converge to an error, not a spawn loop).
        respawn_budget = [n_workers + len(files) * max(1, max_file_retries)]

        def pump():
            procs: Dict[int, object] = {}
            current: Dict[int, Optional[str]] = {}
            staged: Dict[int, list] = {}
            committed: Dict[int, set] = {}
            finished: set = set()
            settled: set = set()   # paths that reached done/error
            file_retries: Dict[str, int] = {}
            next_wid = [0]

            def new_worker():
                faults.faultpoint("ingest/worker_spawn")
                if respawn_budget[0] <= 0:
                    raise RuntimeError(
                        "ingest worker respawn budget exhausted")
                respawn_budget[0] -= 1
                wid = next_wid[0]
                next_wid[0] += 1
                p = ctx.Process(target=worker_main,
                                args=(wid, parent_pid, load_id, task_q,
                                      msg_q, self.config),
                                daemon=True)
                p.start()
                procs[wid] = p
                current[wid] = None
                staged[wid] = []
                committed[wid] = set()
                self._ingest_procs.append(p)
                monitor.add("ingest/workers_spawned", 1)

            def discard_staged(wid):
                for _name, _chunk, release in staged[wid]:
                    release()
                staged[wid] = []

            def record_error(exc: BaseException):
                with self._lock:
                    self._reader_errors.append(exc)

            def handle(msg):
                kind, wid = msg[0], msg[1]
                if kind == "file_start":
                    current[wid] = msg[2]
                elif kind in ("file_done", "file_error"):
                    settled.add(msg[2])
                if kind == "file_done":
                    current[wid] = None
                    frames, staged[wid] = staged[wid], []
                    n = 0
                    for name, chunk, _release in frames:
                        committed[wid].add(name)
                        n += chunk.num_rows
                        out.put(chunk)
                    monitor.add("dataset/ins_loaded", n)
                    monitor.add("ingest/chunks", len(frames))
                    monitor.add("ingest/rows", n)
                    log.vlog(1, "ingest: loaded %d instances from %s",
                             n, msg[2])
                elif kind == "chunk":
                    _k, _w, _path, name, _n, _nb = msg
                    faults.faultpoint("ingest/shm_attach")
                    chunk, release = shm_channel.read_chunk(name)
                    staged[wid].append((name, chunk, release))
                elif kind == "file_error":
                    _k, _w, path, ename, emsg = msg
                    current[wid] = None
                    discard_staged(wid)
                    t = getattr(builtins, ename, None)
                    if isinstance(t, type) and issubclass(t, BaseException):
                        record_error(t(emsg))
                    else:
                        record_error(RuntimeError(f"{ename}: {emsg}"))
                elif kind == "exit":
                    finished.add(wid)

            def check_dead():
                dead = [wid for wid, p in procs.items()
                        if wid not in finished and not p.is_alive()]
                if not dead:
                    return
                # Final drain first: messages the worker flushed before
                # dying (possibly its file_done/exit) must win over the
                # death verdict, or a COMPLETED file would be requeued
                # and its rows duplicated.
                while True:
                    try:
                        handle(msg_q.get_nowait())
                    except queue.Empty:
                        break
                for wid in dead:
                    if wid in finished:
                        continue  # the drain found its exit after all
                    faults.faultpoint("ingest/worker_exit")
                    p = procs[wid]
                    finished.add(wid)
                    discard_staged(wid)
                    shm_channel.sweep_orphans(parent_pid, load_id,
                                              worker_id=wid,
                                              exclude=committed[wid])
                    path = current.get(wid)
                    current[wid] = None
                    monitor.add("ingest/worker_deaths", 1)
                    if path is not None:
                        n = file_retries.get(path, 0)
                        if n < max_file_retries:
                            file_retries[path] = n + 1
                            monitor.add("ingest/worker_restarts", 1)
                            log.warning(
                                "ingest worker %d died (exitcode %s) "
                                "parsing %s — retry %d/%d on a fresh "
                                "worker", wid, p.exitcode, path, n + 1,
                                max_file_retries)
                            task_q.put(path)
                            new_worker()
                        else:
                            settled.add(path)
                            record_error(RuntimeError(
                                f"ingest worker died (exitcode "
                                f"{p.exitcode}) parsing {path!r}; "
                                f"{max_file_retries} retries exhausted"))
                    elif (not any(procs[w].is_alive() for w in procs)
                            and not task_q.empty()):
                        # Died idle with files still queued and no
                        # sibling left to drain them.
                        new_worker()

            try:
                for _ in range(n_workers):
                    new_worker()
                while len(finished) < len(procs):
                    try:
                        msg = msg_q.get(timeout=0.25)
                    except queue.Empty:
                        check_dead()
                        continue
                    handle(msg)
                missing = [f for f in files if f not in settled]
                with self._lock:
                    have_errors = bool(self._reader_errors)
                if missing and not have_errors:
                    # Closes the kill window between a worker's task_q
                    # pop and its file_start announcement: a file that
                    # never settled must fail the load, not silently
                    # shrink the pass.
                    record_error(RuntimeError(
                        f"ingest ended with {len(missing)} unparsed "
                        f"file(s): {missing[:3]}"))
            except ClosedChannelError:
                pass  # consumer bailed early (dump error path)
            except BaseException as e:
                record_error(e)
            finally:
                # SIGKILL, not SIGTERM: workers are stateless daemons
                # (any staged shm is discarded below) and a teardown
                # must never wait on a wedged parse.
                for p in procs.values():
                    if p.is_alive():
                        p.kill()
                for wid, p in procs.items():
                    p.join(timeout=5)
                    discard_staged(wid)
                    shm_channel.sweep_orphans(parent_pid, load_id,
                                              worker_id=wid,
                                              exclude=committed[wid])
                with self._lock:
                    self._ingest_procs = [
                        p for p in self._ingest_procs if p.is_alive()]
                out.close()

        threading.Thread(target=pump, daemon=True,
                         name="pbx-ingest-pump").start()
        return out

    def _raise_reader_errors(self) -> None:
        with self._lock:
            errs, self._reader_errors = self._reader_errors, []
        if errs:
            raise errs[0]

    def load_into_memory(self) -> None:
        """Blocking load of the whole filelist (role of LoadIntoMemory)."""
        ch = self._start_load()
        self._drain(ch)
        self._raise_reader_errors()

    def preload_into_memory(self) -> None:
        """Start background load (role of PreLoadIntoMemory — overlaps the
        previous pass's training with the next pass's read)."""
        ch = self._start_load()
        t = threading.Thread(target=self._drain, args=(ch,), daemon=True)
        t.start()
        self._preload_threads = [t]

    def wait_preload_done(self) -> None:
        """Role of WaitPreLoadDone/WaitFeedPassDone."""
        for t in self._preload_threads:
            t.join()
        self._preload_threads = []
        self._raise_reader_errors()

    def _collect_key_runs(self, chunk: ColumnarChunk) -> None:
        """Dedup the chunk's per-slot keys into sorted runs DURING the
        load (overlapping ingest) so pass_keys() becomes a linear k-way
        merge instead of one end-of-load np.unique over every id (the
        r02 feed-time sort). Bit-parity: merge(runs) == np.unique(concat)
        — dedup_keys drops the 0 sentinel, so a seen-zero flag restores
        it for the slots where the old path would have reported it."""
        from paddlebox_tpu.native.keymap_py import dedup_keys
        runs: List[Tuple[str, np.ndarray, bool]] = []
        for s, ids in chunk.sparse_ids.items():
            if ids.size:
                runs.append((s, dedup_keys(ids), bool((ids == 0).any())))
        with self._lock:
            if not self._key_runs_valid:
                return
            for s, run, zero in runs:
                if run.size:
                    self._key_runs.setdefault(s, []).append(run)
                if zero:
                    self._key_zero[s] = True

    def _invalidate_key_runs(self) -> None:
        with self._lock:
            self._key_runs_valid = False
            self._key_runs = {}
            self._key_zero = {}

    def _drain(self, ch: Channel) -> None:
        sink = self.key_sink
        collect = bool(flags.flag("ingest_key_runs"))
        qc = None
        if flags.flag("quality_collect"):
            from paddlebox_tpu.core import quality
            with self._lock:
                if self._quality is None:
                    self._quality = quality.SlotHealthCollector()
                qc = self._quality
        local: List[ColumnarChunk] = []
        try:
            while True:
                chunk = ch.get()
                local.append(chunk)
                if collect:
                    self._collect_key_runs(chunk)
                if qc is not None:
                    qc.observe_chunk(chunk)
                if sink is not None:
                    keys = chunk.all_keys()
                    if keys.size:
                        sink(keys)
        except ClosedChannelError:
            pass
        with self._lock:
            self._chunks.extend(local)
            self._merged = None
            if local and not collect:
                # Runs no longer cover every loaded chunk — pass_keys
                # falls back to the exact merged-sort path.
                self._key_runs_valid = False
                self._key_runs = {}
                self._key_zero = {}

    def _merge(self) -> ColumnarChunk:
        with self._lock:
            if self._merged is None:
                chunks = self._chunks or [ColumnarChunk.empty(self.config)]
                self._merged = ColumnarChunk.concat(chunks)
                self._chunks = [self._merged]
            return self._merged

    # -- shuffle -----------------------------------------------------------

    def _check_no_preload(self, op: str) -> None:
        # Shuffles snapshot-then-replace the chunk list; a concurrent
        # preload _drain appending chunks would be silently discarded.
        if any(t.is_alive() for t in self._preload_threads):
            raise RuntimeError(
                f"{op} while preload_into_memory is running — call "
                f"wait_preload_done() first")

    def local_shuffle(self, seed: Optional[int] = None) -> None:
        self._check_no_preload("local_shuffle")
        merged = self._merge()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(merged.num_rows)
        shuffled = merged.take(perm)
        with self._lock:
            self._chunks = [shuffled]
            self._merged = shuffled

    def global_shuffle(self, *, num_ranks: int = 1, rank: int = 0,
                       exchange: Optional[Callable[[List[ColumnarChunk]],
                                                   ColumnarChunk]] = None,
                       seed: Optional[int] = None,
                       allow_partition: bool = False) -> None:
        """Cross-node record shuffle (role of PadBoxSlotDataset::ShuffleData
        → boxps::PaddleShuffler → ReceiveSuffleData, data_set.cc:2436,2544).

        Records are hashed into ``num_ranks`` bucket chunks; ``exchange``
        ships them to their owner ranks and returns the chunk this rank
        receives. With ``num_ranks > 1`` a transport is REQUIRED unless
        ``allow_partition=True`` explicitly opts into keeping only this
        rank's bucket (simulating one rank — other buckets are dropped).
        """
        if num_ranks > 1 and exchange is None and not allow_partition:
            raise ValueError(
                "global_shuffle with num_ranks>1 needs an exchange transport "
                "(or allow_partition=True to keep only this rank's bucket, "
                "dropping the rest)")
        self._check_no_preload("global_shuffle")
        merged = self._merge()
        rng = np.random.default_rng(seed)
        assign = rng.integers(num_ranks, size=merged.num_rows)
        buckets = [merged.take(np.flatnonzero(assign == r))
                   for r in range(num_ranks)]
        if exchange is None:
            received = buckets[rank]
            dropped = merged.num_rows - received.num_rows
            if dropped:
                monitor.add("dataset/shuffle_partition_dropped", dropped)
        else:
            received = exchange(buckets)
        # The key SET changed (rows left/arrived) — ingest-time runs no
        # longer describe what is loaded.
        self._invalidate_key_runs()
        with self._lock:
            self._chunks = [received]
            self._merged = received
        self.local_shuffle(seed)

    # -- access ------------------------------------------------------------

    @property
    def num_instances(self) -> int:
        with self._lock:
            return sum(c.num_rows for c in self._chunks)

    def batches(self, *, drop_last: bool = False,
                batch_size: Optional[int] = None) -> Iterator[SlotBatch]:
        """Yield static-shape SlotBatches; the short final batch is padded
        with invalid rows unless drop_last."""
        bs = batch_size or self.config.batch_size
        merged = self._merge()
        n = merged.num_rows
        for lo in range(0, n, bs):
            hi = min(lo + bs, n)
            if hi - lo < bs and drop_last:
                return
            yield merged.pack_batch(lo, hi, self.config, bs)

    def batches_sharded(self, num_shards: int, *,
                        batch_size: Optional[int] = None
                        ) -> Iterator[SlotBatch]:
        """Yield batches in the per-device sharded layout (see
        SlotBatch.pack_sharded) — what a dp-sharded train step consumes."""
        bs = batch_size or self.config.batch_size
        merged = self._merge()
        n = merged.num_rows
        for lo in range(0, n, bs):
            hi = min(lo + bs, n)
            yield merged.pack_batch_sharded(lo, hi, self.config, num_shards,
                                            bs)

    def slots_shuffle(self, slots: Sequence[str],
                      seed: Optional[int] = None) -> None:
        """AUC-runner eval mode: decorrelate the given slots from labels by
        shuffling their values across records (role of
        BoxPSDataset.slots_shuffle, dataset.py:1288)."""
        self._check_no_preload("slots_shuffle")
        merged = self._merge()
        rng = np.random.default_rng(seed)
        for s in slots:
            merged = merged.shuffle_slot(s, rng)
        with self._lock:
            self._chunks = [merged]
            self._merged = merged

    def snapshot_chunks(self):
        """Cheap state snapshot (chunk refs; chunks are treated as
        immutable — transforms like shuffle_slot return new ones) so
        AUC-runner eval can shuffle a slot and restore afterwards."""
        with self._lock:
            return (list(self._chunks), self._merged)

    def restore_chunks(self, snap) -> None:
        chunks, merged = snap
        self._invalidate_key_runs()  # snapshot may predate current load
        with self._lock:
            self._chunks = list(chunks)
            self._merged = merged

    # -- disk spill (role of PreLoadIntoDisk/DumpIntoDisk + LoadDiskData,
    # data_set.cc:2088,2167) ----------------------------------------------

    def dump_into_disk(self, spill_dir: str) -> int:
        """Stream-parse the filelist straight to disk chunk archives
        without holding records in RAM (role of PreLoadIntoDisk: datasets
        larger than host memory spill between load and train). Returns
        the number of chunks written."""
        os.makedirs(spill_dir, exist_ok=True)
        # A re-dump producing fewer chunks must not leave stale chunks
        # from a previous run to be silently mixed in at load time.
        for old in self._disk_chunk_files(spill_dir):
            os.unlink(old)
        ch = self._start_load()
        n = 0
        try:
            while True:
                chunk = ch.get()
                chunk.save(os.path.join(spill_dir, f"chunk-{n:06d}.npz"))
                if self.key_sink is not None:
                    keys = chunk.all_keys()
                    if keys.size:
                        self.key_sink(keys)
                n += 1
        except ClosedChannelError:
            pass
        except BaseException:
            # e.g. disk-full in save(): readers are blocked on the bounded
            # channel — close it so their put() raises and threads exit
            # instead of leaking.
            ch.close()
            raise
        self._raise_reader_errors()
        log.vlog(0, "dump_into_disk: %d chunks -> %s", n, spill_dir)
        return n

    @staticmethod
    def _disk_chunk_files(spill_dir: str) -> List[str]:
        import glob
        return sorted(glob.glob(os.path.join(spill_dir, "chunk-*.npz")))

    def load_from_disk(self, spill_dir: str) -> None:
        """Load previously spilled chunks back into memory."""
        files = self._disk_chunk_files(spill_dir)
        if not files:
            # Same convention as set_filelist's missing-file error: a
            # misconfigured path must not silently yield an empty pass.
            raise FileNotFoundError(f"no chunk-*.npz under {spill_dir!r}")
        chunks = [ColumnarChunk.load(p) for p in files]
        self._invalidate_key_runs()  # spilled chunks carry no runs
        with self._lock:
            self._chunks = chunks
            self._merged = None

    def batches_from_disk(self, spill_dir: str, *,
                          batch_size: Optional[int] = None,
                          drop_last: bool = False) -> Iterator[SlotBatch]:
        """Stream batches chunk-by-chunk from a spill dir, holding at most
        one chunk (+remainder rows) in RAM — training directly from the
        disk tier."""
        bs = batch_size or self.config.batch_size
        rest: Optional[ColumnarChunk] = None
        for path in self._disk_chunk_files(spill_dir):
            cur = ColumnarChunk.load(path)
            if rest is not None and rest.num_rows:
                cur = ColumnarChunk.concat([rest, cur])
            n = cur.num_rows
            lo = 0
            while lo + bs <= n:
                yield cur.pack_batch(lo, lo + bs, self.config, bs)
                lo += bs
            rest = cur.take(np.arange(lo, n)) if lo < n else None
        if rest is not None and rest.num_rows and not drop_last:
            yield rest.pack_batch(0, rest.num_rows, self.config, bs)

    # -- pv/ins grouped batching (role of PaddleBoxDataFeed pv mode,
    # data_feed.h:1701: group instances by search id; a batch holds whole
    # pvs) ------------------------------------------------------------------

    def batches_grouped(self, group_slot: str, *,
                        batch_size: Optional[int] = None,
                        ) -> Iterator[Tuple[SlotBatch, np.ndarray]]:
        """Yield (SlotBatch, group_ids[bs]) where rows of the same group
        (e.g. search id / pv) are contiguous and never split across
        batches; group_ids carries the per-row group key (0 on padding
        rows). Groups larger than batch_size are truncated with a monitor
        tick (the reference drops such pvs)."""
        bs = batch_size or self.config.batch_size
        merged = self._merge()
        keys, has = merged.group_keys(group_slot)
        n = merged.num_rows
        if n == 0:
            return
        # Group rank = first-occurrence order (NOT sorted key order: that
        # would make every epoch's batch composition identical and nullify
        # local_shuffle between pvs). Keyless rows are singleton groups in
        # encounter order.
        gid = np.empty((n,), np.int64)
        num_keyed = 0
        if has.any():
            uniq, inv = np.unique(keys[has], return_inverse=True)
            num_keyed = uniq.size
            gid[has] = inv
        gid[~has] = num_keyed + np.arange(int((~has).sum()))
        first_seen = np.full(num_keyed + int((~has).sum()), n, np.int64)
        np.minimum.at(first_seen, gid, np.arange(n))
        rank_of_gid = np.argsort(np.argsort(first_seen))
        order = np.argsort(rank_of_gid[gid], kind="stable")
        merged = merged.take(order)
        # Boundaries come from the reordered group ids — NOT the key array
        # with keyless rows zeroed, which would merge adjacent keyless
        # singletons (and any real group whose key happens to be 0) into
        # one pseudo-group.
        gid_ord = gid[order]
        keys = np.where(has, keys, 0)[order]
        starts = np.concatenate(
            [[0], np.flatnonzero(gid_ord[1:] != gid_ord[:-1]) + 1, [n]])
        lo = 0
        g = 0  # index into starts of the first group of this batch
        while g < starts.size - 1:
            lo = starts[g]
            # extend until next group would overflow the batch
            h = g + 1
            while h < starts.size - 1 and starts[h + 1] - lo <= bs:
                h += 1
            hi = min(starts[h], lo + bs)
            if starts[h] - lo > bs and h == g + 1:
                monitor.add("dataset/pv_truncated", int(starts[h] - lo - bs))
            batch = merged.pack_batch(lo, hi, self.config, bs)
            gids = np.zeros((bs,), np.uint64)
            gids[:hi - lo] = keys[lo:hi]
            yield batch, gids
            g = h

    def pass_keys(self, slots: Optional[Sequence[str]] = None) -> np.ndarray:
        """Unique feasigns currently loaded (role of the per-pass key set
        registered via FeedPass, box_wrapper.h:1239): sorted unique, in
        the shape feed_pass's dedup bypass recognizes.

        ``slots`` restricts to the given sparse slots — used by dim-grouped
        embedding engines that feed each width group its own key set.

        Fast path (round 13): when the per-chunk sorted runs collected
        during ingest still cover everything loaded, this is a linear
        k-way merge of those runs — no end-of-load sort. Any operation
        that changed the key set (global shuffle, chunk restore, disk
        reload) falls back to the exact merged-sort path."""
        with self._lock:
            runs_ok = self._key_runs_valid
            if runs_ok:
                names = (list(self._key_runs) if slots is None
                         else [s for s in slots if s in self._key_runs])
                runs = [r for s in names for r in self._key_runs[s]]
                seen_zero = any(self._key_zero.get(s, False)
                                for s in (self._key_zero if slots is None
                                          else slots))
        if runs_ok:
            from paddlebox_tpu.native.store_py import SortedRunMerger
            merger = SortedRunMerger()
            for r in runs:
                merger.add_run(r)
            keys = merger.merge()
            if seen_zero:
                keys = np.concatenate(
                    [np.zeros((1,), np.uint64), keys])
            monitor.add("ingest/pass_keys_from_runs", 1)
            return keys
        merged = self._merge()
        if slots is None:
            keys = merged.all_keys()
        else:
            parts = [merged.sparse_ids[s] for s in slots
                     if merged.sparse_ids.get(s) is not None
                     and merged.sparse_ids[s].size]
            keys = (np.concatenate(parts) if parts
                    else np.empty((0,), np.uint64))
        if keys.size == 0:
            return keys
        return np.unique(keys)

    def quality_health(self):
        """Finalized per-slot data-health of everything this dataset
        loaded (core/quality.py SlotHealthCollector.finalize()); None
        when FLAGS_quality_collect was off during the load. The
        trainer attaches this to the pass's quality report — load-time
        collection keeps the per-chunk work off the pass critical path
        and attributes a pipelined preload's chunks to the dataset
        (and so the pass) that actually consumes them."""
        with self._lock:
            qc = self._quality
        return qc.finalize() if qc is not None else None

    def clear(self) -> None:
        with self._lock:
            self._chunks.clear()
            self._merged = None
            self._key_runs = {}
            self._key_zero = {}
            self._key_runs_valid = True
            self._quality = None
        # Chunk finalizers unlink their shm segments as the refs die;
        # nothing else to do here (gc-immediate under CPython).
