"""Dataset: file list → threaded load → shuffle → static-shape batches.

Role of the reference's dataset hierarchy (``data_set.{h,cc}``, SURVEY.md
§2.4): ``PadBoxSlotDataset::LoadIntoMemory`` (reader thread pool feeding a
channel + pass-key merge, ``data_set.cc:2283-2289``), preload/wait
(``box_wrapper.h:1140,1161``), local & cross-node shuffle
(``ShuffleData``/``ReceiveSuffleData``, ``data_set.cc:2436,2544``), and the
python ``BoxPSDataset`` API (``python/paddle/fluid/dataset.py:1225``).

TPU-first shape: batches are packed host-side to STATIC shapes
(:class:`SlotBatch`) so the jitted train step never recompiles; per-pass
unique keys are collected during load (role of ``MergeInsKeys`` →
``PSAgent::AddKey``) and handed to the sparse embedding engine's
``feed_pass``. Cross-node shuffle exchanges record buckets between hosts
(pluggable transport; in-process loopback by default — multi-host wiring
rides jax distributed / gRPC, not MPI).
"""

from __future__ import annotations

import os
import queue
import subprocess
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import log, monitor
from paddlebox_tpu.data.channel import Channel, ClosedChannelError
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.data.slots import DataFeedConfig, Instance, SlotBatch


def _read_file_lines(path: str, pipe_command: str) -> Iterator[str]:
    """Stream lines from a file, optionally through a shell filter.

    Role of ``pipe_command`` in data_feed.proto:47 / shell_popen in
    ``io/fs.cc:69`` — e.g. ``pipe_command="zcat"`` for gzip shards.
    """
    if pipe_command:
        with open(path, "rb") as f:
            proc = subprocess.Popen(
                pipe_command, shell=True, stdin=f,
                stdout=subprocess.PIPE, bufsize=1 << 20)
            assert proc.stdout is not None
            try:
                for raw in proc.stdout:
                    yield raw.decode("utf-8", "replace")
            finally:
                proc.stdout.close()
                ret = proc.wait()
            if ret != 0:
                # A failing filter (typo'd decompressor, truncated file)
                # must not silently produce an empty pass.
                raise RuntimeError(
                    f"pipe_command {pipe_command!r} exited {ret} on {path}")
    else:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            yield from f


class Dataset:
    """In-memory slot dataset with pass lifecycle.

    Typical CTR pass loop (mirrors BoxPSDataset usage, dataset.py:1225):

        ds = Dataset(config, num_reader_threads=8)
        ds.set_filelist(shards)
        ds.load_into_memory()          # or preload_into_memory + wait
        ds.local_shuffle(seed)
        for batch in ds.batches():     # static-shape SlotBatch stream
            ...
        ds.clear()
    """

    def __init__(self, config: DataFeedConfig, *, num_reader_threads: int = 4,
                 channel_capacity: int = 1 << 14):
        self.config = config
        self.num_reader_threads = max(1, num_reader_threads)
        self._channel_capacity = channel_capacity
        self._filelist: List[str] = []
        self._instances: List[Instance] = []
        self._preload_threads: List[threading.Thread] = []
        self._preload_channel: Optional[Channel] = None
        self._reader_errors: List[BaseException] = []
        self._lock = threading.Lock()
        # Hook invoked with each loaded instance batch's keys at load time —
        # wired to the embedding engine's pass-key collector (role of
        # PSAgent::AddKey threading in MergeInsKeys, data_set.cc:2289).
        self.key_sink: Optional[Callable[[np.ndarray], None]] = None

    # -- file list ---------------------------------------------------------

    def set_filelist(self, files: Sequence[str]) -> None:
        missing = [f for f in files if not os.path.exists(f)]
        if missing:
            raise FileNotFoundError(f"dataset files missing: {missing[:3]}")
        self._filelist = list(files)

    @property
    def filelist(self) -> List[str]:
        return list(self._filelist)

    # -- load --------------------------------------------------------------

    def _reader_worker(self, file_q: "queue.Queue[str]", out: Channel) -> None:
        try:
            self._read_files(file_q, out)
        except BaseException as e:  # surfaced by load_into_memory/wait
            with self._lock:
                self._reader_errors.append(e)

    def _read_files(self, file_q: "queue.Queue[str]", out: Channel) -> None:
        cfg = self.config
        while True:
            try:
                path = file_q.get_nowait()
            except queue.Empty:
                return
            n = 0
            chunk: List[str] = []
            for line in _read_file_lines(path, cfg.pipe_command):
                chunk.append(line)
                if len(chunk) >= 4096:
                    ins = parse_lines(chunk, cfg)
                    n += len(ins)
                    out.put_many(ins)
                    chunk.clear()
            if chunk:
                ins = parse_lines(chunk, cfg)
                n += len(ins)
                out.put_many(ins)
            monitor.add("dataset/ins_loaded", n)
            log.vlog(1, "loaded %d instances from %s", n, path)

    def _start_load(self) -> Channel:
        file_q: "queue.Queue[str]" = queue.Queue()
        for f in self._filelist:
            file_q.put(f)
        out: Channel = Channel(self._channel_capacity)
        threads = []
        nthreads = min(self.num_reader_threads, max(1, len(self._filelist)))
        for _ in range(nthreads):
            t = threading.Thread(target=self._reader_worker,
                                 args=(file_q, out), daemon=True)
            t.start()
            threads.append(t)

        def closer():
            for t in threads:
                t.join()
            out.close()

        threading.Thread(target=closer, daemon=True).start()
        return out

    def _raise_reader_errors(self) -> None:
        with self._lock:
            errs, self._reader_errors = self._reader_errors, []
        if errs:
            raise errs[0]

    def load_into_memory(self) -> None:
        """Blocking load of the whole filelist (role of LoadIntoMemory)."""
        ch = self._start_load()
        self._drain(ch)
        self._raise_reader_errors()

    def preload_into_memory(self) -> None:
        """Start background load (role of PreLoadIntoMemory — overlaps the
        previous pass's training with the next pass's read)."""
        ch = self._start_load()
        self._preload_channel = ch
        t = threading.Thread(target=self._drain, args=(ch,), daemon=True)
        t.start()
        self._preload_threads = [t]

    def wait_preload_done(self) -> None:
        """Role of WaitPreLoadDone/WaitFeedPassDone."""
        for t in self._preload_threads:
            t.join()
        self._preload_threads = []
        self._preload_channel = None
        self._raise_reader_errors()

    def _drain(self, ch: Channel) -> None:
        sink = self.key_sink
        local: List[Instance] = []
        try:
            while True:
                items = ch.get_many(1024)
                local.extend(items)
                if sink is not None:
                    keys = [i for ins in items for i in ins.sparse.values()]
                    if keys:
                        sink(np.concatenate(keys))
        except ClosedChannelError:
            pass
        with self._lock:
            self._instances.extend(local)

    # -- shuffle -----------------------------------------------------------

    def local_shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.default_rng(seed)
        with self._lock:
            rng.shuffle(self._instances)

    def global_shuffle(self, *, num_ranks: int = 1, rank: int = 0,
                       exchange: Optional[Callable[[List[List[Instance]]],
                                                   List[Instance]]] = None,
                       seed: Optional[int] = None,
                       allow_partition: bool = False) -> None:
        """Cross-node record shuffle (role of PadBoxSlotDataset::ShuffleData
        → boxps::PaddleShuffler → ReceiveSuffleData, data_set.cc:2436,2544).

        Records are hashed into ``num_ranks`` buckets; ``exchange`` ships
        bucket lists to their owner ranks and returns what this rank
        receives. With ``num_ranks > 1`` a transport is REQUIRED unless
        ``allow_partition=True`` explicitly opts into keeping only this
        rank's bucket (useful to simulate one rank of a cluster — the other
        buckets are dropped).
        """
        if num_ranks > 1 and exchange is None and not allow_partition:
            raise ValueError(
                "global_shuffle with num_ranks>1 needs an exchange transport "
                "(or allow_partition=True to keep only this rank's bucket, "
                "dropping the rest)")
        rng = np.random.default_rng(seed)
        with self._lock:
            assign = rng.integers(num_ranks, size=len(self._instances))
            order = np.argsort(assign, kind="stable")
            counts = np.bincount(assign, minlength=num_ranks)
            bounds = np.concatenate([[0], np.cumsum(counts)])
            buckets: List[List[Instance]] = [
                [self._instances[j] for j in order[bounds[r]:bounds[r + 1]]]
                for r in range(num_ranks)]
            if exchange is None:
                received = buckets[rank]
                dropped = sum(len(b) for i, b in enumerate(buckets)
                              if i != rank)
                if dropped:
                    monitor.add("dataset/shuffle_partition_dropped", dropped)
            else:
                received = exchange(buckets)
            self._instances = received
        self.local_shuffle(seed)

    # -- access ------------------------------------------------------------

    @property
    def num_instances(self) -> int:
        with self._lock:
            return len(self._instances)

    def batches(self, *, drop_last: bool = False,
                batch_size: Optional[int] = None) -> Iterator[SlotBatch]:
        """Yield static-shape SlotBatches; the short final batch is padded
        with invalid rows unless drop_last."""
        bs = batch_size or self.config.batch_size
        with self._lock:
            snapshot = list(self._instances)
        for i in range(0, len(snapshot), bs):
            chunk = snapshot[i:i + bs]
            if len(chunk) < bs and drop_last:
                return
            yield SlotBatch.pack(chunk, self.config, bs)

    def batches_sharded(self, num_shards: int, *,
                        batch_size: Optional[int] = None
                        ) -> Iterator[SlotBatch]:
        """Yield batches packed as ``num_shards`` self-contained per-device
        sub-batches (see SlotBatch.pack_sharded) — the layout a dp-sharded
        train step consumes directly."""
        bs = batch_size or self.config.batch_size
        with self._lock:
            snapshot = list(self._instances)
        for i in range(0, len(snapshot), bs):
            chunk = snapshot[i:i + bs]
            yield SlotBatch.pack_sharded(chunk, self.config, num_shards, bs)

    # -- pass keys ---------------------------------------------------------

    def pass_keys(self) -> np.ndarray:
        """Unique feasigns currently loaded (role of the per-pass key set
        registered via FeedPass, box_wrapper.h:1239)."""
        with self._lock:
            parts = [v for ins in self._instances
                     for v in ins.sparse.values() if v.size]
        if not parts:
            return np.empty((0,), np.uint64)
        return np.unique(np.concatenate(parts))

    def clear(self) -> None:
        with self._lock:
            self._instances.clear()
