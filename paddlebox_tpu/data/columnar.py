"""Columnar record chunks: the vectorized host-side data representation.

Role of the reference's C++ record structures and batch packing
(``SlotRecordObject`` pools + ``BuildSlotBatchGPU``/``CopyForTensor``,
``data_feed.h:202``, ``data_feed.cc:2713``): instead of per-instance
objects, a parsed file chunk is a set of flat numpy arrays — labels, and
per-slot CSR (concatenated feasigns + row offsets). Every batch/shuffle
operation is then a vectorized gather, and the native C++ parser
(``native/parser.cc``) writes this layout directly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.data.slots import DataFeedConfig, Instance, SlotBatch


def csr_gather(values: np.ndarray, starts: np.ndarray,
               lens: np.ndarray):
    """Gather ragged rows: for each j, take values[starts[j] : starts[j] +
    lens[j]]. Returns (gathered values, new offsets [len(starts)+1])."""
    new_offs = np.zeros(starts.size + 1, np.int64)
    np.cumsum(lens, out=new_offs[1:])
    total = int(new_offs[-1])
    gather = (np.repeat(starts, lens)
              + np.arange(total, dtype=np.int64)
              - np.repeat(new_offs[:-1], lens))
    return values[gather], new_offs


@dataclasses.dataclass
class ColumnarChunk:
    """A set of parsed records in columnar CSR form."""

    labels: np.ndarray                      # [n, L] float32
    sparse_ids: Dict[str, np.ndarray]       # slot -> concat uint64
    sparse_offsets: Dict[str, np.ndarray]   # slot -> [n+1] int64
    dense: Dict[str, np.ndarray]            # slot -> [n, dim] float32

    @property
    def num_rows(self) -> int:
        return int(self.labels.shape[0])

    @property
    def nbytes(self) -> int:
        """Total array payload bytes (shm frame sizing / ingest metrics)."""
        return int(self.labels.nbytes
                   + sum(v.nbytes for v in self.sparse_ids.values())
                   + sum(v.nbytes for v in self.sparse_offsets.values())
                   + sum(v.nbytes for v in self.dense.values()))

    def all_keys(self) -> np.ndarray:
        parts = [v for v in self.sparse_ids.values() if v.size]
        if not parts:
            return np.empty((0,), np.uint64)
        return np.concatenate(parts)

    @staticmethod
    def empty(config: DataFeedConfig) -> "ColumnarChunk":
        return ColumnarChunk(
            labels=np.empty((0, config.num_labels), np.float32),
            sparse_ids={s.name: np.empty((0,), np.uint64)
                        for s in config.sparse_slots},
            sparse_offsets={s.name: np.zeros((1,), np.int64)
                            for s in config.sparse_slots},
            dense={s.name: np.empty((0, s.dim), np.float32)
                   for s in config.dense_slots})

    @staticmethod
    def concat(chunks: Sequence["ColumnarChunk"]) -> "ColumnarChunk":
        if not chunks:
            raise ValueError("concat of no chunks")
        if len(chunks) == 1:
            return chunks[0]
        labels = np.concatenate([c.labels for c in chunks])
        ids: Dict[str, np.ndarray] = {}
        offs: Dict[str, np.ndarray] = {}
        for s in chunks[0].sparse_ids:
            ids[s] = np.concatenate([c.sparse_ids[s] for c in chunks])
            parts = [chunks[0].sparse_offsets[s]]
            base = chunks[0].sparse_offsets[s][-1]
            for c in chunks[1:]:
                parts.append(c.sparse_offsets[s][1:] + base)
                base = base + c.sparse_offsets[s][-1]
            offs[s] = np.concatenate(parts)
        dense = {s: np.concatenate([c.dense[s] for c in chunks])
                 for s in chunks[0].dense}
        return ColumnarChunk(labels, ids, offs, dense)

    def take(self, idx: np.ndarray) -> "ColumnarChunk":
        """Vectorized row gather (shuffle / partition primitive)."""
        idx = np.asarray(idx, np.int64)
        ids: Dict[str, np.ndarray] = {}
        offs: Dict[str, np.ndarray] = {}
        for s, o in self.sparse_offsets.items():
            lens = np.diff(o)
            ids[s], offs[s] = csr_gather(self.sparse_ids[s], o[idx],
                                         lens[idx])
        return ColumnarChunk(
            labels=self.labels[idx], sparse_ids=ids, sparse_offsets=offs,
            dense={s: v[idx] for s, v in self.dense.items()})

    def shuffle_slot(self, slot: str, rng: np.random.Generator
                     ) -> "ColumnarChunk":
        """Shuffle ONE slot's per-row value lists across rows, leaving all
        other slots/labels fixed — the AUC-runner feature-importance mode
        (role of SlotsShuffle, box_wrapper.h:1190 / data_set.h slots_shuffle):
        the AUC drop when a slot's values are decorrelated from the label
        measures that slot's contribution."""
        if slot not in self.sparse_ids:
            raise KeyError(f"unknown sparse slot {slot!r}")
        n = self.num_rows
        perm = rng.permutation(n)
        o = self.sparse_offsets[slot]
        lens = np.diff(o)
        ids = dict(self.sparse_ids)
        offs = dict(self.sparse_offsets)
        ids[slot], offs[slot] = csr_gather(self.sparse_ids[slot],
                                           o[perm], lens[perm])
        return ColumnarChunk(labels=self.labels, sparse_ids=ids,
                             sparse_offsets=offs, dense=self.dense)

    # -- disk spill (role of BinaryArchive record serialization) -----------

    def save(self, path: str) -> None:
        """Write the chunk as one npz archive (role of
        BinaryArchiveWriter in DumpIntoDisk, data_set.cc:2167)."""
        payload = {"labels": self.labels}
        for s, v in self.sparse_ids.items():
            payload[f"sid:{s}"] = v
            payload[f"soff:{s}"] = self.sparse_offsets[s]
        for s, v in self.dense.items():
            payload[f"dense:{s}"] = v
        import os
        # Dot-prefixed temp name: must NOT match the chunk-*.npz glob, or
        # a crash mid-save would poison later loads with a truncated file.
        d, base = os.path.split(path)
        tmp = os.path.join(d, f".{base}.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "ColumnarChunk":
        data = np.load(path)
        ids, offs, dense = {}, {}, {}
        for k in data.files:
            if k.startswith("sid:"):
                ids[k[4:]] = data[k]
            elif k.startswith("soff:"):
                offs[k[5:]] = data[k]
            elif k.startswith("dense:"):
                dense[k[6:]] = data[k]
        return ColumnarChunk(labels=data["labels"], sparse_ids=ids,
                             sparse_offsets=offs, dense=dense)

    # -- pv grouping helpers ----------------------------------------------

    def group_keys(self, slot: str) -> "tuple[np.ndarray, np.ndarray]":
        """Per-row (group key, has_key) from the FIRST value of the given
        sparse slot (role of the search-id grouping in PaddleBoxDataFeed
        pv mode, data_feed.h:1701). Rows with an empty slot report
        has_key=False and form singleton groups downstream — a synthetic
        key value could collide with real full-range uint64 feasigns, so
        the mask travels separately."""
        if slot not in self.sparse_ids:
            raise KeyError(f"unknown sparse slot {slot!r}")
        o = self.sparse_offsets[slot]
        lens = np.diff(o)
        has = lens > 0
        keys = np.zeros((self.num_rows,), np.uint64)
        keys[has] = self.sparse_ids[slot][o[:-1][has]]
        return keys, has

    # -- batch packing (vectorized BuildSlotBatchGPU) ----------------------

    def pack_batch(self, lo: int, hi: int, config: DataFeedConfig,
                   batch_size: int,
                   capacities: Optional[Dict[str, int]] = None) -> SlotBatch:
        """Pack rows [lo, hi) into one static-shape SlotBatch, fully
        vectorized (no per-instance python loop)."""
        n = hi - lo
        bs = batch_size
        if n > bs:
            raise ValueError(f"{n} rows > batch_size {bs}")
        labels = np.zeros((bs, config.num_labels), np.float32)
        labels[:n] = self.labels[lo:hi]
        valid = np.zeros((bs,), bool)
        valid[:n] = True

        ids_out: Dict[str, np.ndarray] = {}
        segs_out: Dict[str, np.ndarray] = {}
        lens_out: Dict[str, np.ndarray] = {}
        for slot in config.sparse_slots:
            name = slot.name
            cap = (capacities[name] if capacities is not None
                   else config.sparse_capacity(slot, bs))
            o = self.sparse_offsets[name]
            lens = np.diff(o[lo:hi + 1]).astype(np.int64)
            if slot.max_len:
                lens = np.minimum(lens, slot.max_len)
            vals, _ = csr_gather(self.sparse_ids[name], o[lo:hi], lens)
            total = int(lens.sum())
            segs = np.repeat(np.arange(n, dtype=np.int32), lens)
            if total > cap:
                monitor.add(f"slot_overflow/{name}", total - cap)
                vals, segs = vals[:cap], segs[:cap]
                total = cap
            out_v = np.zeros((cap,), np.uint64)
            out_s = np.full((cap,), bs, np.int32)
            out_v[:total] = vals
            out_s[:total] = segs
            ids_out[name] = out_v
            segs_out[name] = out_s
            cnt = np.bincount(segs, minlength=bs).astype(np.int32)
            lens_out[name] = cnt

        dense_out: Dict[str, np.ndarray] = {}
        for slot in config.dense_slots:
            d = np.zeros((bs, slot.dim), np.float32)
            src = self.dense.get(slot.name)
            if src is not None and src.size:
                d[:n, :src.shape[1]] = src[lo:hi, :slot.dim]
            dense_out[slot.name] = d

        return SlotBatch(labels=labels, valid=valid, ids=ids_out,
                         segments=segs_out, lengths=lens_out,
                         dense=dense_out)

    def pack_batch_sharded(self, lo: int, hi: int, config: DataFeedConfig,
                           num_shards: int, batch_size: int) -> SlotBatch:
        """Sharded-layout pack (role of SlotBatch.pack_sharded) from
        columnar rows [lo, hi)."""
        if batch_size % num_shards:
            raise ValueError(
                f"batch_size {batch_size} not divisible by {num_shards}")
        bs_local = batch_size // num_shards
        caps_local = {
            slot.name: config.sparse_capacity(slot, batch_size, num_shards)
            // num_shards
            for slot in config.sparse_slots}
        subs = []
        for s in range(num_shards):
            a = min(lo + s * bs_local, hi)
            b = min(a + bs_local, hi)
            subs.append(self.pack_batch(a, b, config, bs_local, caps_local))
        return SlotBatch(
            labels=np.concatenate([b.labels for b in subs]),
            valid=np.concatenate([b.valid for b in subs]),
            ids={k: np.concatenate([b.ids[k] for b in subs])
                 for k in subs[0].ids},
            segments={k: np.concatenate([b.segments[k] for b in subs])
                      for k in subs[0].segments},
            lengths={k: np.concatenate([b.lengths[k] for b in subs])
                     for k in subs[0].lengths},
            dense={k: np.concatenate([b.dense[k] for b in subs])
                   for k in subs[0].dense},
        )


def instances_to_chunk(instances: Sequence[Instance],
                       config: DataFeedConfig) -> ColumnarChunk:
    """Bridge from the python parser's Instance objects."""
    n = len(instances)
    labels = np.zeros((n, config.num_labels), np.float32)
    for i, ins in enumerate(instances):
        labels[i] = ins.labels
    ids: Dict[str, np.ndarray] = {}
    offs: Dict[str, np.ndarray] = {}
    for slot in config.sparse_slots:
        parts = []
        lens = np.zeros(n, np.int64)
        for i, ins in enumerate(instances):
            v = ins.sparse.get(slot.name)
            if v is not None and v.size:
                parts.append(v)
                lens[i] = v.size
        o = np.zeros(n + 1, np.int64)
        np.cumsum(lens, out=o[1:])
        ids[slot.name] = (np.concatenate(parts) if parts
                          else np.empty((0,), np.uint64))
        offs[slot.name] = o
    dense: Dict[str, np.ndarray] = {}
    for slot in config.dense_slots:
        d = np.zeros((n, slot.dim), np.float32)
        for i, ins in enumerate(instances):
            v = ins.dense.get(slot.name)
            if v is not None:
                d[i, :v.size] = v[:slot.dim]
        dense[slot.name] = d
    return ColumnarChunk(labels, ids, offs, dense)
