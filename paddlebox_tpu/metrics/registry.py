"""Named metric registry + the full metric-variant family.

Role of the ``Metric`` singleton and its ``MetricMsg`` hierarchy
(``fleet/metrics.h:217-560``): training code registers named metrics bound
to tensor names and a *phase* (join/update multi-phase training picks which
metrics accumulate on a given pass), the worker feeds every batch to all
metrics of the active phase, and ``get_metric`` computes the distributed
result and resets.

Variants mirrored from the reference:
- basic AUC               (``MetricMsg``)
- per-user AUC            (``WuAucMetricMsg``,        metrics.h:306)
- multi-task AUC          (``MultiTaskMetricMsg``,    metrics.h:346):
  N prediction columns + a cmatch tag per record selects WHICH column
- cmatch/rank-filtered    (``CmatchRankMetricMsg``,   metrics.h:430)
- mask-filtered           (``MaskMetricMsg``,         metrics.h:511)
- cmatch+rank+mask        (``CmatchRankMaskMetricMsg``)
- continue (regression)   (``_continue_bucket_error`` per-bucket mae/rmse)

TPU-first note: the hot-path AUC accumulation in the train step itself is
the device-side ``AucState`` (metrics/auc.py) folded into the jit step with
an incremental psum; this registry is the *host-side* flexible tier the
reference also runs on CPU (its variant add_data loops are host loops over
copied-back tensors, metrics.h:415-428) — used for eval passes, multi-task
slicing, and anything not worth burning device time on.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import log

# Optional cross-rank reduction: fn(array) -> summed array across ranks
# (role of the boxps-MPI / Gloo allreduce in metrics.cc:286-292).
ReduceFn = Callable[[np.ndarray], np.ndarray]


# Constants from the reference bucket-error sweep (metrics.h:213-214).
_REL_ERR_BOUND = 0.05
_MAX_SPAN = 0.01


# The sequential sweep is O(nonzero buckets) in Python; above this size the
# histogram is rebinned first (ctr resolution stays ~60x finer than the
# 0.01 window span, so the result is unchanged to ~1e-4).
_SWEEP_MAX_BUCKETS = 16384


def bucket_error_sweep(table: np.ndarray) -> float:
    """Adaptive-span calibration error (calculate_bucket_error,
    metrics.cc:357-391): grow a bucket window until the binomial relative
    error of its adjusted ctr is small enough, then score
    |actual/adjusted - 1| weighted by impressions. table is [2, nb]."""
    neg, pos = np.asarray(table[0], np.float64), np.asarray(table[1], np.float64)
    if neg.shape[0] > _SWEEP_MAX_BUCKETS:
        nb0 = neg.shape[0]
        factor = -(-nb0 // _SWEEP_MAX_BUCKETS)
        pad = (-nb0) % factor
        if pad:
            neg = np.concatenate([neg, np.zeros(pad)])
            pos = np.concatenate([pos, np.zeros(pad)])
        neg = neg.reshape(-1, factor).sum(axis=1)
        pos = pos.reshape(-1, factor).sum(axis=1)
    last_ctr = -1.0
    impression_sum = ctr_sum = click_sum = 0.0
    error_sum = error_count = 0.0
    nb = neg.shape[0]
    nonzero = np.flatnonzero((neg + pos) > 0)
    for i in nonzero:
        click = pos[i]
        show = neg[i] + pos[i]
        ctr = i / nb
        if abs(ctr - last_ctr) > _MAX_SPAN:
            last_ctr = ctr
            impression_sum = ctr_sum = click_sum = 0.0
        impression_sum += show
        ctr_sum += ctr * show
        click_sum += click
        adjust_ctr = ctr_sum / impression_sum
        if adjust_ctr <= 0 or adjust_ctr >= 1:
            continue
        rel = ((1 - adjust_ctr) / (adjust_ctr * impression_sum)) ** 0.5
        if rel < _REL_ERR_BOUND:
            actual_ctr = click_sum / impression_sum
            error_sum += abs(actual_ctr / adjust_ctr - 1) * impression_sum
            error_count += impression_sum
            last_ctr = -1.0
    return error_sum / error_count if error_count > 0 else 0.0


def compute_from_table(table: np.ndarray, abserr: float, sqrerr: float,
                       pred_sum: float, label_sum: float, count: float
                       ) -> Dict[str, float]:
    """Final sweep shared by the device-side AucState and the host
    calculator (computeBucketAuc + calculate_bucket_error + calibration
    ratios, metrics.cc:124-391). table is the [2, nb] neg/pos histogram.

    AUC = P(score_pos > score_neg): each positive in bucket b beats all
    negatives in lower buckets and ties (half) within its own bucket."""
    table = np.asarray(table, np.float64)
    neg, pos = table[0], table[1]
    tot_pos, tot_neg = pos.sum(), neg.sum()
    neg_cum = np.cumsum(neg) - neg
    area = float(np.sum(pos * (neg_cum + neg * 0.5)))
    auc = (area / (tot_pos * tot_neg)
           if tot_pos > 0 and tot_neg > 0 else float("nan"))
    c = max(count, 1.0)
    return {
        "auc": auc,
        "bucket_error": bucket_error_sweep(table),
        "mae": abserr / c,
        "rmse": (sqrerr / c) ** 0.5,
        "actual_ctr": label_sum / c,
        "predicted_ctr": pred_sum / c,
        # COPC (Click Over Predicted Click) = actual/predicted ctr —
        # 1.0 = calibrated; the inverse of the reference's PCOC. The
        # headline calibration ratio every pass report carries.
        "copc": (label_sum / pred_sum if pred_sum > 0
                 else float("nan")),
        "count": count,
    }


class BucketAucCalculator:
    """Host twin of ``BasicAucCalculator`` (fleet/metrics.h:46): bucketed
    pos/neg histograms + running calibration sums; exact AUC + bucket error
    on compute."""

    #: uid-hash spill fan-out (each bucket is one uid-complete partition).
    SPILL_BUCKETS = 32
    _SPILL_DTYPE = np.dtype(
        [("uid", np.uint64), ("pred", np.float64), ("label", np.uint8)])

    def __init__(self, num_buckets: int = 1_000_000,
                 spill_records: Optional[int] = None):
        from paddlebox_tpu.core import flags
        self.num_buckets = num_buckets
        self.spill_records = (int(flags.flag("wuauc_spill_records"))
                              if spill_records is None else spill_records)
        self._spill_dir: Optional[str] = None
        self.reset()

    def reset(self) -> None:
        self._table = np.zeros((2, self.num_buckets), np.float64)
        self._abserr = 0.0
        self._sqrerr = 0.0
        self._pred_sum = 0.0
        self._label_sum = 0.0
        self._count = 0.0
        # WuAuc raw records (uid variant needs exact per-user grouping).
        # RAM holds at most ``spill_records``; beyond that, records stream
        # to uid-hash bucket files (role of the WuAucMetricMsg shuffle —
        # the reference ships records to their uid owner; single-host we
        # ship them to disk) so a production-length eval pass cannot grow
        # host RSS without bound (VERDICT r02 task 10).
        self._uid_chunks: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._uid_in_ram = 0
        self.uid_record_count = 0      # lifetime records since reset
        self._drop_spill()

    def _drop_spill(self) -> None:
        if self._spill_dir is not None:
            import shutil
            shutil.rmtree(self._spill_dir, ignore_errors=True)
            self._spill_dir = None

    def add_data(self, preds: np.ndarray, labels: np.ndarray,
                 mask: Optional[np.ndarray] = None) -> None:
        preds = np.asarray(preds, np.float64).ravel()
        labels = np.asarray(labels, np.float64).ravel()
        if mask is not None:
            keep = np.asarray(mask).ravel().astype(bool)
            preds, labels = preds[keep], labels[keep]
        if preds.size == 0:
            return
        nb = self.num_buckets
        bucket = np.clip((preds * nb).astype(np.int64), 0, nb - 1)
        lab = (labels > 0.5).astype(np.int64)
        np.add.at(self._table, (lab, bucket), 1.0)
        err = preds - labels
        self._abserr += float(np.abs(err).sum())
        self._sqrerr += float((err * err).sum())
        self._pred_sum += float(preds.sum())
        self._label_sum += float(labels.sum())
        self._count += float(preds.size)

    def add_uid_data(self, preds: np.ndarray, labels: np.ndarray,
                     uids: np.ndarray) -> None:
        """Keep raw records for exact per-user AUC (add_uid_data role);
        bounded RAM — spills to uid-hash buckets past the threshold."""
        self.add_data(preds, labels)
        u = np.asarray(uids).ravel().astype(np.uint64)
        self._uid_chunks.append((u,
                                 np.asarray(preds, np.float64).ravel().copy(),
                                 np.asarray(labels, np.float64).ravel().copy()))
        self._uid_in_ram += u.size
        self.uid_record_count += u.size
        if self._uid_in_ram > self.spill_records:
            self._spill_uid_chunks()

    def _spill_uid_chunks(self) -> None:
        """Flush RAM records to per-uid-hash-bucket files (append)."""
        if self._spill_dir is None:
            import tempfile
            self._spill_dir = tempfile.mkdtemp(prefix="wuauc_spill_")
        if not self._uid_chunks:
            return
        uids = np.concatenate([c[0] for c in self._uid_chunks])
        preds = np.concatenate([c[1] for c in self._uid_chunks])
        labels = np.concatenate([c[2] for c in self._uid_chunks])
        rec = np.empty(uids.shape[0], self._SPILL_DTYPE)
        rec["uid"] = uids
        rec["pred"] = preds
        rec["label"] = (labels > 0.5).astype(np.uint8)
        bucket = self._uid_bucket(uids)
        order = np.argsort(bucket, kind="stable")
        sb = bucket[order]
        starts = np.searchsorted(sb, np.arange(self.SPILL_BUCKETS + 1))
        rec_sorted = rec[order]
        import os
        for b in range(self.SPILL_BUCKETS):
            lo, hi = starts[b], starts[b + 1]
            if lo == hi:
                continue
            with open(os.path.join(self._spill_dir, f"b{b:03d}.bin"),
                      "ab") as f:
                f.write(rec_sorted[lo:hi].tobytes())
        self._uid_chunks = []
        self._uid_in_ram = 0

    @classmethod
    def _uid_bucket(cls, uids: np.ndarray) -> np.ndarray:
        h = uids ^ (uids >> np.uint64(33))
        with np.errstate(over="ignore"):
            h = h * np.uint64(0xFF51AFD7ED558CCD)
        return (h % np.uint64(cls.SPILL_BUCKETS)).astype(np.int64)

    def uid_record_partitions(self):
        """Yield exactly SPILL_BUCKETS (uids, preds, labels) partitions,
        each uid-COMPLETE (all of a user's records in exactly one
        partition, by the shared uid hash) — callers sum
        ``wuauc_accumulate`` over them. The count is FIXED so ranks of a
        distributed eval iterate in lockstep regardless of who spilled
        (per-partition gather collectives must pair up). Never
        materializes more than one bucket at once."""
        import os
        empty = (np.empty(0, np.uint64), np.empty(0, np.float64),
                 np.empty(0, np.float64))
        if self._spill_dir is None:
            if self._uid_chunks:
                uids = np.concatenate([c[0] for c in self._uid_chunks])
                preds = np.concatenate([c[1] for c in self._uid_chunks])
                labels = np.concatenate([c[2] for c in self._uid_chunks])
                bucket = self._uid_bucket(uids)
            for b in range(self.SPILL_BUCKETS):
                if not self._uid_chunks:
                    yield empty
                    continue
                sel = bucket == b
                yield uids[sel], preds[sel], labels[sel]
            return
        self._spill_uid_chunks()     # uid-completeness needs the RAM tail
        for b in range(self.SPILL_BUCKETS):
            path = os.path.join(self._spill_dir, f"b{b:03d}.bin")
            if not os.path.exists(path):
                yield empty
                continue
            rec = np.fromfile(path, dtype=self._SPILL_DTYPE)
            yield (rec["uid"].copy(), rec["pred"].copy(),
                   rec["label"].astype(np.float64))

    # -- final sweep -------------------------------------------------------

    def compute(self, reduce_fn: Optional[ReduceFn] = None) -> Dict[str, float]:
        table = self._table
        scalars = np.array([self._abserr, self._sqrerr, self._pred_sum,
                            self._label_sum, self._count], np.float64)
        if reduce_fn is not None:
            table = reduce_fn(table)
            scalars = reduce_fn(scalars)
        return compute_from_table(table, *scalars)


class ContinueCalculator:
    """Regression ("continue value") metrics with per-value-bucket stats.

    Role of ``add_continue_data`` + ``_continue_bucket_error``
    (``box_wrapper.h:785-800``, ``metrics.cc:560-600``): global mae/rmse/
    actual/predicted means plus the same stats per label-magnitude bucket.
    """

    def __init__(self, num_buckets: int = 10, max_value: float = 1.0):
        self.num_buckets = num_buckets
        self.max_value = max_value
        self.reset()

    def reset(self) -> None:
        # per bucket: [abserr, sqrerr, label_sum, pred_sum, count]
        self._acc = np.zeros((self.num_buckets, 5), np.float64)

    def add_data(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, np.float64).ravel()
        labels = np.asarray(labels, np.float64).ravel()
        if preds.size == 0:
            return
        b = np.clip((labels / self.max_value * self.num_buckets).astype(int),
                    0, self.num_buckets - 1)
        err = preds - labels
        for col, v in enumerate((np.abs(err), err * err, labels, preds,
                                 np.ones_like(preds))):
            np.add.at(self._acc[:, col], b, v)

    def compute(self, reduce_fn: Optional[ReduceFn] = None) -> Dict[str, object]:
        acc = reduce_fn(self._acc) if reduce_fn is not None else self._acc
        tot = acc.sum(axis=0)
        c = max(tot[4], 1.0)
        cb = np.maximum(acc[:, 4], 1.0)
        return {
            "mae": tot[0] / c,
            "rmse": (tot[1] / c) ** 0.5,
            "actual_value": tot[2] / c,
            "predicted_value": tot[3] / c,
            "count": tot[4],
            "bucket_mae": (acc[:, 0] / cb).tolist(),
            "bucket_rmse": np.sqrt(acc[:, 1] / cb).tolist(),
            "bucket_count": acc[:, 4].tolist(),
        }


def _parse_cmatch_rank(x: np.ndarray, ignore_rank: bool
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Tag decode (parse_cmatch_rank, metrics.h:300): with ignore_rank the
    whole value is the cmatch id; otherwise high 32 bits = cmatch, low
    8 bits = rank."""
    x = np.asarray(x, np.uint64)
    if ignore_rank:
        return x.astype(np.int64), np.zeros_like(x, np.int64)
    return (x >> np.uint64(32)).astype(np.int64), \
        (x & np.uint64(0xFF)).astype(np.int64)


@dataclasses.dataclass
class MetricMsg:
    """One registered metric: variant config + calculator + phase."""

    name: str
    kind: str                      # auc | wuauc | multi_task | cmatch_rank |
    #                                mask | cmatch_rank_mask | continue
    phase: int = -1                # -1: active in every phase
    calculator: object = None
    cmatch_rank_group: Tuple[Tuple[int, int], ...] = ()
    ignore_rank: bool = True

    def matches(self, cmatch: np.ndarray, rank: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
        """(keep mask, matched group index per record)."""
        keep = np.zeros(cmatch.shape[0], bool)
        idx = np.full(cmatch.shape[0], -1, np.int64)
        for j, (cm, rk) in enumerate(self.cmatch_rank_group):
            hit = ((cmatch == cm) if self.ignore_rank
                   else (cmatch == cm) & (rank == rk))
            idx = np.where(~keep & hit, j, idx)
            keep |= hit
        return keep, idx


def parse_group(spec: str, ignore_rank: bool) -> Tuple[Tuple[int, int], ...]:
    """"23_0 severa_1"-style spec → ((cmatch, rank), ...) pairs
    (constructor parsing, metrics.h:365-377,445-458)."""
    out = []
    for tok in spec.split():
        if ignore_rank and "_" not in tok:
            out.append((int(tok), 0))
        else:
            cm, rk = tok.split("_")
            out.append((int(cm), int(rk)))
    return tuple(out)


class MetricRegistry:
    """Role of the process-wide ``Metric`` singleton (metrics.h:217):
    ``init_metric`` registers, per-batch feeds go through ``add_data``
    keyed by name, ``get_metric`` computes+resets."""

    def __init__(self):
        self._metrics: Dict[str, MetricMsg] = {}
        self.phase = 0             # role of Metric::SetPhase (join/update)

    def init_metric(self, name: str, kind: str = "auc", *, phase: int = -1,
                    bucket_size: int = 1_000_000,
                    cmatch_rank_group: str = "", ignore_rank: bool = True,
                    continue_buckets: int = 10,
                    continue_max_value: float = 1.0) -> MetricMsg:
        kind = kind.lower()
        if kind == "continue":
            calc = ContinueCalculator(continue_buckets, continue_max_value)
        else:
            calc = BucketAucCalculator(bucket_size)
        msg = MetricMsg(
            name=name, kind=kind, phase=phase, calculator=calc,
            cmatch_rank_group=parse_group(cmatch_rank_group, ignore_rank),
            ignore_rank=ignore_rank)
        self._metrics[name] = msg
        log.vlog(1, "init_metric %s kind=%s phase=%d", name, kind, phase)
        return msg

    def names(self) -> List[str]:
        return list(self._metrics)

    def _active(self, msg: MetricMsg) -> bool:
        return msg.phase < 0 or msg.phase == self.phase

    def add_data(self, name: str, preds: np.ndarray, labels: np.ndarray, *,
                 uids: Optional[np.ndarray] = None,
                 mask: Optional[np.ndarray] = None,
                 cmatch_rank: Optional[np.ndarray] = None) -> None:
        """Feed one batch. ``preds`` is [B] for single-pred kinds or a
        sequence/2-D [T, B] for multi_task (one row per task head)."""
        msg = self._metrics[name]
        if not self._active(msg):
            return
        cal = msg.calculator
        if msg.kind == "continue":
            cal.add_data(preds, labels)
            return
        if msg.kind == "auc":
            cal.add_data(preds, labels)
            return
        if msg.kind == "wuauc":
            if uids is None:
                raise ValueError(f"metric {name}: wuauc needs uids")
            cal.add_uid_data(preds, labels, uids)
            return
        if msg.kind == "mask":
            if mask is None:
                raise ValueError(f"metric {name}: mask kind needs mask")
            cal.add_data(preds, labels, mask=mask)
            return
        if cmatch_rank is None:
            raise ValueError(f"metric {name}: {msg.kind} needs cmatch_rank")
        cmatch, rank = _parse_cmatch_rank(cmatch_rank, msg.ignore_rank)
        keep, idx = msg.matches(cmatch, rank)
        labels = np.asarray(labels).ravel()
        if msg.kind == "multi_task":
            preds2 = np.atleast_2d(np.asarray(preds, np.float64))
            sel = np.where(keep, idx, 0)
            chosen = preds2[sel, np.arange(labels.shape[0])]
            cal.add_data(chosen[keep], labels[keep])
        elif msg.kind in ("cmatch_rank", "cmatch_rank_mask"):
            preds = np.asarray(preds, np.float64).ravel()
            if msg.kind == "cmatch_rank_mask":
                if mask is None:
                    raise ValueError(
                        f"metric {name}: cmatch_rank_mask needs mask")
                keep &= np.asarray(mask).ravel().astype(bool)
            cal.add_data(preds[keep], labels[keep])
        else:
            raise ValueError(f"unknown metric kind {msg.kind!r}")

    def get_metric(self, name: str, reduce_fn: Optional[ReduceFn] = None,
                   reset: bool = True,
                   gather_fn: Optional[Callable[[np.ndarray], np.ndarray]]
                   = None) -> Dict[str, object]:
        """Compute (with optional cross-rank allreduce) and reset — the
        GetMetricMsg/print path (metrics.cc:286-355).

        For the wuauc kind the per-user grouping needs the raw records, not
        a histogram, so distributed wuauc takes ``gather_fn`` (concat an
        array across ranks — WuAuc's allgather path in the reference). With
        only ``reduce_fn`` the histogram stats are global but the per-user
        keys are reported as ``wuauc_local``."""
        msg = self._metrics[name]
        cal = msg.calculator
        out = cal.compute(reduce_fn)
        if msg.kind == "wuauc":
            from paddlebox_tpu.metrics.auc import wuauc_accumulate
            ws = wt = 0.0
            users = 0
            local_records = cal.uid_record_count
            # Partitions are uid-complete (hash-bucketed), so per-user
            # sums combine across partitions AND across ranks (the uid
            # hash agrees everywhere), keeping peak memory one bucket.
            # With a gather_fn EVERY rank must iterate all partitions
            # (the per-partition collectives have to pair up), even if
            # this rank holds no records.
            if local_records or gather_fn is not None:
                for uids, preds, labels in cal.uid_record_partitions():
                    if gather_fn is not None:
                        uids = gather_fn(uids)
                        preds = gather_fn(preds)
                        labels = gather_fn(labels)
                    s, w_, c = wuauc_accumulate(uids, preds, labels)
                    ws += s
                    wt += w_
                    users += c
            # Report only when records existed (globally, in the gathered
            # case) — a phase that never ran keeps the key absent, as the
            # pre-spill behavior did.
            if local_records or wt > 0:
                w = {"wuauc": ws / wt if wt else float("nan"),
                     "wuauc_users": float(users)}
                if gather_fn is None and reduce_fn is not None:
                    w = {f"{k}_local": v for k, v in w.items()}
                out.update(w)
        if reset:
            cal.reset()
        return out


# Process-wide instance (role of Metric::GetInstance).
_global_registry: Optional[MetricRegistry] = None


def global_registry() -> MetricRegistry:
    global _global_registry
    if _global_registry is None:
        _global_registry = MetricRegistry()
    return _global_registry
