"""Distributed training metrics: exact AUC, calibration stats, per-user AUC.

Role of the reference metrics engine (``fleet/metrics.{h,cc}``, SURVEY.md
§2.2 "Metrics (AUC engine)"): ``BasicAucCalculator`` bucketed pos/neg
histograms + exact distributed AUC via histogram allreduce + trapezoid
sweep, plus mae/rmse/predicted-vs-actual CTR; ``WuAucMetricMsg`` per-user
AUC; the named ``Metric`` registry with multi-task / cmatch-rank / mask /
continue variants (metrics.h:217-560).

TPU-first: histogram accumulation is a device-side ``segment_sum`` fused
into the train step; the cross-replica reduction is a ``psum`` over the dp
axis (replacing the Gloo/MPI allreduce at metrics.cc:289); the final
trapezoid sweep runs on host at pass end. The registry tier
(metrics/registry.py) is the host-side flexible path for eval/multi-task
slicing, as in the reference.
"""

from paddlebox_tpu.metrics.auc import (
    AucState,
    auc_state_init,
    auc_accumulate,
    auc_compute,
    wuauc_compute,
)
from paddlebox_tpu.metrics.registry import (
    BucketAucCalculator,
    ContinueCalculator,
    MetricRegistry,
    global_registry,
    parse_group,
)

__all__ = [
    "AucState",
    "auc_accumulate",
    "auc_compute",
    "auc_state_init",
    "wuauc_compute",
    "BucketAucCalculator",
    "ContinueCalculator",
    "MetricRegistry",
    "global_registry",
    "parse_group",
]
