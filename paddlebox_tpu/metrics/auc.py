"""Exact bucketed AUC + calibration statistics.

Role of ``BasicAucCalculator`` (``fleet/metrics.h:46``, ``metrics.cc:33-355``):
- ``add_data``: bucket = pred * num_buckets; ``_table[label][bucket] += 1``
- distributed: allreduce-sum both histograms (metrics.cc:286-292)
- ``computeBucketAuc``: sweep buckets high→low accumulating trapezoid area
- side stats: actual ctr, predicted ctr, mae, rmse, bucket error

and ``WuAucMetricMsg`` per-user AUC (``metrics.h:306``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import flags


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class AucState:
    """Device-side accumulator (all replicated across dp after psum).

    table [2, num_buckets] float32 — pos/neg prediction histograms;
    scalar sums for calibration stats.
    """

    table: jax.Array
    abserr: jax.Array
    sqrerr: jax.Array
    pred_sum: jax.Array
    label_sum: jax.Array
    count: jax.Array

    def tree_flatten(self):
        return ((self.table, self.abserr, self.sqrerr, self.pred_sum,
                 self.label_sum, self.count), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def auc_state_init(num_buckets: Optional[int] = None) -> AucState:
    nb = num_buckets or flags.flag("auc_num_buckets")

    def z():
        # Distinct buffers per field: a shared constant would break buffer
        # donation (same buffer donated N times).
        return jnp.zeros((), jnp.float32)

    return AucState(table=jnp.zeros((2, nb), jnp.float32),
                    abserr=z(), sqrerr=z(), pred_sum=z(), label_sum=z(),
                    count=z())


def auc_accumulate(state: AucState, preds: jax.Array, labels: jax.Array,
                   valid: Optional[jax.Array] = None,
                   axis: Optional[str] = None) -> AucState:
    """Accumulate a batch (device-side, jit/shard_map-safe).

    preds/labels [B] float32 in [0,1]/{0,1}; valid [B] bool masks padding
    rows. When ``axis`` is given (inside shard_map) the per-batch increment
    is psum'd over it so the state stays replicated — the role of the
    Gloo/MPI allreduce, paid incrementally.
    """
    nb = state.table.shape[1]
    w = jnp.ones_like(preds) if valid is None else valid.astype(preds.dtype)
    bucket = jnp.clip((preds * nb).astype(jnp.int32), 0, nb - 1)
    pos = (labels > 0.5).astype(preds.dtype) * w
    # ONE width-2 scatter-add builds BOTH histograms: each sample adds
    # its (neg_w, pos_w) column at its bucket. XLA TPU scatter pays a
    # ~5 ms fixed cost per OP (PROFILE.md "AUC hist scatter"), so the
    # split show/click form — one scatter per label row, or the flat
    # segment_sum over [2*nb] whose index arithmetic defeats the
    # unique-window lowering — pays the overhead twice for the same
    # bytes. Column-major update ([:, bucket]) keeps the state layout
    # [2, nb] unchanged for checkpoints and compute_from_table.
    inc_table = jnp.zeros((2, nb), preds.dtype).at[:, bucket].add(
        jnp.stack([w - pos, pos], axis=0))
    err = (preds - labels) * w
    inc = (inc_table, jnp.sum(jnp.abs(err)), jnp.sum(err * err),
           jnp.sum(preds * w), jnp.sum(labels * w), jnp.sum(w))
    if axis is not None:
        inc = jax.lax.psum(inc, axis)
    return AucState(table=state.table + inc[0],
                    abserr=state.abserr + inc[1],
                    sqrerr=state.sqrerr + inc[2],
                    pred_sum=state.pred_sum + inc[3],
                    label_sum=state.label_sum + inc[4],
                    count=state.count + inc[5])


def auc_compute(state: AucState) -> Dict[str, float]:
    """Host-side final sweep (role of computeBucketAuc + calculate_bucket_error,
    metrics.cc:124-391). Returns auc, bucket_error, actual/predicted ctr,
    mae, rmse — via the sweep shared with the host calculator."""
    from paddlebox_tpu.metrics.registry import compute_from_table
    return compute_from_table(
        np.asarray(state.table, np.float64), float(state.abserr),
        float(state.sqrerr), float(state.pred_sum), float(state.label_sum),
        float(state.count))


def wuauc_accumulate(user_ids: np.ndarray, preds: np.ndarray,
                     labels: np.ndarray) -> Tuple[float, float, int]:
    """(wauc_sum, weight_sum, user_count) over one uid-complete partition
    of records — partitions (e.g. uid-hash spill buckets) sum, since each
    user's records live in exactly one partition."""
    order = np.argsort(user_ids, kind="stable")
    uids, preds, labels = user_ids[order], preds[order], labels[order]
    boundaries = np.flatnonzero(
        np.concatenate([[True], uids[1:] != uids[:-1], [True]]))
    wauc_sum = 0.0
    weight_sum = 0.0
    user_count = 0
    for lo, hi in zip(boundaries[:-1], boundaries[1:]):
        p, l = preds[lo:hi], labels[lo:hi]
        npos = float((l > 0.5).sum())
        nneg = float(len(l) - npos)
        if npos == 0 or nneg == 0:
            continue
        # rank-sum AUC within user
        ranks = np.argsort(np.argsort(p, kind="stable"), kind="stable") + 1
        auc_u = (ranks[l > 0.5].sum() - npos * (npos + 1) / 2) / (npos * nneg)
        w = hi - lo
        wauc_sum += auc_u * w
        weight_sum += w
        user_count += 1
    return wauc_sum, weight_sum, user_count


def wuauc_compute(user_ids: np.ndarray, preds: np.ndarray,
                  labels: np.ndarray) -> Dict[str, float]:
    """Per-user (weighted-user) AUC on host (role of WuAucMetricMsg,
    metrics.h:306 / ``computeWuAuc``): group records by user, compute AUC
    per user with >=1 pos and >=1 neg, average weighted by instance count."""
    wauc_sum, weight_sum, user_count = wuauc_accumulate(user_ids, preds,
                                                        labels)
    return {
        "wuauc": wauc_sum / weight_sum if weight_sum else float("nan"),
        "wuauc_users": float(user_count),
    }
