"""Utilities: profiler, dump writers."""

from paddlebox_tpu.utils.profiler import Profiler, profile_pass
from paddlebox_tpu.utils.dump import DumpWriter

__all__ = ["DumpWriter", "Profiler", "profile_pass"]
