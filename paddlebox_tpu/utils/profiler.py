"""Profiling: XLA trace capture + pass-stage timers.

Role of the reference profiler stack (SURVEY.md §5): structured
``paddle.profiler.Profiler`` (host tracer + CUPTI → chrome trace,
``platform/profiler/``) and the hand-rolled hot-path timers printed by
``PrintSyncTimer`` (``box_wrapper.h:395-420``) / ``TrainFilesWithProfiler``.

TPU-first: device-side tracing is ``jax.profiler`` (TensorBoard/XPlane
format — the TPU equivalent of the chrome trace, viewable in
tensorboard or Perfetto); host-side stage attribution reuses
``core.timers.TimerGroup``; ``annotate`` marks named regions
(``TraceAnnotation``) that show up inside the device trace.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax

from paddlebox_tpu.core import log, timers


class Profiler:
    """start()/stop() trace capture + named step annotations.

    Usage:
        prof = Profiler(logdir="/tmp/trace")
        prof.start()
        with prof.step(3):
            loss = train_step(...)
        prof.stop()
    """

    def __init__(self, logdir: str = "/tmp/pbx_profile"):
        self.logdir = logdir
        self._active = False
        self.timers = timers.TimerGroup()

    def start(self) -> None:
        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)
        self._active = True
        log.vlog(0, "profiler: tracing to %s", self.logdir)

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            log.vlog(0, "profiler: trace written to %s", self.logdir)

    @contextlib.contextmanager
    def step(self, step_num: Optional[int] = None) -> Iterator[None]:
        # One aggregated host timer for all steps; per-step attribution
        # lives in the device trace via the step annotation.
        with jax.profiler.StepTraceAnnotation(
                "train", step_num=step_num or 0):
            with self.timers.scope("step"):
                yield

    @contextlib.contextmanager
    def annotate(self, name: str) -> Iterator[None]:
        """Named region visible in the device trace (role of the
        RecordEvent host annotations)."""
        with jax.profiler.TraceAnnotation(name):
            with self.timers.scope(name):
                yield

    def report(self) -> str:
        return self.timers.report()


@contextlib.contextmanager
def profile_pass(logdir: str, *, enabled: bool = True) -> Iterator[Optional[Profiler]]:
    """Trace one whole pass (role of TrainFilesWithProfiler gating)."""
    if not enabled:
        yield None
        return
    prof = Profiler(logdir)
    prof.start()
    try:
        yield prof
    finally:
        prof.stop()
