"""Numeric sanitizer: NaN/Inf detection over pytrees with named reports.

Role of the reference's ``FLAGS_check_nan_inf`` machinery
(``framework/details/nan_inf_utils_detail.{cc,cu}``): after each batch the
worker scans every scope tensor (``CheckBatchNanOrInfRet`` hooked at
``boxps_worker.cc:699-707``), and on a hit dumps the scope and aborts with
the offending variable names.

TPU-first: the scan is a jitted reduction per leaf (one ``isfinite.all()``
fused into the step when used inside jit); reporting walks the pytree on
host only after a hit, so the hot path stays collective-free and cheap.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import flags, log


def all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every leaf of the pytree is finite. Jit-friendly —
    compose into the train step (role of CheckBatchNanOrInfRet)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if isinstance(x, (jax.Array, np.ndarray))
              and jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)]
    ok = jnp.asarray(True)
    for leaf in leaves:
        ok &= jnp.isfinite(leaf).all()
    return ok


def find_nonfinite(tree: Any) -> List[Tuple[str, str, int]]:
    """Host-side report: [(path, kind, count)] for each offending leaf
    (role of the per-variable PrintNanInf dump). Call only after
    ``all_finite`` came back False — it materializes every leaf."""
    out: List[Tuple[str, str, int]] = []
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.inexact):
            continue
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        name = jax.tree_util.keystr(path)
        if n_nan:
            out.append((name, "nan", n_nan))
        if n_inf:
            out.append((name, "inf", n_inf))
    return out


def check_batch(tree: Any, *, step: int = -1, raise_on_hit: bool = True,
                force: bool = False) -> bool:
    """Post-batch host check honoring the ``check_nan_inf`` flag (or
    ``force=True`` from a per-trainer switch): returns True when clean; on
    a hit logs the per-leaf report and (by default) raises — matching the
    reference's abort-with-dump behavior."""
    if not force and not flags.flag("check_nan_inf"):
        return True
    if bool(all_finite(tree)):
        return True
    report = find_nonfinite(tree)
    for name, kind, count in report:
        log.error("nan_inf[step %d]: %s has %d %s values", step, name,
                  count, kind)
    if raise_on_hit:
        raise FloatingPointError(
            f"non-finite values at step {step}: "
            + ", ".join(f"{n}({k}x{c})" for n, k, c in report))
    return False
