"""Program introspection: jaxpr/HLO views, compile stats, tensor printer.

Roles from the reference (SURVEY.md §2.1/§2.7): the IR pass framework's
graph views (``framework/ir/graph.h`` — here the jaxpr IS the graph and
XLA owns the passes, so the useful equivalent is *inspection*), the CINN
compiler bridge's compiled-subgraph stats (``paddle2cinn/cinn_compiler``),
and ``lodtensor_printer`` (per-tensor debug summaries pulled from scopes).

TPU-first: everything reads from JAX's own artifacts — ``make_jaxpr`` for
the traced graph, ``lower().as_text()`` for HLO, and the compiled
executable's memory/cost analyses for what XLA actually scheduled.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from paddlebox_tpu.core import log


def jaxpr_summary(fn: Callable, *args, **kw) -> Dict[str, int]:
    """Count of equations by primitive in the traced program (the op-level
    graph view the IR passes of the reference operate on)."""
    jaxpr = jax.make_jaxpr(fn, **kw)(*args)

    def subjaxprs(p):
        # Anything exposing .eqns is a traversable program: plain Jaxpr
        # (shard_map's param) and ClosedJaxpr (scan/pjit/cond branches —
        # its .eqns property forwards to the inner jaxpr) both qualify.
        items = p if isinstance(p, (tuple, list)) else (p,)
        for item in items:
            if hasattr(item, "eqns"):
                yield item

    def walk(jx) -> Counter:
        c: Counter = Counter()
        for eqn in jx.eqns:
            c[eqn.primitive.name] += 1
            for p in eqn.params.values():
                for inner in subjaxprs(p):
                    c += walk(inner)
        return c

    return dict(walk(jaxpr.jaxpr))


def hlo_text(fn: Callable, *args, dialect: str = "stablehlo") -> str:
    """Lowered program text (what the reference would dump from its
    compiled subgraphs / CINN bridge)."""
    return jax.jit(fn).lower(*args).as_text(dialect)


def compiled_stats(fn: Callable, *args) -> Dict[str, Any]:
    """Post-compilation facts from XLA: memory analysis (bytes by class)
    and cost analysis (flops etc.) when the backend provides them."""
    compiled = jax.jit(fn).lower(*args).compile()
    out: Dict[str, Any] = {}
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            for f in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "generated_code_size_in_bytes"):
                v = getattr(mem, f, None)
                if v is not None:
                    out[f] = int(v)
    except Exception:  # backend-dependent
        pass
    try:
        cost = compiled.cost_analysis()
        if cost:
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            for k in ("flops", "bytes accessed"):
                if k in c:
                    out[k.replace(" ", "_")] = float(c[k])
    except Exception:
        pass
    return out


def print_tensor(x, name: str = "tensor", *, max_vals: int = 8) -> str:
    """One-line tensor debug summary (role of lodtensor_printer's
    PrintVar): shape/dtype/min/mean/max/nonfinite + leading values.
    Returns the line (and logs it)."""
    arr = np.asarray(x)
    if arr.size == 0:
        line = f"{name}: shape={arr.shape} dtype={arr.dtype} <empty>"
    elif np.issubdtype(arr.dtype, np.number):
        flat = arr.ravel()
        head = np.array2string(flat[:max_vals], precision=4,
                               separator=",", threshold=max_vals)
        nonfinite = (int(np.size(flat) - np.isfinite(flat).sum())
                     if np.issubdtype(arr.dtype, np.inexact) else 0)
        line = (f"{name}: shape={arr.shape} dtype={arr.dtype} "
                f"min={flat.min():.6g} mean={flat.mean():.6g} "
                f"max={flat.max():.6g} nonfinite={nonfinite} head={head}")
    else:
        line = f"{name}: shape={arr.shape} dtype={arr.dtype}"
    log.vlog(0, "%s", line)
    return line
