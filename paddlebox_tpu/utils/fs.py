"""Filesystem abstraction: local posix + HDFS/AFS via shell client.

Role of the reference's ``paddle/fluid/framework/io/fs.{cc,h}``: one
interface over local files and HDFS, where HDFS access shells out to the
``hadoop fs`` CLI through popen pipes (``fs.cc:224-244``, ``shell_popen``
``fs.cc:69``) — used by dump writers (``boxps_trainer.cc:110``), dataset
readers (``pipe_command``), and the checkpoint save paths; plus the boxps
``PaddleFileMgr`` AFS client (``box_wrapper.h:716``).

TPU-first/neutral: same split — :class:`LocalFS` is plain python IO;
:class:`HadoopFS` drives a configurable CLI (``hadoop fs`` by default, so
an ``afs``/``gsutil``-style tool can swap in). Scheme-based routing via
:func:`fs_for`: paths like ``hdfs://...`` or ``afs://...`` pick the shell
client, everything else is local.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import IO, List, Optional

from paddlebox_tpu.core import log


class FS:
    """Interface (role of the fs.h function table)."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def ls(self, path: str) -> List[str]:
        raise NotImplementedError

    def open_read(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def open_write(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def put(self, local_path: str, remote_path: str) -> None:
        raise NotImplementedError

    def get(self, remote_path: str, local_path: str) -> None:
        raise NotImplementedError


class LocalFS(FS):
    """Plain posix IO (role of the local_* half of fs.cc)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def ls(self, path: str) -> List[str]:
        return sorted(os.path.join(path, n) for n in os.listdir(path))

    def open_read(self, path: str) -> IO[bytes]:
        return open(path, "rb")

    def open_write(self, path: str) -> IO[bytes]:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return open(path, "wb")

    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def put(self, local_path: str, remote_path: str) -> None:
        if os.path.abspath(local_path) != os.path.abspath(remote_path):
            os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
            shutil.copy(local_path, remote_path)

    def get(self, remote_path: str, local_path: str) -> None:
        self.put(remote_path, local_path)


class _PipeStream:
    """Wraps a CLI subprocess pipe so close() is DURABLE: it waits for the
    process and raises on nonzero exit — otherwise a failed ``-put``
    (quota/permission/network) would silently lose the data, and a
    ``-cat`` of a missing path would read as an empty file."""

    def __init__(self, proc: subprocess.Popen, stream: IO[bytes],
                 desc: str, reading: bool = False):
        self._proc = proc
        self._stream = stream
        self._desc = desc
        self._reading = reading
        self._closed = False

    def read(self, *a) -> bytes:
        return self._stream.read(*a)

    def readline(self, *a) -> bytes:
        return self._stream.readline(*a)

    def write(self, data: bytes) -> int:
        return self._stream.write(data)

    def __iter__(self):
        return iter(self._stream)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._stream.close()
        finally:
            rc = self._proc.wait()
        # Read side: closing before EOF SIGPIPEs the CLI (exit 141/-13) —
        # that's a deliberate partial read, not a failure.
        if self._reading and rc in (141, -13):
            return
        if rc != 0:
            raise IOError(f"{self._desc} failed with exit code {rc}")

    def __enter__(self) -> "_PipeStream":
        return self

    def __exit__(self, et, ev, tb) -> None:
        # Propagate the CLI failure unless an exception is already flying.
        if et is None:
            self.close()
        else:
            try:
                self.close()
            except IOError:
                pass


class HadoopFS(FS):
    """HDFS-family client shelling out to the hadoop CLI (role of the
    hdfs_* half of fs.cc: every op is ``<cmd> fs -<op>`` through a pipe).

    ``command`` is the CLI prefix (default ``hadoop fs``); extra configs
    (ugi, name services) ride in via ``args`` — mirroring the reference's
    ``fs.ugi``-style options passed per call.
    """

    def __init__(self, command: str = "hadoop fs",
                 args: Optional[List[str]] = None, timeout: float = 300.0):
        self._cmd = command.split() + list(args or [])
        self.timeout = timeout

    def _run(self, *op: str, check: bool = True
             ) -> subprocess.CompletedProcess:
        cmd = self._cmd + list(op)
        proc = subprocess.run(cmd, capture_output=True, timeout=self.timeout)
        if check and proc.returncode != 0:
            raise IOError(
                f"{' '.join(cmd)} failed ({proc.returncode}): "
                f"{proc.stderr.decode(errors='replace')[:500]}")
        return proc

    def exists(self, path: str) -> bool:
        return self._run("-test", "-e", path, check=False).returncode == 0

    def ls(self, path: str) -> List[str]:
        out = self._run("-ls", path).stdout.decode()
        paths = []
        for line in out.splitlines():
            parts = line.split()
            # 'hadoop fs -ls' rows end with the path; skip the summary line
            if len(parts) >= 8:
                paths.append(parts[-1])
        return paths

    def open_read(self, path: str) -> IO[bytes]:
        """Streaming read through a pipe (role of hdfs_open_read's
        ``-text``/``-cat`` popen, fs.cc:224). close() raises if the CLI
        failed (e.g. missing path) instead of reading as empty."""
        proc = subprocess.Popen(self._cmd + ["-cat", path],
                                stdout=subprocess.PIPE)
        return _PipeStream(proc, proc.stdout,  # type: ignore[arg-type]
                           f"read {path}",
                           reading=True)  # type: ignore[return-value]

    def open_write(self, path: str) -> IO[bytes]:
        """Streaming write through ``-put - <path>`` (fs.cc:244); close()
        blocks until the upload lands and raises on failure."""
        proc = subprocess.Popen(self._cmd + ["-put", "-f", "-", path],
                                stdin=subprocess.PIPE)
        return _PipeStream(proc, proc.stdin,  # type: ignore[arg-type]
                           f"write {path}")  # type: ignore[return-value]

    def mkdir(self, path: str) -> None:
        self._run("-mkdir", "-p", path)

    def remove(self, path: str) -> None:
        self._run("-rm", "-r", "-f", path)

    def rename(self, src: str, dst: str) -> None:
        self._run("-mv", src, dst)

    def put(self, local_path: str, remote_path: str) -> None:
        self._run("-put", "-f", local_path, remote_path)

    def get(self, remote_path: str, local_path: str) -> None:
        self._run("-get", remote_path, local_path)


_REMOTE_SCHEMES = ("hdfs://", "afs://", "viewfs://")


def fs_for(path: str, *, hadoop_command: str = "hadoop fs",
           hadoop_args: Optional[List[str]] = None) -> FS:
    """Scheme-routed FS selection (role of fs_select in fs.cc)."""
    if path.startswith(_REMOTE_SCHEMES):
        return HadoopFS(hadoop_command, hadoop_args)
    return LocalFS()
