"""Per-line prediction/field dump writer.

Role of the reference dump machinery: ``DeviceWorker::DumpFieldBoxPS`` /
``DumpParamBoxPS`` (``device_worker.cc:511,543``) and the trainer dump
channel writing per-instance prediction lines to HDFS
(``boxps_trainer.cc:102-142``) — used in production to join predictions
back to logs.

TPU-first: a background writer thread drains a channel of formatted
batches; filesystem is pluggable (local file; an fsspec-style writer can
swap in for object stores).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import faults, log, monitor
from paddlebox_tpu.data.channel import Channel, ClosedChannelError


class DumpWriter:
    """Threaded line dump: ``write_batch`` is non-blocking; ``close``
    flushes and joins."""

    def __init__(self, path: str, *, capacity: int = 1024):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._ch: Channel = Channel(capacity)
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._error: Optional[BaseException] = None
        self._thread.start()

    def _writer(self) -> None:
        try:
            with open(self.path, "w") as f:
                while True:
                    try:
                        lines = self._ch.get()
                    except ClosedChannelError:
                        return
                    faults.faultpoint("dump/write")
                    f.write(lines)
                    monitor.add("dump/lines", lines.count("\n"))
        except BaseException as e:
            # Publication is ordered by the channel close below (put
            # raises strictly after _error is set; close() reads after
            # join()), so no lock is needed on either side.
            # graftlint: allow-lock(event-ordered via channel close + join)
            self._error = e
            monitor.add("fault/dump_errors", 1)
            log.warning("dump writer for %s died: %r — the next "
                        "write_batch/close raises it", self.path, e)
            # Close so a blocked producer wakes up (put raises on closed)
            # instead of hanging on a full channel; write_batch re-raises
            # the root cause.
            self._ch.close()

    def write_batch(self, preds: np.ndarray, labels: np.ndarray,
                    valid: Optional[np.ndarray] = None,
                    ins_ids: Optional[Sequence[str]] = None,
                    extra: Optional[Dict[str, np.ndarray]] = None) -> None:
        """Queue one batch of prediction lines:
        ``<ins_id>\\t<pred>\\t<label>[\\t<extra>...]``.

        A writer-thread failure (disk full, IO error) surfaces HERE on
        the next call — with the ORIGINAL exception — not silently at
        close() after an entire pass of dropped lines."""
        if self._error is not None:
            raise self._error
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        n = preds.shape[0]
        rows = []
        for i in range(n):
            if valid is not None and not valid[i]:
                continue
            parts = [ins_ids[i] if ins_ids is not None else str(i),
                     f"{preds[i]:.6f}", f"{labels[i]:g}"]
            if extra:
                parts += [f"{np.asarray(v).reshape(-1)[i]:g}"
                          for v in extra.values()]
            rows.append("\t".join(parts))
        if rows:
            if self._error is not None:
                raise self._error
            try:
                self._ch.put("\n".join(rows) + "\n")
            except ClosedChannelError:
                raise self._error if self._error is not None else \
                    RuntimeError("write_batch after close()")

    def close(self) -> None:
        self._ch.close()
        self._thread.join()
        if self._error is not None:
            raise self._error
        log.vlog(1, "dump closed: %s", self.path)
