"""Automatic mixed precision: bf16 policies + dynamic loss scaling.

Role of the reference AMP stack (SURVEY.md §2.7): static AMP pass
(``fleet/meta_optimizers/amp_optimizer.py``), dygraph ``paddle.amp``, and
the fused C++ AMP ops ``check_finite_and_unscale_op`` /
``update_loss_scaling_op`` (``operators/amp/``).

TPU-first: the native fast dtype is bfloat16, whose fp32-sized exponent
makes loss scaling unnecessary for most models — ``Policy("bf16")`` just
casts compute to bf16 and keeps params/updates fp32, and XLA uses the MXU
bf16 path. Dynamic loss scaling is still provided for fp16-style parity
(and for models with bf16-underflowing grads): scale/unscale + global
finite check + growth/backoff, matching update_loss_scaling semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy: cast inputs/compute, keep params and optimizer fp32."""

    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def cast_to_compute(self, tree: Any) -> Any:
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            tree)

    def cast_to_param(self, tree: Any) -> Any:
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            tree)


def bf16_policy() -> Policy:
    return Policy(compute_dtype=jnp.bfloat16)


def _is_bn_node(d: Any) -> bool:
    """True for a dict carrying the full batchnorm_init signature
    (g/b/mean/var, nn/conv.py:37). Shared by the cast and the merge —
    matching on mean+var names alone would misclassify an unrelated
    param group that happens to use those names."""
    return (isinstance(d, dict) and "mean" in d and "var" in d
            and "g" in d and "b" in d)


def cast_compute_except_stats(p: Any,
                              stat_keys: Optional[Tuple[str, ...]] = None
                              ) -> Any:
    """bf16 compute cast over a nested-dict param tree that leaves
    normalization running statistics f32 — casting them would
    re-quantize the EMA every step and defeat an f32 master.

    With the default ``stat_keys=None``, mean/var are preserved only
    inside a full BN node (same _is_bn_node contract as merge_bn_stats)
    so an unrelated param that happens to be named mean/var still gets
    cast. Passing an explicit tuple preserves exactly those keys in ANY
    dict — the caller owns that contract (e.g. a custom stats node with
    no g/b siblings)."""
    bn_gated = stat_keys is None
    keys = ("mean", "var") if bn_gated else stat_keys
    preserve_here = (not bn_gated) or _is_bn_node(p)
    out = {}
    for k, v in p.items():
        if isinstance(v, dict):
            out[k] = cast_compute_except_stats(v, stat_keys)
        elif preserve_here and k in keys:
            out[k] = v
        else:
            out[k] = v.astype(jnp.bfloat16)
    return out


def merge_bn_stats(master: Any, fresh: Any) -> Any:
    """Write a forward pass's BN running-stat updates back into the f32
    master tree (stats are state, not gradients — the optimizer sees
    zero grads for them). BN nodes are identified by _is_bn_node."""
    out = {}
    for k, v in master.items():
        if _is_bn_node(v):
            out[k] = {**v,
                      "mean": fresh[k]["mean"].astype(jnp.float32),
                      "var": fresh[k]["var"].astype(jnp.float32)}
        elif isinstance(v, dict):
            out[k] = merge_bn_stats(v, fresh[k])
        else:
            out[k] = v
    return out


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LossScaleState:
    """Dynamic loss-scale state (role of update_loss_scaling_op):
    scale grows 2x after ``growth_interval`` consecutive finite steps
    (incr_every_n_steps) and backs off after ``backoff_interval``
    consecutive non-finite steps (decr_every_n_nan_or_inf); a non-finite
    step always skips the param update regardless."""

    scale: jax.Array
    growth_tracker: jax.Array
    nonfinite_tracker: jax.Array
    growth_interval: int = 2000
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    backoff_interval: int = 1
    max_scale: float = 2.0 ** 24

    def tree_flatten(self):
        return ((self.scale, self.growth_tracker, self.nonfinite_tracker),
                (self.growth_interval, self.growth_factor,
                 self.backoff_factor, self.backoff_interval, self.max_scale))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(leaves[0], leaves[1], leaves[2], *aux)


def loss_scale_init(initial: float = 2.0 ** 15, **kw) -> LossScaleState:
    return LossScaleState(scale=jnp.float32(initial),
                          growth_tracker=jnp.int32(0),
                          nonfinite_tracker=jnp.int32(0), **kw)


def scale_loss(state: LossScaleState, loss: jax.Array) -> jax.Array:
    return loss * state.scale


def unscale_and_check(state: LossScaleState, grads: Any
                      ) -> Tuple[Any, jax.Array, LossScaleState]:
    """(unscaled grads, all_finite, new state). Apply the update only
    where all_finite (role of check_finite_and_unscale +
    update_loss_scaling)."""
    inv = 1.0 / state.scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    finite = jnp.array(True)
    for g in jax.tree.leaves(grads):
        finite &= jnp.isfinite(g).all()
    new_tracker = jnp.where(finite, state.growth_tracker + 1, 0)
    new_nf = jnp.where(finite, 0, state.nonfinite_tracker + 1)
    grow = new_tracker >= state.growth_interval
    backoff = new_nf >= state.backoff_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grow, jnp.minimum(state.scale * state.growth_factor,
                                    state.max_scale), state.scale),
        jnp.where(backoff, state.scale * state.backoff_factor, state.scale))
    new_tracker = jnp.where(grow, 0, new_tracker)
    new_nf = jnp.where(backoff, 0, new_nf)
    return grads, finite, LossScaleState(
        scale=new_scale, growth_tracker=new_tracker,
        nonfinite_tracker=new_nf,
        growth_interval=state.growth_interval,
        growth_factor=state.growth_factor,
        backoff_factor=state.backoff_factor,
        backoff_interval=state.backoff_interval, max_scale=state.max_scale)


def masked_update(finite: jax.Array, new_tree: Any, old_tree: Any) -> Any:
    """Select new values only when grads were finite (skip-step)."""
    return jax.tree.map(
        lambda n, o: jnp.where(finite, n, o), new_tree, old_tree)
