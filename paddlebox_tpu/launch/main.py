"""Process launcher: ``python -m paddlebox_tpu.launch <opts> script.py``.

Role of the reference launch stack (``python/paddle/distributed/launch/
main.py:18`` + ``controllers/collective.py``): spawn one training process
per host/worker with the cluster env injected, watch them, and restart on
failure (role of ``controllers/watcher.py`` + the elastic manager's
fault-tolerant restart, ``fleet/elastic/manager.py``).

TPU-first: one process per HOST (jax owns all local chips), env contract
``PBX_COORDINATOR/PBX_NUM_PROCESSES/PBX_PROCESS_ID`` consumed by
``paddlebox_tpu.distributed.initialize``. ``--nproc`` spawns N local
processes (useful with forced host-platform device counts for tests).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from paddlebox_tpu.core import log


def build_env(rank: int, world: int, coordinator: str,
              base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(base if base is not None else os.environ)
    env["PBX_COORDINATOR"] = coordinator
    env["PBX_NUM_PROCESSES"] = str(world)
    env["PBX_PROCESS_ID"] = str(rank)
    return env


class Watcher:
    """Spawn + monitor worker processes; restart failed ranks up to
    ``max_restarts`` (role of launch watcher + elastic restart)."""

    def __init__(self, cmds: List[List[str]], envs: List[Dict[str, str]],
                 *, max_restarts: int = 0, poll_sec: float = 0.5):
        self.cmds = cmds
        self.envs = envs
        self.max_restarts = max_restarts
        self.poll_sec = poll_sec
        self.procs: List[Optional[subprocess.Popen]] = [None] * len(cmds)
        self.restarts = [0] * len(cmds)
        # terminate() sets this so run() stops respawning SIGTERM'd ranks
        # (an elastic restart must not race the failure-restart logic).
        self._stopping = False

    def _spawn(self, i: int) -> None:
        self.procs[i] = subprocess.Popen(self.cmds[i], env=self.envs[i])
        log.vlog(0, "launched rank %d (pid %d)", i, self.procs[i].pid)

    def run(self) -> int:
        for i in range(len(self.cmds)):
            self._spawn(i)
        try:
            while True:
                all_done = True
                for i, p in enumerate(self.procs):
                    if p is None:
                        continue
                    ret = p.poll()
                    if ret is None:
                        all_done = False
                        continue
                    if ret == 0:
                        self.procs[i] = None
                        continue
                    if self._stopping:
                        self.procs[i] = None
                        continue
                    if self.restarts[i] < self.max_restarts:
                        self.restarts[i] += 1
                        log.warning("rank %d exited %d; restart %d/%d", i,
                                    ret, self.restarts[i], self.max_restarts)
                        self._spawn(i)
                        all_done = False
                    else:
                        log.error("rank %d failed (%d); terminating job",
                                  i, ret)
                        self.terminate()
                        return ret
                if all_done:
                    return 0
                time.sleep(self.poll_sec)
        except KeyboardInterrupt:
            self.terminate()
            return 130

    def terminate(self) -> None:
        self._stopping = True
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for p in self.procs:
            if p is None:
                continue
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()


def run_elastic(args) -> int:
    """Elastic mode: membership from an ElasticManager over a shared dir;
    rank/world derive from the published rank table and workers restart on
    membership generation changes (role of `paddle.distributed.run
    --elastic` wiring ElasticManager into the launch controllers)."""
    import socket
    import threading

    from paddlebox_tpu.launch.elastic import ElasticManager

    host_id = args.host_id or socket.gethostname()
    em = ElasticManager(args.elastic_dir, host_id,
                        min_hosts=args.min_hosts, max_hosts=args.max_hosts)
    em.start()
    try:
        while True:
            try:
                table = em.wait_for_quorum(timeout=args.elastic_timeout)
            except TimeoutError:
                log.error("elastic: quorum of %d hosts not reached in %.0fs",
                          args.min_hosts, args.elastic_timeout)
                return 3
            gen = table.generation
            host_rank = table.rank_of(host_id)
            world = table.world_size * args.nproc
            cmds, envs = [], []
            for i in range(args.nproc):
                rank = host_rank * args.nproc + i
                cmds.append([sys.executable, args.script] + args.script_args)
                env = build_env(rank, world, args.coordinator)
                env["PBX_ELASTIC_GENERATION"] = str(gen)
                envs.append(env)
            log.vlog(0, "elastic gen %d: host %s rank %d world %d", gen,
                     host_id, host_rank, world)
            watcher = Watcher(cmds, envs, max_restarts=args.max_restarts)
            result: List[Optional[int]] = [None]
            t = threading.Thread(target=lambda: result.__setitem__(
                0, watcher.run()), daemon=True)
            t.start()
            while t.is_alive():
                t.join(0.5)
                cur = em.current_table()
                if cur is not None and cur.generation != gen:
                    log.warning("elastic: membership gen %d -> %d; "
                                "restarting workers", gen, cur.generation)
                    watcher.terminate()
                    t.join(10.0)
                    break
            else:
                return result[0] if result[0] is not None else 1
            # membership changed: loop — wait for the new table and relaunch
    finally:
        em.stop()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddlebox_tpu.launch",
        description="launch distributed training processes")
    ap.add_argument("--nproc", type=int, default=1,
                    help="local processes to spawn (hosts in prod: 1)")
    ap.add_argument("--coordinator", default="127.0.0.1:8476",
                    help="coordinator address for jax.distributed")
    ap.add_argument("--rank-offset", type=int, default=0,
                    help="global rank of this host's first process")
    ap.add_argument("--world-size", type=int, default=0,
                    help="total processes across hosts (default: nproc)")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="per-rank restart budget on failure (elastic)")
    ap.add_argument("--elastic-dir", default="",
                    help="shared dir for elastic membership (enables "
                         "elastic mode: ranks come from the lease table)")
    ap.add_argument("--host-id", default="",
                    help="elastic host identity (default: hostname)")
    ap.add_argument("--min-hosts", type=int, default=1,
                    help="elastic quorum size")
    ap.add_argument("--max-hosts", type=int, default=0,
                    help="elastic max hosts (0 = unbounded)")
    ap.add_argument("--elastic-timeout", type=float, default=300.0,
                    help="seconds to wait for elastic quorum")
    ap.add_argument("script", help="training script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.elastic_dir:
        return run_elastic(args)

    world = args.world_size or args.nproc
    cmds, envs = [], []
    for i in range(args.nproc):
        rank = args.rank_offset + i
        cmds.append([sys.executable, args.script] + args.script_args)
        envs.append(build_env(rank, world, args.coordinator))
    return Watcher(cmds, envs, max_restarts=args.max_restarts).run()


if __name__ == "__main__":
    sys.exit(main())
