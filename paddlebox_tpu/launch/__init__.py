"""Launch CLI package (role of python -m paddle.distributed.launch)."""
