from paddlebox_tpu.launch.main import main
import sys

sys.exit(main())
