"""Elastic membership manager: lease heartbeats, scale watch, rank reassign.

Role of the reference ``ElasticManager`` (``fleet/elastic/manager.py:131``):
host heartbeats through etcd leases (:236), watch callbacks on scale in/out
(:443), fault-tolerant rank reassignment rewriting the trainer rank table,
and restart hooks; plus the launch watcher restarting dead ranks.

TPU-first/infra-neutral: the coordination substrate is a shared directory
(NFS/GCS-fuse — the same trick as the reference's Gloo HdfsStore rendezvous,
``gloo_wrapper.h:53``) instead of etcd: each host touches a heartbeat file
every ``heartbeat_interval``; membership = files fresher than ``timeout``.
The lexicographically-first alive host acts as leader and publishes a new
generation of the rank table when stable membership changes; every host
polls the table and fires the registered callback so training can restart
from the last published base+delta checkpoint
(:mod:`paddlebox_tpu.checkpoint.protocol` ``recovery_chain``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from paddlebox_tpu.core import log


@dataclasses.dataclass
class RankTable:
    """One membership generation: host id → contiguous rank.

    ``meta`` carries each member's self-advertised metadata (host id →
    dict), published with the table by the leader from the heartbeat
    payloads — the multi-host shard tier rides it to announce each
    host's ``shard_endpoint`` so peers can (re)build the
    :class:`~paddlebox_tpu.multihost.keyrange.ShardRangeTable` client
    set after a membership change without a second rendezvous."""

    generation: int
    hosts: List[str]                  # sorted; index = rank
    meta: Dict[str, Dict] = dataclasses.field(default_factory=dict)

    def rank_of(self, host_id: str) -> Optional[int]:
        try:
            return self.hosts.index(host_id)
        except ValueError:
            return None

    @property
    def world_size(self) -> int:
        return len(self.hosts)


def read_rank_table(root: str) -> Optional[RankTable]:
    """Read-only view of the published rank table under ``root`` (None
    when missing/torn — the writer replaces atomically, so a parse
    failure is a race, not corruption). Non-member observers use this
    for discovery: the serving fleet router reads the per-host ``meta``
    for ``serving_endpoint`` advertisements WITHOUT joining membership
    itself, the same way shard clients rebuild their endpoint set from
    ``shard_endpoint`` meta."""
    try:
        with open(os.path.join(root, "ranktable.json")) as f:
            d = json.load(f)
        return RankTable(generation=d["generation"], hosts=d["hosts"],
                         meta=d.get("meta", {}))
    except (OSError, ValueError, KeyError):
        return None


class ElasticManager:
    """Directory-lease membership + leader-published rank table."""

    def __init__(self, root: str, host_id: str, *,
                 min_hosts: int = 1, max_hosts: int = 0,
                 heartbeat_interval: float = 0.5, timeout: float = 2.0,
                 settle: float = 0.5,
                 on_change: Optional[Callable[[RankTable], None]] = None,
                 meta: Optional[Dict] = None):
        self.root = root
        self.host_id = host_id
        # This host's advertised metadata (e.g. its shard-server
        # endpoint); rides every heartbeat and lands in the published
        # rank table's per-host ``meta``. Mutable via set_meta().
        self.meta: Dict = dict(meta or {})
        self.min_hosts = min_hosts
        self.max_hosts = max_hosts      # 0 = unbounded
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        self.settle = settle
        self.on_change = on_change
        self._hb_dir = os.path.join(root, "hosts")
        os.makedirs(self._hb_dir, exist_ok=True)
        self._running = False
        self._threads: List[threading.Thread] = []
        self._table: Optional[RankTable] = None
        self._table_lock = threading.Lock()

    # -- heartbeat lease ---------------------------------------------------

    def _hb_path(self, host: str) -> str:
        return os.path.join(self._hb_dir, host)

    def set_meta(self, **meta) -> None:
        """Update this host's advertised metadata (picked up by the
        next heartbeat and the next published table generation)."""
        self.meta.update(meta)

    def _beat(self) -> None:
        path = self._hb_path(self.host_id)
        # Atomic replace: the leader READS peer heartbeats for their
        # meta payload, and a torn json would drop a host's endpoint
        # from the published table.
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"ts": time.time(), "meta": self.meta}, f)
        os.replace(tmp, path)

    def _peer_meta(self, hosts: List[str]) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for h in hosts:
            try:
                with open(self._hb_path(h)) as f:
                    d = json.load(f)
                m = d.get("meta", {})
                if isinstance(m, dict) and m:
                    out[h] = m
            except (OSError, ValueError):
                continue  # legacy plain-timestamp beat or mid-replace
        return out

    def alive_hosts(self) -> List[str]:
        """Hosts with a fresh heartbeat (capped at max_hosts by sorted
        order, matching the reference's np scale bounds)."""
        now = time.time()
        alive = []
        for name in os.listdir(self._hb_dir):
            try:
                if now - os.path.getmtime(self._hb_path(name)) < self.timeout:
                    alive.append(name)
            except OSError:
                continue
        alive.sort()
        if self.max_hosts:
            alive = alive[:self.max_hosts]
        return alive

    # -- rank table --------------------------------------------------------

    def _table_path(self) -> str:
        return os.path.join(self.root, "ranktable.json")

    def _read_table(self) -> Optional[RankTable]:
        return read_rank_table(self.root)

    def _publish_table(self, hosts: List[str]) -> None:
        prev = self._read_table()
        gen = (prev.generation + 1) if prev else 0
        tmp = self._table_path() + f".{self.host_id}.tmp"
        with open(tmp, "w") as f:
            json.dump({"generation": gen, "hosts": hosts,
                       "meta": self._peer_meta(hosts),
                       "ts": time.time()}, f)
        os.replace(tmp, self._table_path())
        log.vlog(0, "elastic: leader %s published gen %d hosts=%s",
                 self.host_id, gen, hosts)

    def current_table(self) -> Optional[RankTable]:
        with self._table_lock:
            return self._table

    def current_rank(self) -> Optional[int]:
        t = self.current_table()
        return t.rank_of(self.host_id) if t else None

    def is_leader(self) -> bool:
        alive = self.alive_hosts()
        return bool(alive) and alive[0] == self.host_id

    # -- watch loops -------------------------------------------------------

    def _hb_loop(self) -> None:
        while self._running:
            self._beat()
            time.sleep(self.heartbeat_interval)

    def _watch_loop(self) -> None:
        pending: Optional[List[str]] = None
        pending_since = 0.0
        while self._running:
            time.sleep(self.heartbeat_interval / 2)
            alive = self.alive_hosts()
            if len(alive) < self.min_hosts:
                continue  # below quorum: hold the old table (ref :443 wait)
            published = self._read_table()
            cur_hosts = published.hosts if published else None
            if alive != cur_hosts:
                # Require membership stable for `settle` before reranking —
                # a host mid-restart must not trigger two reassignments.
                if pending != alive:
                    pending = alive
                    pending_since = time.time()
                elif time.time() - pending_since >= self.settle:
                    if self.is_leader():
                        self._publish_table(alive)
                    pending = None
            else:
                pending = None
            # Everyone (leader included) adopts new generations + callback.
            if published is not None:
                with self._table_lock:
                    stale = (self._table is None or
                             self._table.generation != published.generation)
                    self._table = published
                if stale and self.on_change is not None:
                    try:
                        self.on_change(published)
                    except Exception as e:
                        log.error("elastic on_change failed: %s", e)

    def start(self) -> None:
        self._running = True
        self._beat()
        for target in (self._hb_loop, self._watch_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, *, remove_lease: bool = True) -> None:
        self._running = False
        for t in self._threads:
            t.join(self.timeout)
        self._threads.clear()
        if remove_lease:
            try:
                os.unlink(self._hb_path(self.host_id))
            except OSError:
                pass

    def wait_for_quorum(self, timeout: float = 30.0) -> RankTable:
        """Block until a rank table covering >= min_hosts exists and
        includes this host (role of the reference's pod-ready barrier)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            t = self.current_table()
            if t and t.world_size >= self.min_hosts \
                    and t.rank_of(self.host_id) is not None:
                return t
            time.sleep(self.heartbeat_interval / 2)
        raise TimeoutError("elastic quorum not reached")
