"""Elastic live resharding of the host-sharded embedding tier.

When the elastic rank table changes (a host joins or leaves —
``launch/elastic.py``), the key ranges re-draw and the rows whose owner
changed must move. The plan is the MINIMAL-transfer interval overlap
from :func:`~paddlebox_tpu.multihost.keyrange.plan_moves`
("Memory-efficient array redistribution", PAPERS.md): each moved row
crosses the DCN exactly once, rows whose owner is unchanged never move.

Reshard state machine (every resize is a CHECKPOINTED BOUNDARY EVENT —
the controller runs from the day loop's pass-boundary hook, immediately
after that pass's delta published):

    COPY    for each plan segment: ``pull_range`` on the src (read-only
            copy), ``apply_rows`` on the dst (full-row overwrite —
            idempotent, so replays cannot double-apply).
    ADOPT   every server ``set_range`` to the new table; the trainer's
            MultiHostStore switches topology.
    COMMIT  for each segment: ``drop_range`` on the src (now outside
            its range).

A failure (or kill -9) at ANY point rolls back through the PR 5
machinery: shard stores ``reset()`` + the checkpoint protocol's
``recovery_chain()`` reload — and because ``handle_load`` filters rows
by each server's CURRENT range, the reload lands bit-identical in
either the old or the new layout, whichever the cluster is in when it
recovers. Rows are whole-row snapshots keyed by feasign, so recovery
can never double-apply a move (MULTIHOST.md walks the crash windows).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import faults, flags, log, monitor, trace
from paddlebox_tpu.multihost.keyrange import ShardRangeTable, plan_moves
from paddlebox_tpu.multihost.replication import ReplicaMap
from paddlebox_tpu.multihost.shard_service import ShardClient
from paddlebox_tpu.multihost.store import MultiHostStore


def _copy_segment(src: ShardClient, dst: ShardClient, seg,
                  chunk: int) -> int:
    """COPY one plan segment src -> dst. With FLAGS_reshard_chunk_rows
    > 0 the walk is paged into bounded row windows and pipelined TWO
    windows deep: the pull for window k+1 is issued (``call_async`` on
    the PR 16 mux conn) before window k installs on the dst, so the DCN
    pull hides behind the apply and peak memory is two windows instead
    of the whole segment. Every window is a full-row overwrite
    (idempotent) and ``pull_range`` is a pure read, so a kill -9
    mid-walk replays cleanly from the recovery chain."""
    if chunk <= 0:
        rows = src.call("pull_range", lo=str(seg.lo), hi=str(seg.hi))
        n = int(np.asarray(rows["keys"]).shape[0])
        if n:
            dst.call("apply_rows", keys=rows["keys"],
                     values=rows["values"])
        return n
    moved = 0
    fut = src.call_async("pull_range", lo=str(seg.lo),
                         hi=str(seg.hi), limit=chunk)
    while fut is not None:
        rows = fut.result()
        fut = None
        if bool(rows.get("more")):
            fut = src.call_async(
                "pull_range", lo=str(seg.lo), hi=str(seg.hi),
                after=str(int(rows["next_after"])), limit=chunk)
        keys = np.asarray(rows["keys"])
        if keys.shape[0]:
            faults.faultpoint("multihost/reshard_chunk")
            dst.call("apply_rows", keys=keys, values=rows["values"])
            moved += int(keys.shape[0])
        monitor.add("multihost/reshard_chunks", 1)
    return moved


def execute_reshard(old_endpoints: Sequence[str],
                    new_endpoints: Sequence[str],
                    *, old_ranges: Optional[ShardRangeTable] = None,
                    new_ranges: Optional[ShardRangeTable] = None
                    ) -> Dict[str, object]:
    """Run the COPY → ADOPT → COMMIT machine between two endpoint lists
    (hosts present in both keep their index-aligned position; a grown
    tail joins empty, a shrunk tail drains before leaving). Returns the
    audit record: per-segment and total moved-row counts, which tests
    pin against :func:`keyrange.rows_moved_minimal`."""
    old_ranges = old_ranges or ShardRangeTable.for_world(
        len(old_endpoints))
    new_ranges = new_ranges or ShardRangeTable.for_world(
        len(new_endpoints))
    plan = plan_moves(old_ranges, new_ranges)
    # One connection per distinct endpoint across both generations.
    conns: Dict[str, ShardClient] = {}
    for e in list(old_endpoints) + list(new_endpoints):
        if e not in conns:
            conns[e] = ShardClient(e)
    t0 = time.perf_counter()
    moved = 0
    seg_counts: List[int] = []
    try:
        with trace.span("multihost/reshard",
                        old_world=old_ranges.world,
                        new_world=new_ranges.world, segments=len(plan)):
            # COPY: read-only on sources; overwrite-install on dests,
            # in bounded pipelined windows (FLAGS_reshard_chunk_rows).
            chunk = int(flags.flag("reshard_chunk_rows"))
            for seg in plan:
                faults.faultpoint("multihost/reshard_move")
                n = _copy_segment(conns[old_endpoints[seg.src]],
                                  conns[new_endpoints[seg.dst]],
                                  seg, chunk)
                moved += n
                seg_counts.append(n)
            # ADOPT: every server of the NEW generation takes the new
            # table (joining hosts already carry it; survivors re-index).
            for i, e in enumerate(new_endpoints):
                conns[e].call("set_range", table=new_ranges.to_dict(),
                              index=i)
            # COMMIT: sources drop rows now outside their range. A
            # leaving host (not in new_endpoints) drains here too so a
            # later rejoin cannot resurrect stale rows.
            for seg in plan:
                conns[old_endpoints[seg.src]].call(
                    "drop_range", lo=str(seg.lo), hi=str(seg.hi))
    finally:
        for c in conns.values():
            c.close()
    reshard_ms = (time.perf_counter() - t0) * 1e3
    monitor.add("multihost/reshards", 1)
    monitor.add("multihost/reshard_moved_rows", moved)
    return {"moved_rows": moved, "segments": len(plan),
            "segment_rows": seg_counts, "reshard_ms": reshard_ms,
            "old_world": old_ranges.world, "new_world": new_ranges.world}


class ElasticReshardController:
    """Bridges the elastic rank table to the shard tier at pass
    boundaries.

    ``endpoints_of(table)`` maps a
    :class:`~paddlebox_tpu.launch.elastic.RankTable` to the shard-server
    endpoint list in rank order (hosts advertise their endpoint through
    the rank table's per-host ``meta`` — ``launch/elastic.py``).
    ``maybe_apply`` is called from the day loop's pass-boundary hook:
    the pass's delta is already PUBLISHED, so the reshard is a boundary
    event under ``recovery_chain()`` — on any failure the controller
    rolls the shard tier back to that published state and reports the
    resize as not-applied (the next boundary retries); training itself
    never replays a published pass."""

    def __init__(self, store: MultiHostStore, ckpt, *,
                 table_fn=None):
        self.store = store
        self.ckpt = ckpt          # CheckpointProtocol (recovery source)
        self._table_fn = table_fn  # () -> Optional[RankTable]
        self._generation: Optional[int] = None

    @staticmethod
    def endpoints_of(table) -> Optional[List[str]]:
        """Rank-ordered shard endpoints from a RankTable's host meta;
        None while any member has not advertised one yet (a joiner's
        server may still be binding — hold the old topology)."""
        eps = []
        for host in table.hosts:
            ep = (table.meta or {}).get(host, {}).get("shard_endpoint")
            if not ep:
                return None
            eps.append(ep)
        return eps

    def maybe_apply(self, day: str, pass_id: int) -> Optional[Dict]:
        """Adopt a new rank-table generation if one is pending. Returns
        the reshard audit record when a resize ran, None otherwise.
        A REPLICATED store (FLAGS_multihost_replicas > 1) never
        re-draws bounds here: membership changes route to the
        promote/re-replicate repair path instead (fixed slot count,
        endpoints move — MULTIHOST.md "failover repair")."""
        table = self._table_fn() if self._table_fn else None
        if table is None:
            return None
        if self._generation is None:
            # First observation anchors the generation — the initial
            # topology was built from it, nothing to move.
            self._generation = table.generation
            return None
        if table.generation == self._generation:
            return None
        if self.store.replica_map is not None:
            rec = self._maybe_repair(table)
            if rec is not None:
                self._generation = table.generation
            return rec
        new_eps = self.endpoints_of(table)
        if new_eps is None:
            return None
        faults.faultpoint("multihost/ranktable_apply")
        old_eps = list(self.store.endpoints)
        old_ranges = self.store.ranges
        new_ranges = ShardRangeTable.for_world(len(new_eps))
        log.vlog(0, "multihost: rank table gen %s -> %s (world %d -> "
                 "%d) at day %s pass %s boundary", self._generation,
                 table.generation, old_ranges.world, new_ranges.world,
                 day, pass_id)
        try:
            rec = execute_reshard(old_eps, new_eps,
                                  old_ranges=old_ranges,
                                  new_ranges=new_ranges)
            self.store.set_topology(new_eps, new_ranges)
        except Exception as e:
            # Boundary-event rollback: the pass that just finished is
            # published, so reloading the recovery chain restores the
            # shard tier bit-identical; the resize retries at the next
            # boundary instead of poisoning training.
            monitor.add("multihost/reshard_errors", 1)
            log.warning("multihost: reshard to gen %s failed (%r) — "
                        "rolling back via recovery_chain",
                        table.generation, e)
            trace.instant("multihost/reshard_rollback",
                          generation=table.generation, error=repr(e))
            self._rollback()
            return None
        self._generation = table.generation
        return rec

    def _rollback(self) -> None:
        base, deltas = self.ckpt.recovery_chain()
        self.store.reset()
        if base is not None:
            self.store.load(base.path, "base")
        for d in deltas:
            self.store.load(d.path, "delta")

    # -- replicated-tier failover repair -----------------------------------
    #
    # Bounds NEVER re-draw on host loss: the dead endpoint is dropped
    # from the ReplicaMap (its slots fall to their first surviving
    # backup — PROMOTION, a role flip on a server that already holds
    # the rows), and replication is restored by snapshotting each
    # thinned slot to a fresh host (RE-REPLICATION) — so the repair
    # transfer is bounded by the dead host's R slots, never a
    # whole-table reshuffle. COPY = the primary's replica snapshot
    # (idempotent overwrite), ADOPT = set_replication everywhere +
    # client set_replica_map, COMMIT = adopt dropping stale roles.

    @staticmethod
    def _probe(endpoint: str, timeout: float = 2.0) -> bool:
        """Is a shard server answering at this endpoint? Connection
        refused/reset/timeout = dead (loopback refuses instantly; a
        hung host costs `timeout`)."""
        try:
            c = ShardClient(endpoint, timeout=timeout)
        except (OSError, ConnectionError):
            return False
        try:
            c.call("stats")
            return True
        except (OSError, ConnectionError, RuntimeError):
            return False
        finally:
            c.close()

    def _adopt_map(self, rmap: ReplicaMap) -> None:
        """ADOPT on every live server of the new map, then the client.
        Per-server adoption is idempotent, so a crash between servers
        re-runs cleanly at the next repair attempt."""
        for ep in rmap.all_endpoints():
            c = ShardClient(ep)
            try:
                c.call("set_replication", map=rmap.to_dict())
            finally:
                c.close()
        self.store.set_replica_map(rmap)

    def repair(self, *, reason: str = "") -> Optional[Dict]:
        """PROMOTION half of the failover repair, callable from the
        pass-retry hook (DayRunner ``pass_retry_hook``): probe the
        current map's endpoints, drop the dead ones (each dead
        primary's slot falls to its first live backup), adopt the
        thinned map on the survivors and the client. Re-replication to
        a fresh host happens at the next checkpointed boundary
        (``maybe_apply`` → ``_maybe_repair``), so a mid-pass repair
        never moves rows — it only re-points endpoints. Returns the
        audit record, or None when every endpoint answered."""
        rmap = self.store.replica_map
        if rmap is None:
            return None
        dead = [ep for ep in rmap.all_endpoints()
                if not self._probe(ep)]
        if not dead:
            return None
        faults.faultpoint("multihost/ranktable_apply")
        t0 = time.perf_counter()
        promoted: List[int] = []
        new_map = rmap
        for ep in dead:
            before = new_map.primaries()
            new_map = new_map.drop_endpoint(ep)
            promoted += [s for s, (a, b) in enumerate(
                zip(before, new_map.primaries())) if a != b]
        with trace.span("multihost/repair", dead=len(dead),
                        promoted=len(promoted)):
            self._adopt_map(new_map)
        repair_ms = (time.perf_counter() - t0) * 1e3
        monitor.add("multihost/repairs", 1)
        log.warning("multihost: PROMOTED %d slot(s) off dead host(s) %s "
                    "in %.0f ms%s — replication %d until re-replication",
                    len(promoted), dead, repair_ms,
                    f" ({reason})" if reason else "",
                    new_map.replication)
        return {"kind": "promote", "dead": dead, "promoted": promoted,
                "repair_ms": repair_ms,
                "replication": new_map.replication}

    def _maybe_repair(self, table) -> Optional[Dict]:
        """Boundary-hook half: fold a new rank-table generation into
        the replica map — drop members that left (promotion, if the
        retry hook didn't already), then restore the replication factor
        by snapshotting thinned slots to advertised hosts not yet in
        the map. Failures leave the generation un-adopted (retried next
        boundary); every step is an idempotent overwrite."""
        rmap = self.store.replica_map
        live = self.endpoints_of(table)
        if live is None:
            return None            # a joiner has not advertised yet
        faults.faultpoint("multihost/ranktable_apply")
        t0 = time.perf_counter()
        promoted: List[int] = []
        repaired: List[int] = []
        try:
            new_map = rmap
            for ep in [e for e in rmap.all_endpoints() if e not in live]:
                before = new_map.primaries()
                new_map = new_map.drop_endpoint(ep)
                promoted += [s for s, (a, b) in enumerate(
                    zip(before, new_map.primaries())) if a != b]
            # RE-REPLICATION: thinned slots take fresh backups from
            # hosts not yet replicating them (round-robin over the
            # advertised endpoints, distinct-host invariant preserved
            # by add_backup's duplicate check).
            want = self.store._replicas
            fresh = [e for e in live if e not in new_map.all_endpoints()]
            pool = fresh + [e for e in live
                            if e in new_map.all_endpoints()]
            for slot in range(new_map.world):
                i = 0
                while len(new_map.replicas_of(slot)) < want and pool:
                    cand = pool[i % len(pool)]
                    i += 1
                    if i > 2 * len(pool):
                        break     # nobody eligible (all already listed)
                    if cand in new_map.replicas_of(slot):
                        continue
                    new_map = new_map.add_backup(slot, cand)
                    repaired.append(slot)
            if new_map is rmap:
                return {"kind": "noop", "repair_ms": 0.0,
                        "replication": rmap.replication}
            with trace.span("multihost/repair",
                            promoted=len(promoted),
                            repaired=len(repaired)):
                self._adopt_map(new_map)
                # COPY: bring every repaired slot's new backups to the
                # journal head (full snapshot for a fresh host).
                self.store.sync_replicas()
        except Exception as e:
            monitor.add("multihost/repair_errors", 1)
            log.warning("multihost: replica repair failed (%r) — "
                        "retrying at the next boundary", e)
            trace.instant("multihost/repair_rollback", error=repr(e))
            return None
        repair_ms = (time.perf_counter() - t0) * 1e3
        monitor.add("multihost/repairs", 1)
        log.vlog(0, "multihost: repair promoted=%s re-replicated=%s in "
                 "%.0f ms — replication factor %d", promoted,
                 sorted(set(repaired)), repair_ms, new_map.replication)
        return {"kind": "repair", "promoted": promoted,
                "repaired": sorted(set(repaired)),
                "repair_ms": repair_ms,
                "replication": new_map.replication}
