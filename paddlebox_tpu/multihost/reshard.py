"""Elastic live resharding of the host-sharded embedding tier.

When the elastic rank table changes (a host joins or leaves —
``launch/elastic.py``), the key ranges re-draw and the rows whose owner
changed must move. The plan is the MINIMAL-transfer interval overlap
from :func:`~paddlebox_tpu.multihost.keyrange.plan_moves`
("Memory-efficient array redistribution", PAPERS.md): each moved row
crosses the DCN exactly once, rows whose owner is unchanged never move.

Reshard state machine (every resize is a CHECKPOINTED BOUNDARY EVENT —
the controller runs from the day loop's pass-boundary hook, immediately
after that pass's delta published):

    COPY    for each plan segment: ``pull_range`` on the src (read-only
            copy), ``apply_rows`` on the dst (full-row overwrite —
            idempotent, so replays cannot double-apply).
    ADOPT   every server ``set_range`` to the new table; the trainer's
            MultiHostStore switches topology.
    COMMIT  for each segment: ``drop_range`` on the src (now outside
            its range).

A failure (or kill -9) at ANY point rolls back through the PR 5
machinery: shard stores ``reset()`` + the checkpoint protocol's
``recovery_chain()`` reload — and because ``handle_load`` filters rows
by each server's CURRENT range, the reload lands bit-identical in
either the old or the new layout, whichever the cluster is in when it
recovers. Rows are whole-row snapshots keyed by feasign, so recovery
can never double-apply a move (MULTIHOST.md walks the crash windows).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import faults, log, monitor, trace
from paddlebox_tpu.multihost.keyrange import ShardRangeTable, plan_moves
from paddlebox_tpu.multihost.shard_service import ShardClient
from paddlebox_tpu.multihost.store import MultiHostStore


def execute_reshard(old_endpoints: Sequence[str],
                    new_endpoints: Sequence[str],
                    *, old_ranges: Optional[ShardRangeTable] = None,
                    new_ranges: Optional[ShardRangeTable] = None
                    ) -> Dict[str, object]:
    """Run the COPY → ADOPT → COMMIT machine between two endpoint lists
    (hosts present in both keep their index-aligned position; a grown
    tail joins empty, a shrunk tail drains before leaving). Returns the
    audit record: per-segment and total moved-row counts, which tests
    pin against :func:`keyrange.rows_moved_minimal`."""
    old_ranges = old_ranges or ShardRangeTable.for_world(
        len(old_endpoints))
    new_ranges = new_ranges or ShardRangeTable.for_world(
        len(new_endpoints))
    plan = plan_moves(old_ranges, new_ranges)
    # One connection per distinct endpoint across both generations.
    conns: Dict[str, ShardClient] = {}
    for e in list(old_endpoints) + list(new_endpoints):
        if e not in conns:
            conns[e] = ShardClient(e)
    t0 = time.perf_counter()
    moved = 0
    seg_counts: List[int] = []
    try:
        with trace.span("multihost/reshard",
                        old_world=old_ranges.world,
                        new_world=new_ranges.world, segments=len(plan)):
            # COPY: read-only on sources; overwrite-install on dests.
            for seg in plan:
                faults.faultpoint("multihost/reshard_move")
                rows = conns[old_endpoints[seg.src]].call(
                    "pull_range", lo=str(seg.lo), hi=str(seg.hi))
                n = int(np.asarray(rows["keys"]).shape[0])
                if n:
                    conns[new_endpoints[seg.dst]].call(
                        "apply_rows", keys=rows["keys"],
                        values=rows["values"])
                moved += n
                seg_counts.append(n)
            # ADOPT: every server of the NEW generation takes the new
            # table (joining hosts already carry it; survivors re-index).
            for i, e in enumerate(new_endpoints):
                conns[e].call("set_range", table=new_ranges.to_dict(),
                              index=i)
            # COMMIT: sources drop rows now outside their range. A
            # leaving host (not in new_endpoints) drains here too so a
            # later rejoin cannot resurrect stale rows.
            for seg in plan:
                conns[old_endpoints[seg.src]].call(
                    "drop_range", lo=str(seg.lo), hi=str(seg.hi))
    finally:
        for c in conns.values():
            c.close()
    reshard_ms = (time.perf_counter() - t0) * 1e3
    monitor.add("multihost/reshards", 1)
    monitor.add("multihost/reshard_moved_rows", moved)
    return {"moved_rows": moved, "segments": len(plan),
            "segment_rows": seg_counts, "reshard_ms": reshard_ms,
            "old_world": old_ranges.world, "new_world": new_ranges.world}


class ElasticReshardController:
    """Bridges the elastic rank table to the shard tier at pass
    boundaries.

    ``endpoints_of(table)`` maps a
    :class:`~paddlebox_tpu.launch.elastic.RankTable` to the shard-server
    endpoint list in rank order (hosts advertise their endpoint through
    the rank table's per-host ``meta`` — ``launch/elastic.py``).
    ``maybe_apply`` is called from the day loop's pass-boundary hook:
    the pass's delta is already PUBLISHED, so the reshard is a boundary
    event under ``recovery_chain()`` — on any failure the controller
    rolls the shard tier back to that published state and reports the
    resize as not-applied (the next boundary retries); training itself
    never replays a published pass."""

    def __init__(self, store: MultiHostStore, ckpt, *,
                 table_fn=None):
        self.store = store
        self.ckpt = ckpt          # CheckpointProtocol (recovery source)
        self._table_fn = table_fn  # () -> Optional[RankTable]
        self._generation: Optional[int] = None

    @staticmethod
    def endpoints_of(table) -> Optional[List[str]]:
        """Rank-ordered shard endpoints from a RankTable's host meta;
        None while any member has not advertised one yet (a joiner's
        server may still be binding — hold the old topology)."""
        eps = []
        for host in table.hosts:
            ep = (table.meta or {}).get(host, {}).get("shard_endpoint")
            if not ep:
                return None
            eps.append(ep)
        return eps

    def maybe_apply(self, day: str, pass_id: int) -> Optional[Dict]:
        """Adopt a new rank-table generation if one is pending. Returns
        the reshard audit record when a resize ran, None otherwise."""
        table = self._table_fn() if self._table_fn else None
        if table is None:
            return None
        if self._generation is None:
            # First observation anchors the generation — the initial
            # topology was built from it, nothing to move.
            self._generation = table.generation
            return None
        if table.generation == self._generation:
            return None
        new_eps = self.endpoints_of(table)
        if new_eps is None:
            return None
        faults.faultpoint("multihost/ranktable_apply")
        old_eps = list(self.store.endpoints)
        old_ranges = self.store.ranges
        new_ranges = ShardRangeTable.for_world(len(new_eps))
        log.vlog(0, "multihost: rank table gen %s -> %s (world %d -> "
                 "%d) at day %s pass %s boundary", self._generation,
                 table.generation, old_ranges.world, new_ranges.world,
                 day, pass_id)
        try:
            rec = execute_reshard(old_eps, new_eps,
                                  old_ranges=old_ranges,
                                  new_ranges=new_ranges)
            self.store.set_topology(new_eps, new_ranges)
        except Exception as e:
            # Boundary-event rollback: the pass that just finished is
            # published, so reloading the recovery chain restores the
            # shard tier bit-identical; the resize retries at the next
            # boundary instead of poisoning training.
            monitor.add("multihost/reshard_errors", 1)
            log.warning("multihost: reshard to gen %s failed (%r) — "
                        "rolling back via recovery_chain",
                        table.generation, e)
            trace.instant("multihost/reshard_rollback",
                          generation=table.generation, error=repr(e))
            self._rollback()
            return None
        self._generation = table.generation
        return rec

    def _rollback(self) -> None:
        base, deltas = self.ckpt.recovery_chain()
        self.store.reset()
        if base is not None:
            self.store.load(base.path, "base")
        for d in deltas:
            self.store.load(d.path, "delta")
