"""Multi-host embedding exchange tier (MULTIHOST.md).

Three connected pieces take the sparse parameter service across hosts:

- :mod:`~paddlebox_tpu.multihost.shard_service` — the host-sharded
  parameter service: one :class:`ShardServer` per host owning a
  contiguous hash range of the key space, framed-RPC pull/push with the
  PR 5 reconnect/retry machinery.
- :mod:`~paddlebox_tpu.multihost.quant` — the int8 per-block wire codec
  shared by the cross-host DCN exchange
  (``FLAGS_multihost_wire_dtype``) and the single-host ICI all_to_all
  (``FLAGS_embedding_exchange_dtype=int8``).
- :mod:`~paddlebox_tpu.multihost.reshard` — elastic live resharding:
  minimal-transfer row moves at a checkpointed pass boundary when the
  elastic rank table changes.
- :mod:`~paddlebox_tpu.multihost.replication` — the replicated tier
  (``FLAGS_multihost_replicas``): per-slot primary+backup placement
  (:class:`ReplicaMap`), the primary's sequence-numbered
  :class:`DeltaJournal` for briefly-disconnected-backup catch-up, and
  the loud-transient :class:`StalePrimaryError` write contract.

:class:`~paddlebox_tpu.multihost.store.MultiHostStore` plugs the tier
into the existing trainer as its backing store
(``CTRTrainer(..., store=...)``): ICI all_to_all within the host stays
in the jitted step; DCN crossings batch to one exchange per peer per
pass boundary.
"""

from paddlebox_tpu.multihost.keyrange import (MoveSegment,  # noqa: F401
                                              ShardRangeTable, mix_keys,
                                              plan_moves,
                                              rows_moved_minimal)
from paddlebox_tpu.multihost.replication import (DeltaJournal,  # noqa: F401
                                                 ReplicaMap,
                                                 StalePrimaryError)
from paddlebox_tpu.multihost.reshard import (ElasticReshardController,  # noqa: F401,E501
                                             execute_reshard)
from paddlebox_tpu.multihost.shard_service import (ShardClient,  # noqa: F401
                                                   ShardServer,
                                                   start_local_shards,
                                                   stop_shards)
from paddlebox_tpu.multihost.store import MultiHostStore  # noqa: F401
