"""Host-sharded parameter service: one shard server per host.

Role of the reference's multi-node sparse tier (the brpc PS cluster the
GPU pass build pulls from, ``ps_gpu_wrapper.cc:362``) re-keyed by the
elastic :class:`~paddlebox_tpu.multihost.keyrange.ShardRangeTable`: each
host runs ONE :class:`ShardServer` owning the keys whose placement hash
lands in its contiguous range, so no host ever holds the full 50M+ key
table. The server speaks the repo's framed typed-wire protocol
(``distributed/wire.py`` — no pickle) through the shared
:class:`~paddlebox_tpu.distributed.rpc.FramedRPCServer` loop, and
clients ride :class:`~paddlebox_tpu.distributed.rpc.FramedRPCConn`'s
reconnect + idempotent-retry machinery (PR 5), so a shard blip on a pure
read costs latency, not the pass.

Replication (``FLAGS_multihost_replicas``, MULTIHOST.md "replicated
tier"): with R > 1 every range SLOT has one primary and R-1 backups on
distinct hosts (:class:`~paddlebox_tpu.multihost.replication.ReplicaMap`).
A server may replicate several slots — each slot's rows live in their
OWN FeatureStore, so promotion is a role flip, not a data move. Writes
(push / apply_rows / shrink) apply on the primary, take the next
sequence number in that slot's
:class:`~paddlebox_tpu.multihost.replication.DeltaJournal`, and forward
synchronously to the backups; a briefly-unreachable backup is marked
lagged and caught up on the next mutation (or an explicit
``sync_replicas``) — journal replay when the gap fits the retained
window, full range snapshot otherwise. Pure reads (pull / pull_serving /
contains) are served by ANY replica of the keys' slot, which is what
lets clients fail over a read to a backup without coordination. A write
reaching a non-primary replica raises a LOUD
:class:`~paddlebox_tpu.multihost.replication.StalePrimaryError`
(transient — the client re-resolves the replica set and retries).
``R == 1`` (the default) never builds a map and every path is
bit-identical to the pre-replication tier.

Wire format (``FLAGS_multihost_wire_dtype``): the ``emb`` field — the
dominant payload — crosses the DCN as f32 (exact, default), f16, or
int8 with per-block f32 scales (``multihost/quant.py``,
``FLAGS_embedding_quant_block``); every other field (w, optimizer
state, show/click) stays f32, and the receiver widens BEFORE anything
accumulates or persists. Reshard row moves and replica
forwards/snapshots always travel f32: they relocate training state,
which must arrive bit-identical.

Checkpoint layout: ``<path>/hostshard-<slot>/<table>.<kind>.npz`` per
PRIMARY slot (backups never save — their primary does), plus the
``.ages.npz`` sidecar carrying per-row unseen-days TTL ages (ONLINE.md).
``load`` is WORLD-AGNOSTIC: every server scans all hostshard dirs (and
a flat single-host dump — migration), keeping only rows in the ranges
of the slots it currently replicates — so a checkpoint written at world
W recovers cleanly into world W', which is what makes a crashed reshard
rollback safe (MULTIHOST.md, "reshard state machine").
"""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import (faults, flags, incident, log, monitor,
                                timeseries, trace)
from paddlebox_tpu.distributed import rpc, wire
from paddlebox_tpu.embedding.store import _FIELDS, FeatureStore
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import quant
from paddlebox_tpu.multihost.keyrange import ShardRangeTable
from paddlebox_tpu.multihost.replication import (DeltaJournal, ReplicaMap,
                                                 StalePrimaryError)

_SPAN = 1 << 64

# Backup-slot epoch while a CHUNKED replica snapshot is mid-stream.
# Never equals a real journal epoch, so a crash between chunks makes the
# next catch-up negotiation fall back to a fresh full snapshot.
_SNAPSHOT_PARTIAL = "~snapshot-partial~"


def wire_mode() -> str:
    mode = flags.flag("multihost_wire_dtype")
    if mode not in ("f32", "f16", "int8"):
        raise ValueError(
            f"unknown multihost_wire_dtype {mode!r} "
            "(want 'f32'/'f16'/'int8')")
    return mode


def encode_emb(emb: np.ndarray, mode: str) -> Dict[str, np.ndarray]:
    """Encode the emb payload for the DCN wire. f32 passes the array
    through UNTOUCHED (the exact path must stay bit-identical)."""
    if mode == "f32":
        return {"emb": emb}
    if mode == "f16":
        return {"emb_f16": np.asarray(emb, np.float32).astype(np.float16)}
    q, scales = quant.quantize_blocked_np(
        emb, int(flags.flag("embedding_quant_block")))
    return {"emb_q": q, "emb_scale": scales,
            "emb_width": np.asarray([emb.shape[1]], np.int64)}


def decode_emb(payload: Dict[str, np.ndarray]) -> np.ndarray:
    """Widen a wire emb payload back to f32 (the only dtype anything
    downstream accumulates or persists in)."""
    if "emb" in payload:
        return payload["emb"]
    if "emb_f16" in payload:
        return payload["emb_f16"].astype(np.float32)
    width = int(payload["emb_width"][0])
    return quant.dequantize_blocked_np(
        payload["emb_q"], payload["emb_scale"], width,
        int(flags.flag("embedding_quant_block")))


def payload_nbytes(payload: Dict[str, np.ndarray]) -> int:
    return int(sum(np.asarray(v).nbytes for v in payload.values()))


class _CoalesceEntry:
    __slots__ = ("keys", "rows", "err", "done")

    def __init__(self, keys: np.ndarray):
        self.keys = keys
        self.rows: Optional[Dict[str, np.ndarray]] = None
        self.err: Optional[Exception] = None
        self.done = False


class _PullCoalescer:
    """Server-side read coalescing: concurrent ``pull`` /
    ``pull_serving`` requests hitting one shard fold into ONE store
    lookup (the ``serving/batcher.py`` window pattern applied to the
    shard tier). Under trainer fan-in the per-slot FeatureStore lock is
    the hot resource; N worker threads queueing on it serially pay N
    lock acquisitions and N gather passes over overlapping keys.

    Protocol: the first request of a round becomes the LEADER — it
    optionally sleeps ``FLAGS_multihost_coalesce_window_ms`` (0 =
    opportunistic: no sleep, riders are whatever piled up while the
    previous round held the store), drains the queue, unions the key
    sets (all sorted unique per the pull contract, so ``np.union1d``
    stays exact), runs the raw lookup ONCE, and scatters each rider's
    slice back via ``np.searchsorted``. Riders block on a timed
    Condition wait (lock-discipline rule: no untimed waits); a rider
    that arrives after the leader's drain claims the NEXT round when
    the busy flag drops. Bit-identity: init rows and ``contains`` are
    per-key deterministic, so a coalesced slice equals the direct
    call's bytes. A leader error fails the whole round loudly — the
    clients' idempotent-retry machinery re-issues.

    Per (server, kind) rounds; ``multihost_coalesce_window_ms < 0``
    disables coalescing entirely (every request takes the direct
    path)."""

    _KINDS = ("pull", "pull_serving")

    def __init__(self, server: "ShardServer"):
        self._srv = server
        self._cv = threading.Condition()
        self._queues: Dict[str, List[_CoalesceEntry]] = {
            k: [] for k in self._KINDS}
        self._busy: Dict[str, bool] = {k: False for k in self._KINDS}

    def rows(self, kind: str, keys: np.ndarray,
             fn: Callable[[np.ndarray], Dict[str, np.ndarray]]
             ) -> Dict[str, np.ndarray]:
        window = float(flags.flag("multihost_coalesce_window_ms"))
        if window < 0 or keys.size == 0:
            return fn(keys)
        ent = _CoalesceEntry(keys)
        with self._cv:
            self._queues[kind].append(ent)
            while not ent.done and self._busy[kind]:
                self._cv.wait(timeout=0.05)
            if not ent.done:
                # Claim leadership of the next round (our entry is
                # still queued — the round serves it with the riders).
                self._busy[kind] = True
        if not ent.done:
            try:
                if window > 0:
                    time.sleep(window / 1e3)
                with self._cv:
                    batch = self._queues[kind]
                    self._queues[kind] = []
                self._serve(batch, fn)
            finally:
                with self._cv:
                    self._busy[kind] = False
                    self._cv.notify_all()
        if ent.err is not None:
            raise ent.err
        assert ent.rows is not None
        return ent.rows

    def _serve(self, batch: List[_CoalesceEntry],
               fn: Callable[[np.ndarray], Dict[str, np.ndarray]]
               ) -> None:
        try:
            if len(batch) == 1:
                batch[0].rows = fn(batch[0].keys)
            else:
                union = batch[0].keys
                for b in batch[1:]:
                    union = np.union1d(union, b.keys)
                rows = fn(union)
                for b in batch:
                    idx = np.searchsorted(union, b.keys)
                    b.rows = {f: v[idx] for f, v in rows.items()}
                self._srv._bump("multihost/coalesced_pulls",
                                len(batch) - 1)
            self._srv._bump("multihost/coalesce_rounds", 1)
        except Exception as e:
            for b in batch:
                b.err = e
        with self._cv:
            for b in batch:
                b.done = True
            self._cv.notify_all()


class ShardServer(rpc.FramedRPCServer):
    """One host's shard(s) of the multi-host embedding tier."""

    def __init__(self, endpoint: str, index: int,
                 ranges: ShardRangeTable,
                 config: TableConfig, *, seed: int = 0,
                 store: Optional[FeatureStore] = None):
        self.index = index
        self.ranges = ranges
        self.config = config
        self._seed = seed
        # Per-slot stores: a replicated server holds one FeatureStore
        # PER slot it participates in (primary or backup), so promotion
        # is a role flip and drop-slot is a dict pop — never a row scan.
        # Unreplicated servers have exactly {index: store}: the legacy
        # single-store layout, byte-identical behavior.
        self._slot_stores: Dict[int, FeatureStore] = {
            index: store if store is not None else FeatureStore(
                config, seed=seed)}
        self._roles: Dict[int, str] = {index: "primary"}
        self._map: Optional[ReplicaMap] = None
        self._journals: Dict[int, DeltaJournal] = {}
        self._applied_seq: Dict[int, int] = {}
        # Per-slot BASELINE EPOCH: names the history a slot store's seq
        # numbers count over ("" = the empty/deterministic-init
        # baseline; hash-chained over checkpoint loads). A seq is only
        # comparable within one epoch — a freshly-loaded primary and a
        # fresh-empty backup both sit at seq 0 with different bytes,
        # and journal replay across that mismatch would silently
        # diverge. Epoch mismatch always forces a full snapshot.
        self._slot_epoch: Dict[int, str] = {index: ""}
        # (slot, backup endpoint) -> {"seq": last acked (None = unknown),
        # "lagged": forward failed, catch up before the next send}.
        self._backup_state: Dict[Tuple[int, str], Dict] = {}
        # Peer conns for replica forwarding; guarded by _peers_lock
        # (forwards for different slots run on different slot locks,
        # and stop() clears the dict from the teardown thread).
        self._peers: Dict[str, "ShardClient"] = {}
        self._peers_lock = threading.Lock()
        # One writer lock over range-mutating sequences (reshard moves /
        # set_range / load): the FeatureStore lock covers single calls,
        # but a pull_range -> drop_range commit must not interleave with
        # a concurrent load's set_all.
        self._mut_lock = threading.Lock()
        # PER-SLOT replication locks serialize apply + journal append +
        # backup forward so backups observe each slot's mutations in
        # seq order. Slot-granular ON PURPOSE: two primaries forwarding
        # to each other concurrently (host A pushes slot 0 -> B while B
        # pushes slot 1 -> A) would deadlock on one server-wide lock,
        # but a slot's primary->backup chain has length 1 and one
        # primary — no cycle is constructible. RLock: shrink/sync paths
        # nest. Ordered AFTER _mut_lock wherever both are held; multi-
        # slot sections acquire slots in sorted order, never during an
        # RPC they initiated.
        self._slot_locks: Dict[int, threading.RLock] = {}
        self._locks_guard = threading.Lock()
        # Per-SERVER registry beside the process-global one (the
        # PredictServer instance-Monitor pattern): in-process multi-
        # server drills run N ShardServers in one interpreter, and
        # per-host assertions (served keys, forward errors, journal
        # lag) need each server's own numbers — the global keeps its
        # process-wide meaning. handle_metrics_snapshot serves this
        # registry to the fleet_top / telemetry_scrape collectors.
        self.metrics = monitor.Monitor()
        # Per-host trend ring (core/timeseries.py) behind the
        # metrics_history RPC; idle until the sampler is armed.
        self.history = timeseries.history_for(self.metrics,
                                              label=f"shard:{index}")
        self._coalescer = _PullCoalescer(self)
        self.service_name = f"shard[{index}]"
        rpc.FramedRPCServer.__init__(self, endpoint, backlog=64)

    def _bump(self, name: str, delta: int = 1) -> None:
        monitor.add(name, delta)
        self.metrics.add(name, delta)

    def _set_gauge(self, name: str, value: float) -> None:
        monitor.set_gauge(name, value)
        self.metrics.set_gauge(name, value)

    def _slot_lock(self, slot: int) -> "threading.RLock":
        with self._locks_guard:
            lk = self._slot_locks.get(slot)
            if lk is None:
                lk = self._slot_locks[slot] = threading.RLock()
            return lk

    def _hold_all_slots(self):
        """Acquire every known slot lock in sorted order (topology /
        load / reset sections — no RPC runs while held)."""
        import contextlib
        stack = contextlib.ExitStack()
        with self._locks_guard:
            slots = sorted(set(self._slot_locks)
                           | set(self._slot_stores) | set(self._roles))
        for slot in slots:
            stack.enter_context(self._slot_lock(slot))
        return stack

    @staticmethod
    def _chain_epoch(prev: str, kind: str, path: str) -> str:
        """Deterministic epoch transition for a checkpoint load: every
        server that applied the same load sequence onto the same prior
        baseline lands on the same epoch string, so post-load journal
        replay needs no snapshot."""
        import hashlib
        h = hashlib.sha1(f"{prev}|{kind}:{path}".encode()).hexdigest()
        return h[:16]

    @property
    def store(self) -> FeatureStore:
        """The store of this server's (first) primary slot — the legacy
        single-slot surface tests and the R=1 paths use."""
        return self._slot_stores[self.index]

    def _after_reply(self) -> bool:
        if not self._running:
            self.stop()
            return True
        return False

    # -- slot routing ------------------------------------------------------

    def _primary_slots(self) -> List[int]:
        return sorted(s for s, r in self._roles.items() if r == "primary")

    def _slot_groups(self, keys: np.ndarray, *, write: bool
                     ) -> List[Tuple[int, Optional[np.ndarray]]]:
        """Group request keys by owning slot; every slot must be locally
        replicated (reads) / locally PRIMARY (writes). ``None`` index =
        the whole (single-slot) request — the common case, since clients
        slice per slot. Subset indices are ascending, so sorted inputs
        stay sorted per group."""
        if keys.size == 0:
            return [(self.index, None)]
        owner = self.ranges.owner_of(keys)
        slots = np.unique(owner)
        for s in slots.tolist():
            role = self._roles.get(int(s))
            if role is None:
                bad = int(s)
                raise ValueError(
                    f"keys not owned by shard {self.index} "
                    f"(first stray owner {bad}) — client range table is "
                    f"stale; re-apply the rank table")
            if write and role != "primary":
                self._bump("multihost/stale_primary_errors", 1)
                incident.note_stale_primary()
                raise StalePrimaryError(
                    f"STALE_PRIMARY: shard {self.index} is {role} for "
                    f"slot {int(s)} — the client's replica map predates "
                    "a promotion/repair; re-resolve and retry")
        if slots.size == 1:
            return [(int(slots[0]), None)]
        return [(int(s), np.flatnonzero(owner == s)) for s in slots]

    def _sub(self, arr: np.ndarray, idx: Optional[np.ndarray]
             ) -> np.ndarray:
        return arr if idx is None else arr[idx]

    # -- replication plumbing ----------------------------------------------

    def _peer(self, endpoint: str) -> "ShardClient":
        with self._peers_lock:
            c = self._peers.get(endpoint)
            if c is None:
                c = self._peers[endpoint] = ShardClient(endpoint)
            return c

    def _replicated(self, slot: int) -> Tuple[str, ...]:
        """Backup endpoints of a slot this server leads (empty when
        unreplicated — the R=1 fast path)."""
        if self._map is None:
            return ()
        return self._map.replicas_of(slot)[1:]

    def _mutate(self, slot: int, op: str, payload: dict, apply_fn) -> None:
        """One slot mutation: apply locally, journal, forward to the
        slot's backups SYNCHRONOUSLY (an unreachable backup is marked
        lagged and caught up later — availability over lockstep; the
        client's push still succeeded on the primary)."""
        backups = self._replicated(slot)
        if not backups and self._map is None:
            apply_fn()      # R=1: nothing else, bit-identical
            return
        with self._slot_lock(slot):
            apply_fn()
            j = self._journals.get(slot)
            if j is None:
                j = self._journals[slot] = DeltaJournal(
                    int(flags.flag("multihost_journal_entries")),
                    epoch=self._slot_epoch.get(slot, ""))
            faults.faultpoint("multihost/journal_append")
            seq = j.append(op, payload)
            if backups:
                faults.faultpoint("multihost/replica_forward")
                self._forward_locked(slot, seq, op, payload)

    def _forward_locked(self, slot: int, seq: int, op: str,
                        payload: dict) -> None:
        # In-sync backups get their replica_apply PIPELINED on the
        # mux'd peer conns (PR 16): all sends go out back-to-back, then
        # the acks are collected — R=3 pays one backup RTT, not two.
        # Out-of-sync backups fall to the sequential catch-up path; a
        # failed pipelined apply falls there too (the peer conn
        # reconnects lazily and journal/snapshot replay is idempotent).
        eps = self._replicated(slot)
        states = {ep: self._backup_state.setdefault(
            (slot, ep), {"seq": None, "lagged": True}) for ep in eps}
        futs: Dict[str, "_ShardFuture"] = {}
        for ep in eps:
            if states[ep]["seq"] == seq - 1:
                try:
                    futs[ep] = self._peer(ep).call_async(
                        "replica_apply", slot=slot, seq=seq, op=op,
                        epoch=self._journals[slot].epoch, **payload)
                except (OSError, ConnectionError, wire.WireError):
                    pass    # send failed: the collect loop catches up
        for ep in eps:
            st = states[ep]
            try:
                try:
                    if ep in futs:
                        futs[ep].result()
                        st["seq"] = seq
                    else:
                        self._catch_up_locked(slot, ep, st)
                        if st["seq"] == seq - 1:
                            self._peer(ep).call(
                                "replica_apply", slot=slot, seq=seq,
                                op=op,
                                epoch=self._journals[slot].epoch,
                                **payload)
                            st["seq"] = seq
                except (OSError, ConnectionError, RuntimeError,
                        wire.WireError):
                    # Direct send bounced (stale conn after a backup
                    # restart, a seq race, a mid-stream drop): one
                    # catch-up attempt — the peer conn reconnects lazily
                    # and the journal/snapshot replay is idempotent. A
                    # backup that is genuinely DOWN fails here too and
                    # stays lagged.
                    self._catch_up_locked(slot, ep, st)
                if st["seq"] < seq:
                    raise ConnectionError(
                        f"backup {ep} slot {slot} at seq {st['seq']}, "
                        f"want {seq}")
                st["lagged"] = False
            except (OSError, ConnectionError, RuntimeError,
                    wire.WireError) as e:
                st["lagged"] = True
                self._bump("multihost/replica_forward_errors", 1)
                log.warning("%s: forward %s seq %d slot %d -> %s failed "
                            "(%r) — backup marked lagged",
                            self.service_name, op, seq, slot, ep, e)

    def _catch_up_locked(self, slot: int, ep: str, st: Dict) -> None:
        """Bring one backup to the journal head: delta replay when the
        journal still covers its gap, full range snapshot otherwise
        (the bounded-re-replication fallback)."""
        peer = self._peer(ep)
        bstate = peer.call("replica_seq", slot=slot)
        bseq, bepoch = int(bstate["seq"]), str(bstate["epoch"])
        j = self._journals[slot]
        # Journal replay is only sound within ONE epoch (same baseline
        # under the seq numbers); anything else snapshots.
        entries = j.since(bseq) if bepoch == j.epoch else None
        if entries is None:
            store = self._slot_stores[slot]
            keys, _ = store.key_stats()
            unseen = store.unseen_for(keys)
            chunk = int(flags.flag("reshard_chunk_rows"))
            n = int(keys.size)
            if chunk <= 0 or n <= chunk:
                peer.call("replica_snapshot", slot=slot, seq=j.seq,
                          epoch=j.epoch, keys=keys,
                          values=store.pull_for_pass(keys),
                          unseen=unseen)
            else:
                # Bounded-memory re-replication: stream the snapshot in
                # FLAGS_reshard_chunk_rows windows so neither side ever
                # materializes the whole slot in one RPC. Chunks are
                # synchronous (strictly ordered); the backup holds the
                # mid-snapshot sentinel epoch until 'last' commits, so
                # a kill -9 between chunks forces a clean re-snapshot.
                for i0 in range(0, n, chunk):
                    i1 = min(i0 + chunk, n)
                    sub = keys[i0:i1]
                    peer.call("replica_snapshot", slot=slot, seq=j.seq,
                              epoch=j.epoch, keys=sub,
                              values=store.pull_for_pass(sub),
                              unseen=unseen[i0:i1],
                              part=("first" if i0 == 0 else
                                    "last" if i1 == n else "mid"))
                    self._bump("multihost/replica_snapshot_chunks", 1)
            self._bump("multihost/replica_snapshots", 1)
            self._bump("multihost/replica_snapshot_rows",
                       int(keys.size))
            log.vlog(0, "%s: slot %d snapshot -> %s (%d rows, seq %d; "
                     "backup was at %d)", self.service_name, slot, ep,
                     keys.size, j.seq, bseq)
        else:
            for e in entries:
                peer.call("replica_apply", slot=slot, seq=e.seq,
                          op=e.op, epoch=j.epoch, **e.payload)
            self._bump("multihost/replica_catchup_entries",
                       len(entries))
            if entries:
                log.vlog(0, "%s: slot %d journal catch-up -> %s "
                         "(%d entries, seq %d -> %d)", self.service_name,
                         slot, ep, len(entries), bseq, j.seq)
        st["seq"] = j.seq

    def adopt_replica_map(self, rmap: ReplicaMap) -> Dict[int, str]:
        """ADOPT a replica-map generation: derive this server's roles
        from its own endpoint, create empty stores for newly assigned
        slots, flip roles (backup→primary = PROMOTION: the slot's store
        already holds the rows, a fresh journal seeds at the applied
        seq), and drop slots no longer replicated here (COMMIT).
        Idempotent — re-adopting the same map is a no-op."""
        with self._mut_lock, self._hold_all_slots():
            new_roles = rmap.slots_of(self.endpoint)
            if not new_roles:
                raise ValueError(
                    f"endpoint {self.endpoint} appears in no slot of "
                    "the replica map — wrong map or drained host")
            cap = int(flags.flag("multihost_journal_entries"))
            for slot, role in new_roles.items():
                old = self._roles.get(slot)
                if slot not in self._slot_stores:
                    self._slot_stores[slot] = FeatureStore(
                        self.config, seed=self._seed)
                    self._slot_epoch.setdefault(slot, "")
                if role == "primary" and old != "primary":
                    faults.faultpoint("multihost/replica_promote")
                    start = self._applied_seq.pop(slot, 0)
                    # The promoted store's (epoch, seq) carries over:
                    # its bytes ARE baseline+seq mutations, and an R=3
                    # sibling backup in the same epoch can keep its
                    # state (same-epoch gap still snapshots, since the
                    # fresh journal holds no entries).
                    self._journals[slot] = DeltaJournal(
                        cap, start_seq=start,
                        epoch=self._slot_epoch.get(slot, ""))
                    if old == "backup":
                        self._bump("multihost/replica_promotes", 1)
                        log.vlog(0, "%s: PROMOTED to primary of slot %d "
                                 "(seq %d)", self.service_name, slot,
                                 start)
                elif role == "backup" and old != "backup":
                    j = self._journals.pop(slot, None)
                    self._applied_seq[slot] = j.seq if j else 0
            for slot in list(self._slot_stores):
                if slot not in new_roles:
                    self._slot_stores.pop(slot)
                    self._journals.pop(slot, None)
                    self._applied_seq.pop(slot, None)
                    self._slot_epoch.pop(slot, None)
            self._roles = new_roles
            self._map = rmap
            self.ranges = rmap.table
            prim = self._primary_slots()
            self.index = prim[0] if prim else sorted(new_roles)[0]
            self._backup_state = {
                (slot, ep): self._backup_state.get(
                    (slot, ep), {"seq": None, "lagged": True})
                for slot in prim
                for ep in rmap.replicas_of(slot)[1:]}
            self.service_name = f"shard[{self.index}]"
            self._set_gauge("multihost/replication",
                            float(rmap.replication))
            return dict(self._roles)

    # -- pull / push (the DCN halves of the lookup exchange) ---------------

    def _pull_rows(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Raw full-row lookup for sorted unique keys (pull_for_pass
        semantics; ``emb`` stays f32 — wire encoding is per-request, on
        top). This is the coalescable unit: one call per coalescing
        round, holding each touched slot store once."""
        groups = self._slot_groups(keys, write=False)
        rows: Optional[Dict[str, np.ndarray]] = None
        for slot, idx in groups:
            part = self._slot_stores[slot].pull_for_pass(
                self._sub(keys, idx))
            if idx is None:
                rows = part
            else:
                if rows is None:
                    rows = {f: np.empty((keys.shape[0],) + v.shape[1:],
                                        v.dtype) for f, v in part.items()}
                for f, v in part.items():
                    rows[f][idx] = v
        return rows

    def handle_pull(self, req) -> Dict[str, np.ndarray]:
        """Full value rows for sorted unique keys in a locally
        replicated slot (pull_for_pass semantics: unseen keys return
        deterministic per-key init rows and are NOT inserted — a pure
        read, declared idempotent by the client, served by primary OR
        backup). ``wire`` selects the emb encoding. Concurrent pulls
        coalesce into one store lookup (``_PullCoalescer``); the wire
        encode and served-keys counter stay per-request."""
        keys = np.asarray(req["keys"], np.uint64)
        rows = self._coalescer.rows("pull", keys, self._pull_rows)
        out: Dict[str, np.ndarray] = {
            f: v for f, v in rows.items() if f != "emb"}
        out.update(encode_emb(rows["emb"], req.get("wire", "f32")))
        self._bump("multihost/served_pull_keys", int(keys.size))
        return out

    def _pull_serving_rows(self, keys: np.ndarray
                           ) -> Dict[str, np.ndarray]:
        """Raw serving lookup: found mask + w + f32 emb (zeros for
        missing keys), per-key deterministic — the coalescable unit
        behind ``handle_pull_serving``."""
        groups = self._slot_groups(keys, write=False)
        n = keys.shape[0]
        found = np.zeros((n,), bool)
        emb: Optional[np.ndarray] = None
        w = np.zeros((n,), np.float32)
        for slot, idx in groups:
            store = self._slot_stores[slot]
            sub = self._sub(keys, idx)
            f = store.contains(sub)
            rows = store.pull_for_pass(sub)
            e = np.ascontiguousarray(rows["emb"], np.float32)
            ww = np.ascontiguousarray(rows["w"], np.float32)
            if not f.all():
                # Masked rows ship zeros (cheap to compress, and the
                # client must not see init values for keys it will
                # serve as unknown anyway).
                e[~f] = 0.0
                ww[~f] = 0.0
            if idx is None:
                found, emb, w = f, e, ww
            else:
                if emb is None:
                    emb = np.zeros((n, e.shape[1]), np.float32)
                found[idx] = f
                emb[idx] = e
                w[idx] = ww
        return {"found": found, "w": w, "emb": emb}

    def handle_pull_serving(self, req) -> Dict[str, np.ndarray]:
        """Serving-tier miss resolution: (found mask, w, wire-encoded
        emb) for sorted unique keys in a locally replicated slot. A PURE
        read like ``pull`` — unseen keys are NOT inserted — but it also
        reports which keys exist (serving must answer zeros for a
        feasign training never saw, not the trainer's init row) and
        ships ONLY the serving fields (emb + w), never optimizer state:
        a replica's miss path reads a fraction of the bytes a trainer
        pull moves. Concurrent calls coalesce like ``pull``."""
        keys = np.asarray(req["keys"], np.uint64)
        rows = self._coalescer.rows("pull_serving", keys,
                                    self._pull_serving_rows)
        out: Dict[str, np.ndarray] = {"found": rows["found"],
                                      "w": rows["w"]}
        out.update(encode_emb(rows["emb"], req.get("wire", "f32")))
        self._bump("multihost/served_serving_keys", int(keys.size))
        return out

    def handle_push(self, req) -> int:
        """EndPass write-back of full rows (emb decoded from the wire
        encoding to f32 BEFORE the store write). Primary-only; the
        decoded f32 rows are what forwards to backups, so replicas stay
        bit-identical to the primary regardless of the client wire."""
        keys = np.asarray(req["keys"], np.uint64)
        groups = self._slot_groups(keys, write=True)
        values = dict(req["values"])
        values["emb"] = decode_emb(values)
        for k in ("emb_f16", "emb_q", "emb_scale", "emb_width"):
            values.pop(k, None)
        for slot, idx in groups:
            sub_k = self._sub(keys, idx)
            sub_v = {f: self._sub(v, idx) for f, v in values.items()}
            self._mutate(
                slot, "push", {"keys": sub_k, "values": sub_v},
                lambda s=slot, k=sub_k, v=sub_v:
                    self._slot_stores[s].push_from_pass(k, v))
        self._bump("multihost/served_push_keys", int(keys.size))
        return int(keys.size)

    # -- replica protocol --------------------------------------------------

    def _require_backup(self, slot: int) -> FeatureStore:
        role = self._roles.get(slot)
        if role != "backup":
            self._bump("multihost/stale_primary_errors", 1)
            incident.note_stale_primary()
            raise StalePrimaryError(
                f"STALE_PRIMARY: shard {self.index} is "
                f"{role or 'no replica'} for slot {slot} — the sender's "
                "replica map predates a promotion/repair")
        return self._slot_stores[slot]

    def handle_replica_apply(self, req) -> int:
        """Backup-side mutation install, strictly in journal order: a
        seq gap raises loudly so the primary falls back to catch-up
        (never a silent divergence)."""
        slot, seq = int(req["slot"]), int(req["seq"])
        with self._slot_lock(slot):
            store = self._require_backup(slot)
            cur = self._applied_seq.get(slot, 0)
            epoch = self._slot_epoch.get(slot, "")
            if str(req.get("epoch", "")) != epoch:
                raise RuntimeError(
                    f"REPLICA_GAP: backup slot {slot} is on epoch "
                    f"{epoch!r}, entry is {req.get('epoch')!r} — "
                    "snapshot required")
            if seq != cur + 1:
                raise RuntimeError(
                    f"REPLICA_GAP: backup slot {slot} at seq {cur}, "
                    f"got {seq} — journal catch-up required")
            op = req["op"]
            if op == "push" or op == "apply":
                store.push_from_pass(
                    np.asarray(req["keys"], np.uint64),
                    dict(req["values"]),
                    unseen=(np.asarray(req["unseen"], np.int32)
                            if "unseen" in req else None))
            elif op == "shrink":
                store.shrink(resolved=(float(req["decay"]),
                                       int(req["ttl"]),
                                       float(req["min_show"])))
            else:
                raise ValueError(f"unknown replica op {op!r}")
            self._applied_seq[slot] = seq
        return seq

    def handle_replica_snapshot(self, req) -> int:
        """Full-slot overwrite install (catch-up past the journal
        window, or initial re-replication COPY). Idempotent.

        Chunked form (bounded-memory re-replication): the primary
        streams the snapshot in FLAGS_reshard_chunk_rows windows —
        ``part='first'`` REPLACES the slot store and stamps the
        mid-snapshot sentinel epoch, ``part='mid'`` appends,
        ``part='last'`` appends then commits the real (seq, epoch).
        A kill -9 between chunks leaves the sentinel epoch, which can
        never equal a primary's epoch, so the next catch-up negotiation
        re-snapshots from scratch instead of trusting a torn store."""
        slot, seq = int(req["slot"]), int(req["seq"])
        part = str(req.get("part", "all"))
        with self._slot_lock(slot):
            store = self._require_backup(slot)
            keys = np.asarray(req["keys"], np.uint64)
            vals = {f: np.asarray(req["values"][f]) for f in _FIELDS}
            unseen = np.asarray(req["unseen"], np.int32)
            if part in ("all", "first"):
                store.set_all(keys, vals, unseen=unseen)
            elif part in ("mid", "last"):
                if self._slot_epoch.get(slot) != _SNAPSHOT_PARTIAL:
                    raise RuntimeError(
                        f"SNAPSHOT_GAP: slot {slot} got snapshot chunk "
                        f"part={part!r} without an open first chunk — "
                        "restart the snapshot")
                if keys.size:
                    store.push_from_pass(keys, vals, unseen=unseen)
            else:
                raise ValueError(f"unknown snapshot part {part!r}")
            if part in ("all", "last"):
                self._applied_seq[slot] = seq
                self._slot_epoch[slot] = str(req.get("epoch", ""))
            else:
                self._slot_epoch[slot] = _SNAPSHOT_PARTIAL
        return int(keys.size)

    def handle_replica_seq(self, req) -> Dict:
        """This backup's applied (seq, epoch) for one slot (pure
        read) — the catch-up negotiation state."""
        slot = int(req["slot"])
        with self._slot_lock(slot):
            self._require_backup(slot)
            return {"seq": int(self._applied_seq.get(slot, 0)),
                    "epoch": self._slot_epoch.get(slot, "")}

    def handle_sync_replicas(self, req) -> Dict[str, int]:
        """Force catch-up of every backup of one primary slot NOW (the
        repair controller's re-replication step and the drills' quiesce
        point). Returns backup endpoint -> acked seq; a still-dead
        backup keeps its lag mark and reports -1."""
        slot = int(req["slot"])
        out: Dict[str, int] = {}
        with self._slot_lock(slot):
            if self._roles.get(slot) != "primary":
                raise StalePrimaryError(
                    f"STALE_PRIMARY: shard {self.index} is not primary "
                    f"of slot {slot}")
            j = self._journals.get(slot)
            if j is None:
                j = self._journals[slot] = DeltaJournal(
                    int(flags.flag("multihost_journal_entries")))
            for ep in self._replicated(slot):
                st = self._backup_state.setdefault(
                    (slot, ep), {"seq": None, "lagged": True})
                try:
                    if st["seq"] != j.seq:
                        self._catch_up_locked(slot, ep, st)
                    st["lagged"] = False
                    out[ep] = int(st["seq"])
                except (OSError, ConnectionError, RuntimeError,
                        wire.WireError) as e:
                    st["lagged"] = True
                    log.warning("%s: sync_replicas slot %d -> %s failed "
                                "(%r)", self.service_name, slot, ep, e)
                    out[ep] = -1
        return out

    def handle_set_replication(self, req) -> Dict:
        roles = self.adopt_replica_map(ReplicaMap.from_dict(req["map"]))
        return {str(s): r for s, r in roles.items()}

    def handle_replica_status(self, req) -> Dict:
        """Introspection for drills/tests: per-slot role, rows, journal
        seq / applied seq, and backup ack state."""
        with self._hold_all_slots():
            slots = {}
            for slot, role in sorted(self._roles.items()):
                j = self._journals.get(slot)
                slots[str(slot)] = {
                    "role": role,
                    "rows": int(self._slot_stores[slot].num_features),
                    "epoch": self._slot_epoch.get(slot, ""),
                    "seq": int(j.seq if j is not None
                               else self._applied_seq.get(slot, 0)),
                    "backups": {
                        ep: int(-1 if st["seq"] is None else st["seq"])
                        for (s, ep), st in self._backup_state.items()
                        if s == slot},
                }
            return {"endpoint": self.endpoint, "index": int(self.index),
                    "slots": slots,
                    "replication": int(self._map.replication
                                       if self._map else 1)}

    # -- reshard protocol --------------------------------------------------

    def handle_pull_range(self, req) -> Dict[str, np.ndarray]:
        """Copy (NOT pop) of every resident row whose placement hash is
        in [lo, hi) — the read-only COPY phase of a reshard move, so a
        crash mid-move loses nothing. Scans every locally replicated
        slot store (one store in the R=1 layout).

        Cursor paging (``after``/``limit``): with ``limit > 0`` the
        reply holds at most ``limit`` rows in global key order starting
        strictly AFTER the ``after`` key, plus ``more``/``next_after``
        so the caller can walk the range in bounded windows
        (FLAGS_reshard_chunk_rows) instead of materializing the whole
        range in one RPC. Pure read — re-pulling any window is free."""
        lo, hi = int(req["lo"]), int(req["hi"])
        after = int(req.get("after", 0) or 0)
        limit = int(req.get("limit", 0) or 0)
        slot_sel: List[Tuple[int, np.ndarray]] = []
        for slot in sorted(self._slot_stores):
            store = self._slot_stores[slot]
            keys, _ = store.key_stats()
            mask = self.ranges.mask_in_range(keys, lo, hi)
            if after:
                mask &= keys > np.uint64(after)
            sel = keys[mask]
            if sel.size:
                slot_sel.append((slot, sel))
        more = False
        next_after = 0
        total = sum(int(s.size) for _, s in slot_sel)
        if limit > 0 and total > limit:
            # The page is the `limit` smallest candidate keys (slot
            # ranges are disjoint, so keys are unique across stores and
            # a <=-cut reproduces the global order exactly).
            cut = np.sort(
                np.concatenate([s for _, s in slot_sel]))[limit - 1]
            slot_sel = [(slot, s[s <= cut]) for slot, s in slot_sel]
            slot_sel = [(slot, s) for slot, s in slot_sel if s.size]
            more = True
            next_after = int(cut)
        parts_k: List[np.ndarray] = []
        parts_v: List[Dict[str, np.ndarray]] = []
        for slot, sel in slot_sel:
            parts_k.append(sel)
            parts_v.append(self._slot_stores[slot].pull_for_pass(sel))
        if not parts_k:
            empty = self._slot_stores[self.index].pull_for_pass(
                np.empty((0,), np.uint64))
            return {"keys": np.empty((0,), np.uint64), "values": empty,
                    "more": False, "next_after": "0"}
        keys = np.concatenate(parts_k)
        vals = {f: np.concatenate([p[f] for p in parts_v])
                for f in parts_v[0]}
        order = np.argsort(keys, kind="stable")
        return {"keys": keys[order],
                "values": {f: v[order] for f, v in vals.items()},
                "more": more, "next_after": str(next_after)}

    def handle_apply_rows(self, req) -> int:
        """Install moved rows (full-row OVERWRITE — naturally idempotent,
        so a replayed move after a crash cannot double-apply). Forwards
        to backups like any other mutation."""
        keys = np.asarray(req["keys"], np.uint64)
        values = dict(req["values"])
        unseen = (np.asarray(req["unseen"], np.int32)
                  if "unseen" in req else None)
        with self._mut_lock:
            if self._map is None:
                # Reshard COPY window: rows land on the DST before the
                # ADOPT re-draws its table, so ownership is checked by
                # the reshard plan, not the (still-old) range table.
                self.store.push_from_pass(keys, values, unseen=unseen)
                return int(keys.size)
            groups = self._slot_groups(keys, write=True)
            for slot, idx in groups:
                sub_k = self._sub(keys, idx)
                sub_v = {f: self._sub(v, idx) for f, v in values.items()}
                payload = {"keys": sub_k, "values": sub_v}
                sub_u = None
                if unseen is not None:
                    sub_u = self._sub(unseen, idx)
                    payload["unseen"] = sub_u
                self._mutate(
                    slot, "apply", payload,
                    lambda s=slot, k=sub_k, v=sub_v, u=sub_u:
                        self._slot_stores[s].push_from_pass(k, v,
                                                            unseen=u))
        return int(keys.size)

    def handle_drop_range(self, req) -> int:
        """COMMIT phase: discard rows in [lo, hi) after every dest has
        acknowledged its copy. Idempotent (an empty range drops 0)."""
        lo, hi = int(req["lo"]), int(req["hi"])
        dropped = 0
        with self._mut_lock:
            for slot in sorted(self._slot_stores):
                store = self._slot_stores[slot]
                keys, _ = store.key_stats()
                mask = self.ranges.mask_in_range(keys, lo, hi)
                sel = keys[mask]
                if sel.size:
                    store.pop_rows(sel)
                    dropped += int(sel.size)
        return dropped

    def handle_set_range(self, req) -> bool:
        """Adopt a new range table (+ this server's index in it) — the
        last step before the drop phase of a reshard. The R=1 elastic
        RESIZE path; a replicated cluster adopts topology through
        ``set_replication`` instead (fixed slot count, endpoints move)."""
        with self._mut_lock:
            if self._map is not None and self._map.replication > 1:
                raise RuntimeError(
                    "set_range on a replicated shard server — elastic "
                    "world resizing runs at replicas=1; use "
                    "set_replication for failover repair (MULTIHOST.md)")
            new_index = int(req["index"])
            if new_index != self.index:
                self._slot_stores[new_index] = self._slot_stores.pop(
                    self.index)
                self._roles = {new_index: "primary"}
                self._slot_epoch[new_index] = self._slot_epoch.pop(
                    self.index, "")
                j = self._journals.pop(self.index, None)
                if j is not None:
                    self._journals[new_index] = j
            self.ranges = ShardRangeTable.from_dict(req["table"])
            self.index = new_index
            self._map = None
            self.service_name = f"shard[{self.index}]"
        return True

    # -- checkpoint / lifecycle --------------------------------------------

    def _shard_dir(self, path: str, slot: Optional[int] = None) -> str:
        d = os.path.join(
            path, f"hostshard-{self.index if slot is None else slot:04d}")
        os.makedirs(d, exist_ok=True)
        return d

    def handle_save(self, req) -> bool:
        """Save every PRIMARY slot to its own hostshard dir (backups
        never save: their primary's dump covers the range, and two
        replicas dumping the same rows would double them on load)."""
        mode = req.get("mode", "base")
        with self._mut_lock:
            for slot in self._primary_slots():
                store = self._slot_stores[slot]
                d = self._shard_dir(req["path"], slot)
                if mode == "base":
                    store.save_base(d)
                elif mode == "delta":
                    store.save_delta(d)
                else:
                    store.save_xbox(d)
        return True

    def _checkpoint_parts(self, path: str, kind: str, lo: int, hi: int
                          ) -> List[Tuple[np.ndarray, Dict,
                                          Optional[np.ndarray]]]:
        """Every (keys, values, ages) part of a checkpoint FILTERED to
        [lo, hi) — hostshard dirs from any world size, plus a flat
        single-host dump (migration path). ``ages`` is the unseen-days
        sidecar (None for pre-sidecar checkpoints — those rows restart
        their TTL lease, the documented legacy behavior)."""
        name = self.config.name
        files = sorted(glob.glob(os.path.join(
            path, "hostshard-*", f"{name}.{kind}.npz")))
        flat = os.path.join(path, f"{name}.{kind}.npz")
        if os.path.exists(flat):
            files.append(flat)
        if not files:
            raise FileNotFoundError(
                f"no {kind} dump for table {name!r} under {path}")
        parts = []
        for f in files:
            data = np.load(f)
            keys = data["keys"].astype(np.uint64)
            mask = self.ranges.mask_in_range(keys, lo, hi)
            if not mask.any():
                continue
            ages = None
            ages_f = f[:-len(".npz")] + ".ages.npz"
            if os.path.exists(ages_f):
                a = np.load(ages_f)["unseen"]
                if a.shape[0] == keys.shape[0]:
                    ages = a[mask].astype(np.int32)
            parts.append((keys[mask],
                          {fld: data[fld][mask] for fld in _FIELDS},
                          ages))
        return parts

    def handle_load(self, req) -> int:
        """World-agnostic load: each locally replicated slot (primary
        AND backup — a recovered cluster comes back fully replicated
        from the checkpoint alone) keeps only rows in its range.
        ``base`` REPLACES contents (set_all semantics, like
        FeatureStore.load); ``delta`` applies on top. Journals reset:
        every replica now holds the same bytes."""
        path, kind = req["path"], req.get("kind", "base")
        total = 0
        with self._mut_lock, self._hold_all_slots():
            for slot in sorted(self._roles):
                store = self._slot_stores[slot]
                lo, hi = self.ranges.range_of(slot)
                parts = self._checkpoint_parts(path, kind, lo, hi)
                if kind == "base":
                    if parts:
                        keys = np.concatenate([k for k, _, _ in parts])
                        vals = {f: np.concatenate(
                            [v[f] for _, v, _ in parts])
                            for f in _FIELDS}
                        ages = np.concatenate(
                            [(a if a is not None
                              else np.zeros(k.shape, np.int32))
                             for k, _, a in parts])
                        order = np.argsort(keys, kind="stable")
                        store.set_all(keys[order],
                                      {f: v[order]
                                       for f, v in vals.items()},
                                      unseen=ages[order])
                    else:
                        store.reset()
                else:
                    for keys, vals, ages in parts:
                        store.push_from_pass(keys, vals, unseen=ages)
                new_epoch = self._chain_epoch(
                    self._slot_epoch.get(slot, ""), kind, path)
                self._slot_epoch[slot] = new_epoch
                j = self._journals.get(slot)
                if j is not None:
                    j.reset(epoch=new_epoch)
                if slot in self._applied_seq:
                    self._applied_seq[slot] = 0
                total += int(store.num_features)
            for st in self._backup_state.values():
                st["seq"] = None
                st["lagged"] = True
        return total

    def handle_reset(self, req) -> bool:
        with self._mut_lock, self._hold_all_slots():
            for slot, store in self._slot_stores.items():
                store.reset()
                self._slot_epoch[slot] = ""
                j = self._journals.get(slot)
                if j is not None:
                    j.reset(epoch="")
                if slot in self._applied_seq:
                    self._applied_seq[slot] = 0
            for st in self._backup_state.values():
                st["seq"] = None
                st["lagged"] = True
        return True

    def handle_shrink(self, req) -> int:
        """Day-boundary lifecycle on this server's PRIMARY slots (the
        FeatureStore resolves FLAGS_table_* decay/TTL/min-show in THIS
        process, and forwards the RESOLVED numbers to backups so a
        backup host with different flags cannot diverge); the
        post-shrink row count is republished as this server's gauge so
        the bounded-store story is observable per host too."""
        from paddlebox_tpu.embedding import lifecycle
        evicted = 0
        with self._mut_lock:
            for slot in self._primary_slots():
                store = self._slot_stores[slot]
                if self._replicated(slot):
                    params = lifecycle.shrink_params(
                        self.config, req.get("min_show", 0.0))
                    box: List[int] = []
                    self._mutate(
                        slot, "shrink",
                        {"decay": float(params[0]), "ttl": int(params[1]),
                         "min_show": float(params[2])},
                        lambda s=store, p=params, b=box:
                            b.append(s.shrink(resolved=p)))
                    evicted += box[0]
                else:
                    evicted += store.shrink(
                        min_show=req.get("min_show", 0.0))
        self._set_gauge(
            "multihost/shard_rows",
            float(sum(self._slot_stores[s].num_features
                      for s in self._primary_slots())))
        return evicted

    def handle_contains(self, req) -> np.ndarray:
        """Membership mask for keys in locally replicated slots (pure
        read — the FeatureStore.contains surface across the wire)."""
        keys = np.asarray(req["keys"], np.uint64)
        groups = self._slot_groups(keys, write=False)
        out = np.zeros(keys.shape, bool)
        for slot, idx in groups:
            got = self._slot_stores[slot].contains(self._sub(keys, idx))
            if idx is None:
                out = got
            else:
                out[idx] = got
        return out

    def handle_unseen_for(self, req) -> np.ndarray:
        """Unseen-days TTL ages for keys in locally replicated slots
        (pure read — the FeatureStore.unseen_for surface across the
        wire; the ages sidecar makes these restart-durable)."""
        keys = np.asarray(req["keys"], np.uint64)
        groups = self._slot_groups(keys, write=False)
        out = np.zeros(keys.shape, np.int32)
        for slot, idx in groups:
            got = self._slot_stores[slot].unseen_for(
                self._sub(keys, idx))
            if idx is None:
                out = got
            else:
                out[idx] = got
        return out

    def handle_key_stats(self, req) -> Dict[str, np.ndarray]:
        """(keys, show) of this server's PRIMARY slots (pure read) —
        the cluster-wide key_stats fan-in's per-server share."""
        ks, shows = [], []
        for slot in self._primary_slots():
            k, sh = self._slot_stores[slot].key_stats()
            ks.append(k)
            shows.append(sh)
        keys = (np.concatenate(ks) if ks
                else np.empty((0,), np.uint64))
        show = (np.concatenate(shows) if shows
                else np.empty((0,), np.float32))
        return {"keys": keys, "show": show}

    def replication_lag(self) -> Dict[str, float]:
        """Per-slot journal lag of this server's primary slots: for
        every (slot, backup) pair, primary seq minus the backup's last
        acked seq (an unacked/never-synced backup counts the full
        journal seq). Returns the worst and the p99 across slots — the
        fleet-wide freshness-of-replicas gauges a scrape reads. An
        approximate stat: read without slot locks (a torn read is off
        by at most the in-flight mutation)."""
        lags: List[int] = []
        journals = dict(self._journals)
        for (slot, _ep), st in list(self._backup_state.items()):
            j = journals.get(slot)
            if j is None:
                continue
            acked = st.get("seq")
            lags.append(max(0, j.seq - (acked if acked is not None
                                        else 0)))
        if not lags:
            return {"worst": 0.0, "p99": 0.0, "pairs": 0.0}
        lags.sort()
        p99 = lags[min(len(lags) - 1,
                       max(0, int(round(0.99 * (len(lags) - 1)))))]
        return {"worst": float(lags[-1]), "p99": float(p99),
                "pairs": float(len(lags))}

    def handle_metrics_snapshot(self, req) -> dict:
        """This server's labeled instance-registry snapshot, with the
        replication-lag gauges computed AT SCRAPE TIME (they are a
        derived view of journal/ack state, not an event counter) — the
        per-host share of the one-scrape cluster snapshot
        (core/telemetry_scrape.py, tools/fleet_top.py)."""
        lag = self.replication_lag()
        self._set_gauge("multihost/replica_lag_worst", lag["worst"])
        self._set_gauge("multihost/replica_lag_p99", lag["p99"])
        return self.metrics.snapshot_all(
            labels={"service": self.service_name,
                    "endpoint": self.endpoint,
                    "shard": int(self.index)})

    def handle_metrics_history(self, req) -> dict:
        """This shard host's trend ring (instance registry: served
        volume, journal lag gauges as of the last scrape) for the
        fleet_top sparkline pane."""
        return self.history.to_dict(window_s=req.get("window_s"),
                                    last_n=req.get("last_n"))

    def handle_stats(self, req) -> Dict[str, int]:
        snap = monitor.snapshot()
        return {"num_features": int(sum(
                    self._slot_stores[s].num_features
                    for s in self._primary_slots())),
                "index": int(self.index),
                "world": int(self.ranges.world),
                "replication": int(self._map.replication
                                   if self._map else 1),
                # Process-level conn health: the failover drills assert
                # the retry budget actually consumed.
                "rpc_reconnects": int(snap.get("rpc/reconnects", 0)),
                "rpc_retries": int(snap.get("rpc/retries", 0))}

    def handle_stop(self, req) -> bool:
        self._running = False
        return True

    def stop(self) -> None:
        """Graceful stop: close the listener; established conns drain
        their in-flight replies (the PS stop-RPC discipline)."""
        with self._peers_lock:
            peers, self._peers = dict(self._peers), {}
        for c in peers.values():
            c.close()
        rpc.FramedRPCServer.stop(self)

    def kill(self) -> None:
        """Host-death simulation for in-process tests/drills: stop AND
        sever every established connection, the way a SIGKILL'd host
        drops its sockets — a lingering persistent client conn must not
        receive one more reply from a corpse."""
        self.stop()
        self.close_connections()


class ShardClient:
    """One client handle to a shard slot's servers: a thin FramedRPCConn
    wrapper declaring the idempotent methods. ``replicas_fn`` wires the
    conn's reconnect-time ``resolve`` hook to the slot's CURRENT
    replica set — the conn always re-points at the set's PRIMARY, so a
    retried pull/push after a primary death (and the repair
    controller's promotion) lands on the live primary instead of
    burning ``FLAGS_rpc_retry_deadline_s`` on the dead endpoint — the
    same fix PR 11 gave PredictClient.

    Pure READS additionally fail over across the slot's backups when
    the primary stays unreachable (any replica serves them — a shard
    host kill -9 under serving traffic costs a reconnect, not an
    error); the failover conn sticks until the next failure or a
    topology refresh rebuilds the client. Writes never fail over: a
    backup answers them with the loud transient STALE_PRIMARY contract.

    ``push`` IS declared idempotent: a shard push is a full-row
    overwrite keyed by feasign (replaying it writes the same bytes), so
    retry-after-reconnect can never double-apply."""

    #: Methods any replica may answer (pure reads).
    READS = frozenset(("pull", "pull_serving", "pull_range", "stats",
                       "contains", "unseen_for", "key_stats",
                       "replica_seq", "replica_status"))

    def __init__(self, endpoint: str, *, timeout: float = 60.0,
                 replicas_fn=None):
        self.endpoint = endpoint
        self._timeout = timeout
        self._replicas_fn = replicas_fn
        try:
            self._conn = self._make_conn(endpoint)
        except (OSError, ConnectionError):
            if replicas_fn is None:
                raise
            # Replicated slot with a dead primary AT CLIENT BUILD TIME
            # — a replica joining mid-failover (the autopilot spawns
            # joiners precisely while hosts are dying). Defer: the
            # first call builds the conn, and its read failover walks
            # the replica set if the primary is still down.
            self._conn = None

    def _make_conn(self, endpoint: str) -> rpc.FramedRPCConn:
        return rpc.FramedRPCConn(
            endpoint, timeout=self._timeout, service_name="shard",
            idempotent=("pull", "pull_serving", "pull_range", "stats",
                        "contains", "unseen_for", "key_stats",
                        "replica_seq", "replica_status", "push"),
            resolve=(self._resolve if self._replicas_fn is not None
                     else None))

    def _resolve(self, current: str) -> str:
        """Reconnect target: the slot's CURRENT primary (after a
        promotion/repair refreshed the map, that is the live one)."""
        eps = tuple(self._replicas_fn() or ())
        return eps[0] if eps else current

    def call(self, method: str, **kw):
        try:
            conn = self._conn
            if conn is None:
                conn = self._conn = self._make_conn(self.endpoint)
            return conn.call(method, **kw)
        except (OSError, ConnectionError, wire.WireError):
            if self._replicas_fn is None or method not in self.READS:
                raise
            # Try every replica in map order, PRIMARY FIRST, on a fresh
            # conn — the failed conn may have been swapped/closed under
            # us by a concurrently failing thread, so its endpoint says
            # nothing about who is dead.
            eps = tuple(self._replicas_fn() or ())
            for ep in eps:
                try:
                    conn = self._make_conn(ep)
                    out = conn.call(method, **kw)
                except (OSError, ConnectionError, wire.WireError):
                    continue
                # Stick to the live replica (swap BEFORE closing the
                # old conn: another thread mid-call on it will fail and
                # re-enter this loop against the full candidate list).
                old, self._conn = self._conn, conn
                try:
                    if old is not None:
                        old.close()
                except OSError:
                    pass
                monitor.add("multihost/replica_failovers", 1)
                # The failover HOP is part of the request's story: the
                # instant carries the active trace id (when traced), so
                # a merged trace shows which replica answered after the
                # primary died.
                trace.instant("multihost/replica_failover",
                              method=method, endpoint=ep)
                log.warning("shard client: read %s failed over to "
                            "replica %s", method, ep)
                return out
            raise

    def call_async(self, method: str, **kw) -> "_ShardFuture":
        """Pipelined call on the underlying mux conn (PR 16): N
        ``call_async`` results share one round trip instead of N.
        ``result()`` applies the same fallback as :meth:`call` — a
        transport failure on a method :meth:`call` would retry/fail
        over re-issues it synchronously through :meth:`call`; anything
        else re-raises (the caller owns catch-up, exactly as with the
        blocking path)."""
        conn = self._conn
        if conn is None:
            try:
                conn = self._conn = self._make_conn(self.endpoint)
            except (OSError, ConnectionError):
                if method not in _ShardFuture._REISSUE:
                    raise
                # Dead primary on a deferred conn: resolve through the
                # synchronous failover path at result() time.
                return _ShardFuture(self, None, method, kw)
        return _ShardFuture(self, conn.call_async(method, **kw),
                            method, kw)

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()


class _ShardFuture:
    """Future returned by :meth:`ShardClient.call_async`: resolves the
    pipelined reply, falling back to the client's synchronous
    retry/failover path when the transport died and the method is safe
    to re-issue (a read, or the idempotent-by-contract ``push``)."""

    _REISSUE = ShardClient.READS | frozenset(("push",))

    def __init__(self, client: ShardClient, fut, method: str, kw: dict):
        self._client = client
        self._fut = fut
        self._method = method
        self._kw = kw

    def result(self, timeout: Optional[float] = None):
        if self._fut is None:
            # call_async could not even build a conn to the primary
            # (deferred-conn client, primary dead): straight to the
            # synchronous failover path.
            return self._client.call(self._method, **self._kw)
        try:
            return self._fut.result(timeout)
        except (OSError, ConnectionError, wire.WireError):
            if self._method not in self._REISSUE:
                raise
            return self._client.call(self._method, **self._kw)


def start_local_shards(world: int, config: TableConfig, *, seed: int = 0,
                       replicas: int = 1
                       ) -> Tuple[List[ShardServer], List[str]]:
    """Loopback cluster on 127.0.0.1 ephemeral ports (tests / the
    ``bench.py multihost`` loopback mode). ``replicas`` > 1 wires the
    ring replica map across the started servers."""
    ranges = ShardRangeTable.for_world(world)
    servers = [ShardServer("127.0.0.1:0", i, ranges, config, seed=seed)
               for i in range(world)]
    eps = [s.endpoint for s in servers]
    if replicas > 1:
        rmap = ReplicaMap.ring(eps, replicas, ranges)
        for s in servers:
            s.adopt_replica_map(rmap)
    return servers, eps


def stop_shards(servers: List[ShardServer]) -> None:
    for s in servers:
        try:
            s.stop()
        except Exception as e:  # best-effort teardown
            log.vlog(1, "shard stop failed: %s", e)
