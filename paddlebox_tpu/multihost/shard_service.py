"""Host-sharded parameter service: one shard server per host.

Role of the reference's multi-node sparse tier (the brpc PS cluster the
GPU pass build pulls from, ``ps_gpu_wrapper.cc:362``) re-keyed by the
elastic :class:`~paddlebox_tpu.multihost.keyrange.ShardRangeTable`: each
host runs ONE :class:`ShardServer` owning the keys whose placement hash
lands in its contiguous range, so no host ever holds the full 50M+ key
table. The server speaks the repo's framed typed-wire protocol
(``distributed/wire.py`` — no pickle) through the shared
:class:`~paddlebox_tpu.distributed.rpc.FramedRPCServer` loop, and
clients ride :class:`~paddlebox_tpu.distributed.rpc.FramedRPCConn`'s
reconnect + idempotent-retry machinery (PR 5), so a shard blip on a pure
read costs latency, not the pass.

Wire format (``FLAGS_multihost_wire_dtype``): the ``emb`` field — the
dominant payload — crosses the DCN as f32 (exact, default), f16, or
int8 with per-block f32 scales (``multihost/quant.py``,
``FLAGS_embedding_quant_block``); every other field (w, optimizer
state, show/click) stays f32, and the receiver widens BEFORE anything
accumulates or persists. Reshard row moves (``pull_range`` /
``apply_rows``) always travel f32: they relocate training state, which
must arrive bit-identical.

Checkpoint layout: ``<path>/hostshard-<k>/<table>.<kind>.npz`` per
server. ``load`` is WORLD-AGNOSTIC: every server scans all hostshard
dirs (and a flat single-host dump — migration), keeping only rows in
its own current range — so a checkpoint written at world W recovers
cleanly into world W', which is what makes a crashed reshard rollback
safe (MULTIHOST.md, "reshard state machine").
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.core import flags, log, monitor
from paddlebox_tpu.distributed import rpc
from paddlebox_tpu.embedding.store import _FIELDS, FeatureStore
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import quant
from paddlebox_tpu.multihost.keyrange import ShardRangeTable

_SPAN = 1 << 64


def wire_mode() -> str:
    mode = flags.flag("multihost_wire_dtype")
    if mode not in ("f32", "f16", "int8"):
        raise ValueError(
            f"unknown multihost_wire_dtype {mode!r} "
            "(want 'f32'/'f16'/'int8')")
    return mode


def encode_emb(emb: np.ndarray, mode: str) -> Dict[str, np.ndarray]:
    """Encode the emb payload for the DCN wire. f32 passes the array
    through UNTOUCHED (the exact path must stay bit-identical)."""
    if mode == "f32":
        return {"emb": emb}
    if mode == "f16":
        return {"emb_f16": np.asarray(emb, np.float32).astype(np.float16)}
    q, scales = quant.quantize_blocked_np(
        emb, int(flags.flag("embedding_quant_block")))
    return {"emb_q": q, "emb_scale": scales,
            "emb_width": np.asarray([emb.shape[1]], np.int64)}


def decode_emb(payload: Dict[str, np.ndarray]) -> np.ndarray:
    """Widen a wire emb payload back to f32 (the only dtype anything
    downstream accumulates or persists in)."""
    if "emb" in payload:
        return payload["emb"]
    if "emb_f16" in payload:
        return payload["emb_f16"].astype(np.float32)
    width = int(payload["emb_width"][0])
    return quant.dequantize_blocked_np(
        payload["emb_q"], payload["emb_scale"], width,
        int(flags.flag("embedding_quant_block")))


def payload_nbytes(payload: Dict[str, np.ndarray]) -> int:
    return int(sum(np.asarray(v).nbytes for v in payload.values()))


class ShardServer(rpc.FramedRPCServer):
    """One host's shard of the multi-host embedding tier."""

    def __init__(self, endpoint: str, index: int,
                 ranges: ShardRangeTable,
                 config: TableConfig, *, seed: int = 0,
                 store: Optional[FeatureStore] = None):
        self.index = index
        self.ranges = ranges
        self.config = config
        self.store = store if store is not None else FeatureStore(
            config, seed=seed)
        # One writer lock over range-mutating sequences (reshard moves /
        # set_range / load): the FeatureStore lock covers single calls,
        # but a pull_range -> drop_range commit must not interleave with
        # a concurrent load's set_all.
        self._mut_lock = threading.Lock()
        self.service_name = f"shard[{index}]"
        rpc.FramedRPCServer.__init__(self, endpoint, backlog=64)

    def _after_reply(self) -> bool:
        if not self._running:
            self.stop()
            return True
        return False

    def _check_owned(self, keys: np.ndarray) -> None:
        if keys.size:
            owner = self.ranges.owner_of(keys)
            if not np.all(owner == self.index):
                bad = int(owner[owner != self.index][0])
                raise ValueError(
                    f"keys not owned by shard {self.index} "
                    f"(first stray owner {bad}) — client range table is "
                    f"stale; re-apply the rank table")

    # -- pull / push (the DCN halves of the lookup exchange) ---------------

    def handle_pull(self, req) -> Dict[str, np.ndarray]:
        """Full value rows for sorted unique keys in this shard's range
        (pull_for_pass semantics: unseen keys return deterministic
        per-key init rows and are NOT inserted — a pure read, declared
        idempotent by the client). ``wire`` selects the emb encoding."""
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        rows = self.store.pull_for_pass(keys)
        out: Dict[str, np.ndarray] = {
            f: v for f, v in rows.items() if f != "emb"}
        out.update(encode_emb(rows["emb"], req.get("wire", "f32")))
        monitor.add("multihost/served_pull_keys", int(keys.size))
        return out

    def handle_pull_serving(self, req) -> Dict[str, np.ndarray]:
        """Serving-tier miss resolution: (found mask, w, wire-encoded
        emb) for sorted unique keys in this shard's range. A PURE read
        like ``pull`` — unseen keys are NOT inserted — but it also
        reports which keys exist (serving must answer zeros for a
        feasign training never saw, not the trainer's init row) and
        ships ONLY the serving fields (emb + w), never optimizer state:
        a replica's miss path reads a fraction of the bytes a trainer
        pull moves."""
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        found = self.store.contains(keys)
        rows = self.store.pull_for_pass(keys)
        emb = np.ascontiguousarray(rows["emb"], np.float32)
        w = np.ascontiguousarray(rows["w"], np.float32)
        if not found.all():
            # Masked rows ship zeros (cheap to compress, and the client
            # must not see init values for keys it will serve as
            # unknown anyway).
            emb[~found] = 0.0
            w[~found] = 0.0
        out: Dict[str, np.ndarray] = {"found": found, "w": w}
        out.update(encode_emb(emb, req.get("wire", "f32")))
        monitor.add("multihost/served_serving_keys", int(keys.size))
        return out

    def handle_push(self, req) -> int:
        """EndPass write-back of full rows (emb decoded from the wire
        encoding to f32 BEFORE the store write)."""
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        values = dict(req["values"])
        values["emb"] = decode_emb(values)
        for k in ("emb_f16", "emb_q", "emb_scale", "emb_width"):
            values.pop(k, None)
        self.store.push_from_pass(keys, values)
        monitor.add("multihost/served_push_keys", int(keys.size))
        return int(keys.size)

    # -- reshard protocol --------------------------------------------------

    def handle_pull_range(self, req) -> Dict[str, np.ndarray]:
        """Copy (NOT pop) of every resident row whose placement hash is
        in [lo, hi) — the read-only COPY phase of a reshard move, so a
        crash mid-move loses nothing."""
        lo, hi = int(req["lo"]), int(req["hi"])
        keys, _ = self.store.key_stats()
        mask = self.ranges.mask_in_range(keys, lo, hi)
        sel = keys[mask]
        vals = (self.store.pull_for_pass(sel) if sel.size else
                self.store.pull_for_pass(np.empty((0,), np.uint64)))
        return {"keys": sel, "values": vals}

    def handle_apply_rows(self, req) -> int:
        """Install moved rows (full-row OVERWRITE — naturally idempotent,
        so a replayed move after a crash cannot double-apply)."""
        keys = np.asarray(req["keys"], np.uint64)
        with self._mut_lock:
            self.store.push_from_pass(keys, req["values"])
        return int(keys.size)

    def handle_drop_range(self, req) -> int:
        """COMMIT phase: discard rows in [lo, hi) after every dest has
        acknowledged its copy. Idempotent (an empty range drops 0)."""
        lo, hi = int(req["lo"]), int(req["hi"])
        with self._mut_lock:
            keys, _ = self.store.key_stats()
            mask = self.ranges.mask_in_range(keys, lo, hi)
            sel = keys[mask]
            if sel.size:
                self.store.pop_rows(sel)
        return int(sel.size)

    def handle_set_range(self, req) -> bool:
        """Adopt a new range table (+ this server's index in it) — the
        last step before the drop phase of a reshard."""
        with self._mut_lock:
            self.ranges = ShardRangeTable.from_dict(req["table"])
            self.index = int(req["index"])
            self.service_name = f"shard[{self.index}]"
        return True

    # -- checkpoint / lifecycle --------------------------------------------

    def _shard_dir(self, path: str) -> str:
        d = os.path.join(path, f"hostshard-{self.index:04d}")
        os.makedirs(d, exist_ok=True)
        return d

    def handle_save(self, req) -> bool:
        mode = req.get("mode", "base")
        with self._mut_lock:
            if mode == "base":
                self.store.save_base(self._shard_dir(req["path"]))
            elif mode == "delta":
                self.store.save_delta(self._shard_dir(req["path"]))
            else:
                self.store.save_xbox(self._shard_dir(req["path"]))
        return True

    def _checkpoint_parts(self, path: str, kind: str
                          ) -> List[Tuple[np.ndarray, Dict]]:
        """Every (keys, values) part of a checkpoint FILTERED to this
        server's current range — hostshard dirs from any world size,
        plus a flat single-host dump (migration path)."""
        name = self.config.name
        files = sorted(glob.glob(os.path.join(
            path, "hostshard-*", f"{name}.{kind}.npz")))
        flat = os.path.join(path, f"{name}.{kind}.npz")
        if os.path.exists(flat):
            files.append(flat)
        if not files:
            raise FileNotFoundError(
                f"no {kind} dump for table {name!r} under {path}")
        parts = []
        lo, hi = self.ranges.range_of(self.index)
        for f in files:
            data = np.load(f)
            keys = data["keys"].astype(np.uint64)
            mask = self.ranges.mask_in_range(keys, lo, hi)
            if not mask.any():
                continue
            parts.append((keys[mask],
                          {fld: data[fld][mask] for fld in _FIELDS}))
        return parts

    def handle_load(self, req) -> int:
        """World-agnostic load: keep only rows in this server's range.
        ``base`` REPLACES contents (set_all semantics, like
        FeatureStore.load); ``delta`` applies on top."""
        path, kind = req["path"], req.get("kind", "base")
        with self._mut_lock:
            parts = self._checkpoint_parts(path, kind)
            if kind == "base":
                if parts:
                    keys = np.concatenate([k for k, _ in parts])
                    vals = {f: np.concatenate([v[f] for _, v in parts])
                            for f in _FIELDS}
                    order = np.argsort(keys, kind="stable")
                    self.store.set_all(keys[order],
                                       {f: v[order]
                                        for f, v in vals.items()})
                else:
                    self.store.reset()
            else:
                for keys, vals in parts:
                    self.store.push_from_pass(keys, vals)
        return int(self.store.num_features)

    def handle_reset(self, req) -> bool:
        with self._mut_lock:
            self.store.reset()
        return True

    def handle_shrink(self, req) -> int:
        """Day-boundary lifecycle on this shard's rows (the FeatureStore
        resolves FLAGS_table_* decay/TTL/min-show in THIS process); the
        post-shrink row count is republished as this server's gauge so
        the bounded-store story is observable per host too."""
        with self._mut_lock:
            evicted = self.store.shrink(min_show=req.get("min_show", 0.0))
        monitor.set_gauge("multihost/shard_rows",
                          float(self.store.num_features))
        return evicted

    def handle_contains(self, req) -> np.ndarray:
        """Membership mask for keys in this shard's range (pure read —
        the FeatureStore.contains surface across the wire)."""
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        return self.store.contains(keys)

    def handle_stats(self, req) -> Dict[str, int]:
        return {"num_features": int(self.store.num_features),
                "index": int(self.index),
                "world": int(self.ranges.world)}

    def handle_stop(self, req) -> bool:
        self._running = False
        return True


class ShardClient:
    """One host's client handle to a peer shard server (a thin
    FramedRPCConn wrapper declaring the idempotent reads)."""

    def __init__(self, endpoint: str, *, timeout: float = 60.0):
        self.endpoint = endpoint
        self._conn = rpc.FramedRPCConn(
            endpoint, timeout=timeout, service_name="shard",
            idempotent=("pull", "pull_serving", "pull_range", "stats",
                        "contains"))

    def call(self, method: str, **kw):
        return self._conn.call(method, **kw)

    def close(self) -> None:
        self._conn.close()


def start_local_shards(world: int, config: TableConfig, *, seed: int = 0
                       ) -> Tuple[List[ShardServer], List[str]]:
    """Loopback cluster on 127.0.0.1 ephemeral ports (tests / the
    ``bench.py multihost`` loopback mode)."""
    ranges = ShardRangeTable.for_world(world)
    servers = [ShardServer("127.0.0.1:0", i, ranges, config, seed=seed)
               for i in range(world)]
    return servers, [s.endpoint for s in servers]


def stop_shards(servers: List[ShardServer]) -> None:
    for s in servers:
        try:
            s.stop()
        except Exception as e:  # best-effort teardown
            log.vlog(1, "shard stop failed: %s", e)
