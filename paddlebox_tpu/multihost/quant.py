"""int8 per-block symmetric quantization for exchange wires.

EQuARX (PAPERS.md) shows reduced-precision collectives done with
per-block scales and full-precision accumulation lose negligible
quality; this module is that codec for BOTH wires in the repo:

- the single-host ICI all_to_all payloads
  (``FLAGS_embedding_exchange_dtype=int8`` — ``embedding/lookup.py``,
  jnp twins, traced inside the step), and
- the cross-host DCN shard pull/push
  (``FLAGS_multihost_wire_dtype=int8`` — ``multihost/shard_service.py``,
  numpy twins on the host wire).

Codec: a payload row ``[W]`` splits into ``ceil(W / block)`` blocks of
``block`` consecutive values; each block carries one f32 scale
``absmax / 127`` (zero block -> scale 1 so dequantization is exact
zeros); values quantize to round-half-even int8 in [-127, 127]. The wire
carries the int8 values UNPADDED ([n, W] — a narrow payload must not
pay a full block of padding bytes) plus the [n, nb] f32 scales; the
decoder re-pads with zeros (exact) to undo the block reshape.
Accumulation NEVER happens in int8 — both consumers widen to f32
before any add.

The numpy and jnp twins are bit-identical on the quantized payload
(same absmax, same round-half-even — pinned by tests/test_multihost.py).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def num_blocks(width: int, block: int) -> int:
    if block < 1:
        raise ValueError(f"quant block must be >= 1, got {block}")
    return -(-width // block)


def quantized_wire_bytes(rows: int, width: int, block: int) -> int:
    """Wire bytes of one quantized [rows, width] payload: int8 values
    (unpadded — the codec strips the block padding before the wire)
    + f32 per-block scales (the exchange_bytes observable)."""
    nb = num_blocks(width, block)
    return rows * width * 1 + rows * nb * 4


def quantize_blocked_np(x: np.ndarray, block: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """f32 [n, w] -> (int8 [n, w], f32 scales [n, nb])."""
    x = np.asarray(x, np.float32)
    n, w = x.shape
    nb = num_blocks(w, block)
    pad = nb * block - w
    if pad:
        x = np.pad(x, ((0, 0), (0, pad)))
    xb = x.reshape(n, nb, block)
    amax = np.abs(xb).max(axis=-1)
    scale = np.where(amax > 0, amax / np.float32(127.0),
                     np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(xb / scale[:, :, None]), -127, 127
                ).astype(np.int8)
    return q.reshape(n, nb * block)[:, :w], scale


def dequantize_blocked_np(q: np.ndarray, scales: np.ndarray, width: int,
                          block: int) -> np.ndarray:
    """(int8 [n, width], f32 [n, nb]) -> f32 [n, width]."""
    n = q.shape[0]
    nb = num_blocks(width, block)
    pad = nb * block - width
    if pad:
        q = np.pad(q, ((0, 0), (0, pad)))
    xb = q.reshape(n, nb, block).astype(np.float32) * scales[:, :, None]
    return xb.reshape(n, nb * block)[:, :width]


def quantize_blocked(x, block: int):
    """jnp twin of :func:`quantize_blocked_np` (traced in the jitted
    step — static shapes only)."""
    import jax.numpy as jnp
    n, w = x.shape
    nb = num_blocks(w, block)
    pad = nb * block - w
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    xb = x.reshape(n, nb, block)
    amax = jnp.max(jnp.abs(xb), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale[:, :, None]), -127, 127
                 ).astype(jnp.int8)
    return q.reshape(n, nb * block)[:, :w], scale


def dequantize_blocked(q, scales, width: int, block: int):
    """jnp twin of :func:`dequantize_blocked_np`."""
    import jax.numpy as jnp
    n = q.shape[0]
    nb = num_blocks(width, block)
    pad = nb * block - width
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad)))
    xb = q.reshape(n, nb, block).astype(jnp.float32) * scales[:, :, None]
    return xb.reshape(n, nb * block)[:, :width]
