"""Trainer-side multi-host store: the DCN half of the lookup exchange.

:class:`MultiHostStore` presents the FeatureStore surface to
:class:`~paddlebox_tpu.embedding.pass_engine.PassEngine`, so the
existing trainer stack gains the cross-host tier WITHOUT touching the
hot loop: within a host the jitted step keeps its ICI ``all_to_all``
exchange over the device mesh (``embedding/lookup.py``); between hosts
this store batches the whole pass's working set into ONE pull per peer
at ``begin_pass`` and one push per peer at ``end_pass`` — the DCN-aware
layout (DCN latency is paid per PASS, not per step, exactly like the
reference's BuildPull-from-PS staging, ``ps_gpu_wrapper.cc:362``).

The per-host payloads ride the ONE shared sort: pass keys arrive as the
single sorted-unique array every tier already shares (the sorted-stream
layout of PR 1/8); a stable argsort by owner makes each host's slice
CONTIGUOUS in that order, and the same plan object is reused by the
matching push (``_plan_for`` caches it), so the boundary pays one owner
argsort per pass, not one per direction.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import faults, monitor, trace
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import shard_service
from paddlebox_tpu.multihost.keyrange import ShardRangeTable
from paddlebox_tpu.multihost.shard_service import (ShardClient, decode_emb,
                                                   encode_emb,
                                                   payload_nbytes)


class _OwnerPlan:
    """One pass's owner split of the shared sorted key array: per-host
    contiguous slices of ``order`` (stable argsort by owner, so keys
    stay sorted WITHIN each slice)."""

    def __init__(self, keys: np.ndarray, table: ShardRangeTable):
        self.keys = keys
        owner = table.owner_of(keys)
        self.order = np.argsort(owner, kind="stable")
        sorted_owner = owner[self.order]
        starts = np.searchsorted(sorted_owner,
                                 np.arange(table.world + 1))
        self.slices: List[np.ndarray] = [
            self.order[starts[i]:starts[i + 1]]
            for i in range(table.world)]

    def matches(self, keys: np.ndarray, world: int) -> bool:
        return (len(self.slices) == world
                and self.keys.shape == keys.shape
                and np.array_equal(self.keys, keys))


class MultiHostStore:
    """FeatureStore-shaped client over the host-sharded shard servers."""

    #: One backing cluster shared by every rank: day-end shrink and
    #: checkpoint writes must run once (rank 0), like PSBackedStore.
    shared = True

    def __init__(self, config: TableConfig, endpoints: Sequence[str], *,
                 ranges: Optional[ShardRangeTable] = None):
        self.config = config
        from paddlebox_tpu.embedding.optimizers import make_sparse_optimizer
        self.opt = make_sparse_optimizer(config)
        self.ranges = ranges or ShardRangeTable.for_world(len(endpoints))
        if self.ranges.world != len(endpoints):
            raise ValueError(
                f"{len(endpoints)} endpoints != range table world "
                f"{self.ranges.world}")
        self.endpoints = list(endpoints)
        self._clients = [ShardClient(e) for e in self.endpoints]
        self._plan: Optional[_OwnerPlan] = None
        self._plan_lock = threading.Lock()
        monitor.set_gauge("multihost/world_size", float(self.ranges.world))

    # -- topology ----------------------------------------------------------

    @property
    def world(self) -> int:
        return self.ranges.world

    def set_topology(self, endpoints: Sequence[str],
                     ranges: ShardRangeTable) -> None:
        """Adopt a resharded cluster (new membership generation). Old
        connections close; the owner-plan cache is invalid by
        construction (world changed)."""
        if ranges.world != len(endpoints):
            raise ValueError(
                f"{len(endpoints)} endpoints != world {ranges.world}")
        old = self._clients
        self.endpoints = list(endpoints)
        self.ranges = ranges
        self._clients = [ShardClient(e) for e in self.endpoints]
        with self._plan_lock:
            self._plan = None
        for c in old:
            c.close()
        monitor.set_gauge("multihost/world_size", float(ranges.world))

    def _plan_for(self, keys: np.ndarray) -> _OwnerPlan:
        """The ONE owner argsort per pass: the pull computes it, the
        matching push (same shared sorted key array) reuses it."""
        with self._plan_lock:
            plan = self._plan
            if plan is not None and plan.matches(keys, self.ranges.world):
                return plan
            plan = _OwnerPlan(keys, self.ranges)
            self._plan = plan
            return plan

    def _fanout(self, work: List[Tuple[int, dict]], method: str) -> Dict:
        """Issue one RPC per non-empty peer slice concurrently (the DCN
        fan-out); raise the first error — a lost shard must fail the
        pass loudly, never return garbage rows."""
        results: Dict[int, object] = {}
        errs: List[BaseException] = []

        def run(host: int, kw: dict) -> None:
            try:
                results[host] = self._clients[host].call(method, **kw)
            except BaseException as e:
                errs.append(e)

        if len(work) == 1:
            run(*work[0])
        else:
            ts = [threading.Thread(target=run, args=(h, kw), daemon=True)
                  for h, kw in work]
            [t.start() for t in ts]
            [t.join() for t in ts]
        if errs:
            raise errs[0]
        return results

    # -- pass build surface ------------------------------------------------

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        faults.faultpoint("multihost/shard_pull")
        keys = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        wire = shard_service.wire_mode()
        plan = self._plan_for(keys)
        n = keys.shape[0]
        work = [(h, {"keys": keys[idx], "wire": wire})
                for h, idx in enumerate(plan.slices) if idx.size]
        if not work:
            # Empty pass: preserve the FeatureStore contract of fully
            # shaped (0, ...) field arrays.
            return self._empty_rows()
        with trace.span("multihost/shard_pull", keys=n,
                        world=self.ranges.world):
            results = self._fanout(work, "pull")
        out: Optional[Dict[str, np.ndarray]] = None
        rx_bytes = 0
        for h, idx in enumerate(plan.slices):
            if not idx.size:
                continue
            res = results[h]
            rx_bytes += payload_nbytes(res)
            res = dict(res)
            res["emb"] = decode_emb(res)
            for k in ("emb_f16", "emb_q", "emb_scale", "emb_width"):
                res.pop(k, None)
            if out is None:
                out = {f: np.empty((n,) + v.shape[1:], v.dtype)
                       for f, v in res.items()}
            for f, v in res.items():
                out[f][idx] = v
        monitor.add("multihost/pull_keys", n)
        monitor.add("multihost/pull_bytes", rx_bytes)
        monitor.set_gauge(
            "multihost/wire_bits",
            {"f32": 32.0, "f16": 16.0, "int8": 8.0}[wire])
        return out

    def _empty_rows(self) -> Dict[str, np.ndarray]:
        d = self.config.dim
        ke = self.opt.emb_state_width(d)
        kw = self.opt.w_state_width()
        return {"emb": np.empty((0, d), np.float32),
                "emb_state": np.empty((0, ke), np.float32),
                "w": np.empty((0,), np.float32),
                "w_state": np.empty((0, kw), np.float32),
                "show": np.empty((0,), np.float32),
                "click": np.empty((0,), np.float32)}

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray]) -> None:
        faults.faultpoint("multihost/shard_push")
        keys = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        wire = shard_service.wire_mode()
        plan = self._plan_for(keys)
        work = []
        tx_bytes = 0
        for h, idx in enumerate(plan.slices):
            if not idx.size:
                continue
            vals = {f: v[idx] for f, v in values.items()}
            payload = {f: v for f, v in vals.items() if f != "emb"}
            payload.update(encode_emb(vals["emb"], wire))
            tx_bytes += payload_nbytes(payload)
            work.append((h, {"keys": keys[idx], "values": payload}))
        with trace.span("multihost/shard_push", keys=int(keys.shape[0]),
                        world=self.ranges.world):
            if work:
                self._fanout(work, "push")
        monitor.add("multihost/push_keys", int(keys.shape[0]))
        monitor.add("multihost/push_bytes", tx_bytes)

    # -- size / maintenance ------------------------------------------------

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """Membership mask across the shard cluster (pure read; any key
        order — each key is asked of its owner only)."""
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros(k.shape, bool)
        if k.size == 0:
            return out
        owner = self.ranges.owner_of(k)
        work = [(h, {"keys": k[owner == h]}) for h in range(self.world)
                if (owner == h).any()]
        results = self._fanout(work, "contains")
        for h, _kw in work:
            out[owner == h] = np.asarray(results[h], bool)
        return out

    @property
    def num_features(self) -> int:
        return int(sum(s["num_features"]
                       for s in self._fanout(
                           [(h, {}) for h in range(self.world)],
                           "stats").values()))

    def shrink(self, *, min_show: float = 0.0) -> int:
        """Day-boundary lifecycle runs PER SHARD on the owning server
        (its local FeatureStore resolves the FLAGS_table_* decay/TTL/
        min-show policy from that process's flags), then the post-shrink
        row counts are republished so the operator reads the bounded
        store size from one gauge, not a per-host scrape."""
        evicted = int(sum(self._fanout(
            [(h, {"min_show": min_show}) for h in range(self.world)],
            "shrink").values()))
        rows = self.num_features  # one stats fan-out, post-shrink
        monitor.set_gauge("multihost/rows", float(rows))
        return evicted

    def reset(self) -> None:
        """Pass-retry rollback surface: wipe every shard (the recovery
        chain reload that follows re-filters rows by range)."""
        self._fanout([(h, {}) for h in range(self.world)], "reset")
        with self._plan_lock:
            self._plan = None

    # -- checkpoint surface ------------------------------------------------

    def save_base(self, path: str) -> None:
        self._fanout([(h, {"path": path, "mode": "base"})
                      for h in range(self.world)], "save")
        self._write_meta(path, "base")

    def save_delta(self, path: str) -> None:
        self._fanout([(h, {"path": path, "mode": "delta"})
                      for h in range(self.world)], "save")
        self._write_meta(path, "delta")

    def save_xbox(self, path: str) -> int:
        self._fanout([(h, {"path": path, "mode": "xbox"})
                      for h in range(self.world)], "save")
        self._write_meta(path, "xbox")
        return self.num_features

    def _write_meta(self, path: str, kind: str) -> None:
        import json
        import os
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(
                path, f"{self.config.name}.multihost.json"), "w") as f:
            json.dump({"world": self.world, "kind": kind,
                       "table": self.config.name,
                       "ranges": self.ranges.to_dict()}, f)

    def load(self, path: str, kind: str = "base") -> None:
        self._fanout([(h, {"path": path, "kind": kind})
                      for h in range(self.world)], "load")

    def stop_servers(self) -> None:
        try:
            self._fanout([(h, {}) for h in range(self.world)], "stop")
        except Exception:
            pass

    def close(self) -> None:
        for c in self._clients:
            c.close()
