"""Trainer-side multi-host store: the DCN half of the lookup exchange.

:class:`MultiHostStore` presents the FeatureStore surface to
:class:`~paddlebox_tpu.embedding.pass_engine.PassEngine`, so the
existing trainer stack gains the cross-host tier WITHOUT touching the
hot loop: within a host the jitted step keeps its ICI ``all_to_all``
exchange over the device mesh (``embedding/lookup.py``); between hosts
this store batches the whole pass's working set into ONE pull per peer
at ``begin_pass`` and one push per peer at ``end_pass`` — the DCN-aware
layout (DCN latency is paid per PASS, not per step, exactly like the
reference's BuildPull-from-PS staging, ``ps_gpu_wrapper.cc:362``).

The per-host payloads ride the ONE shared sort: pass keys arrive as the
single sorted-unique array every tier already shares (the sorted-stream
layout of PR 1/8); a stable argsort by owner makes each host's slice
CONTIGUOUS in that order, and the same plan object is reused by the
matching push (``_plan_for`` caches it), so the boundary pays one owner
argsort per pass, not one per direction.

Replication (``FLAGS_multihost_replicas`` > 1 / ``replica_map=``): each
slot's client conn carries a ``resolve`` hook wired to the CURRENT
replica set, so the conn-level idempotent retry lands a failed pull on
the next live replica instead of burning the retry deadline on a dead
primary — a shard-host kill -9 under live traffic costs one reconnect
on reads. A push reaching a non-primary replica surfaces the server's
LOUD ``STALE_PRIMARY`` as a TRANSIENT
:class:`~paddlebox_tpu.multihost.replication.StalePrimaryError`: the
pass-retry loop re-resolves the topology (the repair controller's
promotion, ``multihost/reshard.py``) and replays — a retry, not a lost
range. ``replicas == 1`` (default) builds no map and is bit-identical
to the pre-replication client.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import faults, monitor, trace
from paddlebox_tpu.embedding.table import TableConfig
from paddlebox_tpu.multihost import shard_service
from paddlebox_tpu.multihost.keyrange import ShardRangeTable
from paddlebox_tpu.multihost.replication import ReplicaMap, StalePrimaryError
from paddlebox_tpu.multihost.shard_service import (ShardClient, decode_emb,
                                                   encode_emb,
                                                   payload_nbytes)


class _OwnerPlan:
    """One pass's owner split of the shared sorted key array: per-host
    contiguous slices of ``order`` (stable argsort by owner, so keys
    stay sorted WITHIN each slice)."""

    def __init__(self, keys: np.ndarray, table: ShardRangeTable):
        self.keys = keys
        owner = table.owner_of(keys)
        self.order = np.argsort(owner, kind="stable")
        sorted_owner = owner[self.order]
        starts = np.searchsorted(sorted_owner,
                                 np.arange(table.world + 1))
        self.slices: List[np.ndarray] = [
            self.order[starts[i]:starts[i + 1]]
            for i in range(table.world)]

    def matches(self, keys: np.ndarray, world: int) -> bool:
        return (len(self.slices) == world
                and self.keys.shape == keys.shape
                and np.array_equal(self.keys, keys))


class _ExchangeJob:
    """Handle of one background exchange job (a queued boundary push):
    ``wait()`` blocks until the worker ran it and re-raises its error
    in the caller — the pass-retry loop, not the worker thread, owns
    failure classification."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._err: Optional[BaseException] = None
        self.busy_ms = 0.0

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> None:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not self._done.wait(timeout=0.5):
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("exchange job did not complete")
        if self._err is not None:
            raise self._err


_DONE_JOB = _ExchangeJob()
_DONE_JOB._done.set()


class _ExchangeWorker:
    """The ONE background exchange thread: a FIFO of whole push jobs
    drained in order, so overlapped pushes commute with nothing — a
    job either ran completely or has not started (no torn peer state;
    cancel never drops a queued push). ``drain()`` is the ordering
    barrier pulls take before touching rows a queued push may still
    own."""

    def __init__(self) -> None:
        self._q: "queue.Queue[Optional[Tuple[Callable[[], None], _ExchangeJob]]]" = (
            queue.Queue())
        self._lock = threading.Lock()
        self._busy_ms = 0.0
        self._pending = 0
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(
            target=self._run, name="multihost-exchange", daemon=True)
        self._thread.start()

    def submit(self, fn: Callable[[], None]) -> _ExchangeJob:
        job = _ExchangeJob()
        with self._lock:
            self._pending += 1
            self._idle.clear()
        self._q.put((fn, job))
        return job

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, job = item
            t0 = time.perf_counter()
            try:
                fn()
            except BaseException as e:
                job._err = e
            finally:
                job.busy_ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    self._busy_ms += job.busy_ms
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()
                job._done.set()

    def busy_ms(self) -> float:
        with self._lock:
            return self._busy_ms

    def drain(self, timeout: Optional[float] = None) -> None:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not self._idle.wait(timeout=0.5):
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("exchange worker drain timed out")

    def stop(self) -> None:
        self.drain()
        self._q.put(None)
        self._thread.join(timeout=5.0)


def _raise_translated(e: BaseException) -> None:
    """Server-side STALE_PRIMARY crosses the wire as a generic in-band
    RuntimeError — rebuild the typed (transient) error so the pass-retry
    loop classifies it correctly."""
    if isinstance(e, RuntimeError) and "STALE_PRIMARY" in str(e):
        raise StalePrimaryError(str(e)) from e
    raise e


class MultiHostStore:
    """FeatureStore-shaped client over the host-sharded shard servers."""

    #: One backing cluster shared by every rank: day-end shrink and
    #: checkpoint writes must run once (rank 0), like PSBackedStore.
    shared = True

    def __init__(self, config: TableConfig, endpoints: Sequence[str], *,
                 ranges: Optional[ShardRangeTable] = None,
                 replicas: Optional[int] = None,
                 replica_map: Optional[ReplicaMap] = None):
        from paddlebox_tpu.core import flags
        self.config = config
        from paddlebox_tpu.embedding.optimizers import make_sparse_optimizer
        self.opt = make_sparse_optimizer(config)
        self._replicas = int(replicas if replicas is not None
                             else flags.flag("multihost_replicas"))
        if replica_map is not None:
            self.replica_map: Optional[ReplicaMap] = replica_map
            self._replicas = max(self._replicas,
                                 replica_map.replication)
        elif self._replicas > 1:
            self.replica_map = ReplicaMap.ring(
                endpoints, self._replicas,
                ranges or ShardRangeTable.for_world(len(endpoints)))
        else:
            self.replica_map = None
        if self.replica_map is not None:
            self.ranges = self.replica_map.table
            self.endpoints = self.replica_map.primaries()
        else:
            self.ranges = ranges or ShardRangeTable.for_world(
                len(endpoints))
            if self.ranges.world != len(endpoints):
                raise ValueError(
                    f"{len(endpoints)} endpoints != range table world "
                    f"{self.ranges.world}")
            self.endpoints = list(endpoints)
        self._clients = self._build_clients()
        # Endpoint-keyed admin conns (save/load/reset/shrink/stop):
        # distinct from the per-slot data clients so a backup-only host
        # is still reachable for cluster-wide maintenance.
        self._admin_clients: Dict[str, ShardClient] = {}
        # Owner-plan cache keyed by pass id (the pull computes a pass's
        # plan, the matching partial pulls and push reuse it; an
        # interleaved admin fan-out can no longer evict it — the
        # single-entry cache of the pre-overlap tier could).
        self._plans: "OrderedDict[object, _OwnerPlan]" = OrderedDict()
        self._plan_seq = 0
        self._plan_lock = threading.Lock()
        # Background exchange worker (FLAGS_multihost_overlap_exchange):
        # lazily started by the first async push; wait/busy are the
        # overlap accounting behind boundary.exchange_overlap_frac.
        self._exchange: Optional[_ExchangeWorker] = None
        self._exchange_lock = threading.Lock()
        self._exchange_wait_ms = 0.0
        self._exchange_jobs: List[_ExchangeJob] = []
        monitor.set_gauge("multihost/world_size", float(self.ranges.world))
        if self.replica_map is not None:
            monitor.set_gauge("multihost/replication",
                              float(self.replica_map.replication))

    # -- topology ----------------------------------------------------------

    def _build_clients(self) -> List[ShardClient]:
        return [ShardClient(self.endpoints[slot],
                            replicas_fn=self._replicas_fn(slot))
                for slot in range(self.ranges.world)]

    def _replicas_fn(self, slot: int):
        if self.replica_map is None:
            return None

        def fn() -> Tuple[str, ...]:
            m = self.replica_map
            return m.replicas_of(slot) if m is not None else ()
        return fn

    @property
    def world(self) -> int:
        return self.ranges.world

    def set_topology(self, endpoints: Sequence[str],
                     ranges: ShardRangeTable) -> None:
        """Adopt a resharded cluster (new membership generation). Old
        connections close; the owner-plan cache is invalid by
        construction (world changed)."""
        if ranges.world != len(endpoints):
            raise ValueError(
                f"{len(endpoints)} endpoints != world {ranges.world}")
        if self.replica_map is not None:
            self.set_replica_map(
                ReplicaMap.ring(endpoints, self._replicas, ranges))
            return
        old = self._clients
        self.endpoints = list(endpoints)
        self.ranges = ranges
        self._clients = self._build_clients()
        with self._plan_lock:
            self._plans.clear()
        for c in old:
            c.close()
        monitor.set_gauge("multihost/world_size", float(ranges.world))

    def set_replica_map(self, rmap: ReplicaMap) -> None:
        """Adopt a repaired/promoted replica-map generation (same slot
        count; endpoints re-pointed). The owner plan survives when the
        bounds are unchanged — only the clients re-bind."""
        old = self._clients
        same_bounds = rmap.table.bounds == self.ranges.bounds
        self.replica_map = rmap
        self.ranges = rmap.table
        self.endpoints = rmap.primaries()
        self._clients = self._build_clients()
        if not same_bounds:
            with self._plan_lock:
                self._plans.clear()
        for c in old:
            c.close()
        live = set(rmap.all_endpoints())
        for ep in list(self._admin_clients):
            if ep not in live:
                self._admin_clients.pop(ep).close()
        monitor.set_gauge("multihost/world_size", float(rmap.world))
        monitor.set_gauge("multihost/replication",
                          float(rmap.replication))

    _PLAN_CACHE = 4

    def _plan_for(self, keys: np.ndarray,
                  pass_id: Optional[int] = None) -> _OwnerPlan:
        """The ONE owner argsort per pass: the pull computes it, the
        matching partial pulls and push (same shared sorted key array,
        same ``pass_id``) reuse it. Every re-derivation counts on
        ``multihost/plan_misses`` — a steady-state pass pays exactly
        one."""
        with self._plan_lock:
            if pass_id is not None:
                plan = self._plans.get(("pass", pass_id))
                if (plan is not None
                        and plan.matches(keys, self.ranges.world)):
                    self._plans.move_to_end(("pass", pass_id))
                    return plan
            for k in reversed(self._plans):
                plan = self._plans[k]
                if plan.matches(keys, self.ranges.world):
                    self._plans.move_to_end(k)
                    return plan
            monitor.add("multihost/plan_misses", 1)
            plan = _OwnerPlan(keys, self.ranges)
            if pass_id is not None:
                key: object = ("pass", pass_id)
            else:
                self._plan_seq += 1
                key = ("anon", self._plan_seq)
            self._plans[key] = plan
            while len(self._plans) > self._PLAN_CACHE:
                self._plans.popitem(last=False)
            return plan

    def _fanout(self, work: List[Tuple[int, dict]], method: str) -> Dict:
        """Issue one RPC per non-empty peer slice concurrently (the DCN
        fan-out) by PIPELINING on the slots' mux'd conns — all requests
        go on the wire back-to-back from this thread, then the replies
        are collected (PR 16: no per-peer helper threads, and the
        caller's trace context rides each send naturally). Raise the
        first error — a lost shard must fail the pass loudly, never
        return garbage rows (a dead-primary write surfaces as a
        TRANSIENT StalePrimaryError so the pass retry re-resolves and
        replays)."""
        results: Dict[int, object] = {}
        errs: List[Tuple[int, BaseException]] = []
        if len(work) == 1:
            h, kw = work[0]
            try:
                results[h] = self._clients[h].call(method, **kw)
            except BaseException as e:
                errs.append((h, e))
        else:
            futs = []
            for h, kw in work:
                try:
                    futs.append(
                        (h, self._clients[h].call_async(method, **kw)))
                except BaseException as e:
                    errs.append((h, e))
            for h, f in futs:
                try:
                    results[h] = f.result()
                except BaseException as e:
                    errs.append((h, e))
        if errs:
            for h, e in errs:
                if isinstance(e, RuntimeError) and "STALE_PRIMARY" in str(e):
                    # The slot conn drifted onto a backup (sticky read
                    # failover) or the map is stale: re-bind it to the
                    # current primary so the pass retry's replay does
                    # not re-hit the same stale target.
                    old = self._clients[h]
                    self._clients[h] = ShardClient(
                        self.endpoints[h],
                        replicas_fn=self._replicas_fn(h))
                    old.close()
            _raise_translated(errs[0][1])
        return results

    # -- background exchange worker ---------------------------------------

    def _exchange_worker(self) -> _ExchangeWorker:
        with self._exchange_lock:
            if self._exchange is None:
                self._exchange = _ExchangeWorker()
            return self._exchange

    def _submit_exchange(self, fn: Callable[[], None]) -> _ExchangeJob:
        job = self._exchange_worker().submit(fn)
        with self._exchange_lock:
            self._exchange_jobs.append(job)
        return job

    def _drain_exchange(self, *, swallow: bool = False) -> None:
        """Barrier on the exchange worker: every queued push completes
        before the caller proceeds (pulls and admin/maintenance ops may
        otherwise observe a peer mid-overwrite). The blocked time is
        the 'not overlapped' half of exchange_overlap_frac."""
        w = self._exchange
        if w is None:
            return
        t0 = time.perf_counter()
        w.drain()
        with self._exchange_lock:
            self._exchange_wait_ms += (time.perf_counter() - t0) * 1e3
            jobs, self._exchange_jobs = self._exchange_jobs, []
        errs = [j._err for j in jobs if j._err is not None]
        if errs and not swallow:
            _raise_translated(errs[0])

    def exchange_stats(self) -> Dict[str, float]:
        """Cumulative overlap accounting of the background exchange:
        ``exchange_busy_ms`` (worker time spent moving bytes) and
        ``exchange_wait_ms`` (caller time blocked on the worker). Their
        complement-ratio is exchange_overlap_frac — 1.0 means every
        background byte moved while the caller was doing other work."""
        w = self._exchange
        with self._exchange_lock:
            wait = self._exchange_wait_ms
        return {"exchange_busy_ms": w.busy_ms() if w else 0.0,
                "exchange_wait_ms": wait}

    def exchange_overlap_frac(self) -> float:
        s = self.exchange_stats()
        if s["exchange_busy_ms"] <= 0.0:
            return 1.0
        return max(0.0, min(1.0, 1.0 - s["exchange_wait_ms"]
                            / s["exchange_busy_ms"]))

    def _admin_eps(self) -> List[str]:
        """Every distinct server process — primaries AND backup-only
        hosts (a freshly re-replicated host leads no slot yet but must
        still see reset/load/save/stop)."""
        if self.replica_map is not None:
            return self.replica_map.all_endpoints()
        return list(dict.fromkeys(self.endpoints))

    def _ep_client(self, ep: str) -> ShardClient:
        c = self._admin_clients.get(ep)
        if c is None:
            c = self._admin_clients[ep] = ShardClient(ep)
        return c

    def _admin_fanout(self, kw: dict, method: str) -> Dict[str, object]:
        """One RPC per distinct server, pipelined like :meth:`_fanout`;
        first error raises (admin ops — save/load/reset/shrink — must
        cover the whole cluster or fail loudly). Always barriers on the
        exchange worker: a bulk push still in flight during a save or
        shrink would be a lost (or doubly-lifecycled) write."""
        self._drain_exchange(swallow=(method in ("reset", "stop")))
        eps = self._admin_eps()
        results: Dict[str, object] = {}
        errs: List[BaseException] = []
        if len(eps) == 1:
            try:
                results[eps[0]] = self._ep_client(eps[0]).call(
                    method, **kw)
            except BaseException as e:
                errs.append(e)
        else:
            futs = []
            for ep in eps:
                try:
                    futs.append(
                        (ep, self._ep_client(ep).call_async(method, **kw)))
                except BaseException as e:
                    errs.append(e)
            for ep, f in futs:
                try:
                    results[ep] = f.result()
                except BaseException as e:
                    errs.append(e)
        if errs:
            _raise_translated(errs[0])
        return results

    # -- pass build surface ------------------------------------------------

    def pull_for_pass(self, pass_keys_sorted: np.ndarray,
                      select: Optional[np.ndarray] = None, *,
                      pass_id: Optional[int] = None,
                      barrier: bool = True,
                      boundary: bool = False) -> Dict[str, np.ndarray]:
        """Pull rows for a pass's sorted key array — ONE coalesced RPC
        per owning peer. ``select`` (bool mask over the FULL key array)
        pulls only the masked subset while still slicing from the one
        full-array owner plan, so the split-build partial pulls share
        the plan (and the push reuses it via ``pass_id``) instead of
        re-deriving an argsort per sub-pull. Rows return compacted in
        key order of the selected subset.

        ``barrier`` (default) drains the background exchange first —
        a queued push may still own rows this pull reads. The boundary
        shared-remainder pull passes ``barrier=False``: its keys are
        disjoint from every queued bulk push by construction (bulk =
        previous-pass keys NOT in the pending pass). ``boundary=True``
        counts the fan-out on ``multihost/boundary_pulls`` — the pin
        that each boundary pays one coalesced pull round."""
        faults.faultpoint("multihost/shard_pull")
        keys = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        if barrier:
            self._drain_exchange()
        wire = shard_service.wire_mode()
        plan = self._plan_for(keys, pass_id)
        n = keys.shape[0]
        if select is None:
            slices: List[np.ndarray] = list(plan.slices)
            pos: Optional[np.ndarray] = None
            n_out = n
        else:
            sel = np.asarray(select, bool)
            sel_idx = np.flatnonzero(sel)
            pos = np.empty(n, np.int64)
            pos[sel_idx] = np.arange(sel_idx.size)
            slices = [idx[sel[idx]] for idx in plan.slices]
            n_out = int(sel_idx.size)
        work = [(h, {"keys": keys[idx], "wire": wire})
                for h, idx in enumerate(slices) if idx.size]
        if not work:
            # Empty pass: preserve the FeatureStore contract of fully
            # shaped (0, ...) field arrays.
            return self._empty_rows()
        if boundary:
            monitor.add("multihost/boundary_pulls", 1)
        with trace.span("multihost/shard_pull", keys=n_out,
                        world=self.ranges.world):
            results = self._fanout(work, "pull")
        out: Optional[Dict[str, np.ndarray]] = None
        rx_bytes = 0
        for h, idx in enumerate(slices):
            if not idx.size:
                continue
            res = results[h]
            rx_bytes += payload_nbytes(res)
            res = dict(res)
            res["emb"] = decode_emb(res)
            for k in ("emb_f16", "emb_q", "emb_scale", "emb_width"):
                res.pop(k, None)
            if out is None:
                out = {f: np.empty((n_out,) + v.shape[1:], v.dtype)
                       for f, v in res.items()}
            dst = idx if pos is None else pos[idx]
            for f, v in res.items():
                out[f][dst] = v
        monitor.add("multihost/pull_keys", n_out)
        monitor.add("multihost/pull_bytes", rx_bytes)
        monitor.set_gauge(
            "multihost/wire_bits",
            {"f32": 32.0, "f16": 16.0, "int8": 8.0}[wire])
        return out

    def _empty_rows(self) -> Dict[str, np.ndarray]:
        d = self.config.dim
        ke = self.opt.emb_state_width(d)
        kw = self.opt.w_state_width()
        return {"emb": np.empty((0, d), np.float32),
                "emb_state": np.empty((0, ke), np.float32),
                "w": np.empty((0,), np.float32),
                "w_state": np.empty((0, kw), np.float32),
                "show": np.empty((0,), np.float32),
                "click": np.empty((0,), np.float32)}

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray],
                       select: Optional[np.ndarray] = None, *,
                       pass_id: Optional[int] = None,
                       barrier: bool = True) -> None:
        """Write back a pass's rows — one coalesced RPC per owning
        peer, slicing the SAME owner plan the pull built (``pass_id``).
        ``select`` pushes only the masked rows (``values`` stays the
        full [n] arrays); ``barrier`` keeps a direct push FIFO-ordered
        behind queued background pushes (the async path passes False —
        its slices are disjoint from the queue by construction)."""
        faults.faultpoint("multihost/shard_push")
        keys = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        if barrier:
            self._drain_exchange()
        wire = shard_service.wire_mode()
        plan = self._plan_for(keys, pass_id)
        if select is None:
            slices: List[np.ndarray] = list(plan.slices)
            n_out = int(keys.shape[0])
        else:
            sel = np.asarray(select, bool)
            slices = [idx[sel[idx]] for idx in plan.slices]
            n_out = int(np.count_nonzero(sel))
        work = []
        tx_bytes = 0
        for h, idx in enumerate(slices):
            if not idx.size:
                continue
            vals = {f: v[idx] for f, v in values.items()}
            payload = {f: v for f, v in vals.items() if f != "emb"}
            payload.update(encode_emb(vals["emb"], wire))
            tx_bytes += payload_nbytes(payload)
            work.append((h, {"keys": keys[idx], "values": payload}))
        with trace.span("multihost/shard_push", keys=n_out,
                        world=self.ranges.world):
            if work:
                self._fanout(work, "push")
        monitor.add("multihost/push_keys", n_out)
        monitor.add("multihost/push_bytes", tx_bytes)

    def push_from_pass_async(self, pass_keys_sorted: np.ndarray,
                             values: Dict[str, np.ndarray], *,
                             priority_select: Optional[np.ndarray] = None,
                             pass_id: Optional[int] = None
                             ) -> _ExchangeJob:
        """end_pass write-back with the boundary taken off the critical
        path: the ``priority_select`` rows (the ones the PENDING pass
        pulls back at its boundary — previous ∩ next keys) push
        synchronously here, and the disjoint bulk remainder drains on
        the background exchange worker while the next pass trains.
        Pushes are full-row overwrites keyed by the cached owner plan,
        so this reordering cannot change any result — only when each
        byte moves. With ``FLAGS_multihost_overlap_exchange`` off (or
        no priority info and no worker benefit) the whole push runs
        synchronously; the returned job is always waitable."""
        from paddlebox_tpu.core import flags
        if (not bool(flags.flag("multihost_overlap_exchange"))
                or priority_select is None):
            # Overlap off — or no pending-pass key info, so no proof
            # which rows the next boundary pull needs: push everything
            # synchronously (a whole-pass push queued behind the
            # boundary could be read stale by a barrier-free shared
            # pull).
            self.push_from_pass(pass_keys_sorted, values,
                                pass_id=pass_id)
            return _DONE_JOB
        keys = np.ascontiguousarray(pass_keys_sorted, np.uint64)
        pri = np.asarray(priority_select, bool)
        if pri.any():
            # Disjoint from every queued bulk push (those are
            # earlier-pass keys NOT in the pass these rows belong to),
            # so no FIFO barrier needed.
            self.push_from_pass(keys, values, pri, pass_id=pass_id,
                                barrier=False)
        bulk = ~pri
        if not bulk.any():
            return _DONE_JOB

        def run() -> None:
            self.push_from_pass(keys, values, bulk, pass_id=pass_id,
                                barrier=False)
        return self._submit_exchange(run)

    # -- size / maintenance ------------------------------------------------

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """Membership mask across the shard cluster (pure read; any key
        order — each key is asked of its owner only)."""
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros(k.shape, bool)
        if k.size == 0:
            return out
        self._drain_exchange()
        owner = self.ranges.owner_of(k)
        work = [(h, {"keys": k[owner == h]}) for h in range(self.world)
                if (owner == h).any()]
        results = self._fanout(work, "contains")
        for h, _kw in work:
            out[owner == h] = np.asarray(results[h], bool)
        return out

    def unseen_for(self, keys: np.ndarray) -> np.ndarray:
        """Unseen-days TTL ages across the shard cluster (pure read;
        any key order — each key is asked of its owner only)."""
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.zeros(k.shape, np.int32)
        if k.size == 0:
            return out
        self._drain_exchange()
        owner = self.ranges.owner_of(k)
        work = [(h, {"keys": k[owner == h]}) for h in range(self.world)
                if (owner == h).any()]
        results = self._fanout(work, "unseen_for")
        for h, _kw in work:
            out[owner == h] = np.asarray(results[h], np.int32)
        return out

    def key_stats(self) -> Tuple[np.ndarray, np.ndarray]:
        """(keys, show) across the shard cluster, key-sorted — the
        FeatureStore surface drills/exports walk (pure read)."""
        parts = self._admin_fanout({}, "key_stats").values()
        keys = np.concatenate(
            [np.asarray(p["keys"], np.uint64) for p in parts])
        show = np.concatenate(
            [np.asarray(p["show"], np.float32) for p in parts])
        order = np.argsort(keys, kind="stable")
        return keys[order], show[order]

    @property
    def num_features(self) -> int:
        return int(sum(s["num_features"]
                       for s in self._admin_fanout({}, "stats").values()))

    def shrink(self, *, min_show: float = 0.0) -> int:
        """Day-boundary lifecycle runs PER SHARD on the owning server
        (its local FeatureStore resolves the FLAGS_table_* decay/TTL/
        min-show policy from that process's flags and forwards the
        resolved numbers to its backups), then the post-shrink row
        counts are republished so the operator reads the bounded store
        size from one gauge, not a per-host scrape."""
        evicted = int(sum(self._admin_fanout(
            {"min_show": min_show}, "shrink").values()))
        rows = self.num_features  # one stats fan-out, post-shrink
        monitor.set_gauge("multihost/rows", float(rows))
        return evicted

    def sync_replicas(self) -> Dict[int, Dict[str, int]]:
        """Force every slot's backups to the journal head (boundary
        quiesce for drills/benches; no-op sans replication)."""
        if self.replica_map is None:
            return {}
        self._drain_exchange()
        out: Dict[int, Dict[str, int]] = {}
        for slot in range(self.world):
            if len(self.replica_map.replicas_of(slot)) > 1:
                out[slot] = self._clients[slot].call(
                    "sync_replicas", slot=slot)
        return out

    def reset(self) -> None:
        """Pass-retry rollback surface: wipe every shard (the recovery
        chain reload that follows re-filters rows by range)."""
        self._admin_fanout({}, "reset")
        with self._plan_lock:
            self._plans.clear()

    # -- checkpoint surface ------------------------------------------------

    def save_base(self, path: str) -> None:
        self._admin_fanout({"path": path, "mode": "base"}, "save")
        self._write_meta(path, "base")

    def save_delta(self, path: str) -> None:
        self._admin_fanout({"path": path, "mode": "delta"}, "save")
        self._write_meta(path, "delta")

    def save_xbox(self, path: str) -> int:
        self._admin_fanout({"path": path, "mode": "xbox"}, "save")
        self._write_meta(path, "xbox")
        return self.num_features

    def _write_meta(self, path: str, kind: str) -> None:
        import json
        import os
        os.makedirs(path, exist_ok=True)
        meta = {"world": self.world, "kind": kind,
                "table": self.config.name,
                "ranges": self.ranges.to_dict()}
        if self.replica_map is not None:
            meta["replica_map"] = self.replica_map.to_dict()
        with open(os.path.join(
                path, f"{self.config.name}.multihost.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str, kind: str = "base") -> None:
        self._admin_fanout({"path": path, "kind": kind}, "load")

    def stop_servers(self) -> None:
        try:
            self._admin_fanout({}, "stop")
        except Exception:
            pass

    def close(self) -> None:
        with self._exchange_lock:
            w, self._exchange = self._exchange, None
            self._exchange_jobs = []
        if w is not None:
            try:
                w.stop()
            except Exception:
                pass
        for c in self._clients:
            c.close()
        for c in self._admin_clients.values():
            c.close()
        self._admin_clients = {}
