"""Replicated shard tier: replica placement map + delta journal.

The ShardServer tier (shard_service.py) is the backbone of both
training (MultiHostStore) and serving (ShardBackedStore); without
replication one SIGKILL'd shard host loses its whole key range until an
operator reloads a checkpoint. This module holds the two pure data
structures the replicated tier is built from — the wiring lives in
shard_service/store/reshard:

- :class:`ReplicaMap`: the membership-generation assignment of each key
  range SLOT to an ordered endpoint list (primary first, then backups
  on DISTINCT hosts — ring placement, slot i's j-th backup is the host
  that is primary of slot ``(i+j) % world``). The range BOUNDS
  (:class:`~paddlebox_tpu.multihost.keyrange.ShardRangeTable`) never
  change on host loss: fail-over repair only re-points a slot's
  endpoints, so the re-replication transfer is bounded by the dead
  host's R slots — never a full-table reshuffle ("Memory-efficient
  array redistribution", PAPERS.md: the moved set is the measure of the
  assignment delta, and endpoint re-pointing keeps that measure at the
  failed host's share).

- :class:`DeltaJournal`: the primary's per-slot sequence-numbered
  mutation log. Every applied write (push / apply_rows / shrink) gets
  ``seq += 1`` and forwards to the backups synchronously; a backup that
  was briefly unreachable catches up by replaying ``since(its_seq)``
  instead of a full range COPY — bounded by
  ``FLAGS_multihost_journal_entries``, past which catch-up degrades to
  the full snapshot (the bounded-re-replication contract).

``replicas == 1`` constructs trivial single-endpoint maps and never
touches the journal: the tier is bit-identical to the pre-replication
code path (pinned by tests/test_replication.py).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.multihost.keyrange import ShardRangeTable


class StalePrimaryError(RuntimeError):
    """A write reached a server that is not (or no longer) the primary
    of the keys' range — the client's replica map is stale (a failover
    promotion or repair happened, or reads failed over and a push chased
    them). LOUD by design, and TRANSIENT: the pass-retry loop re-resolves
    the replica set through the elastic rank table and replays."""

    transient = True


def ring_assignment(endpoints: Sequence[str], replicas: int
                    ) -> List[Tuple[str, ...]]:
    """Slot i -> (endpoints[i], endpoints[i+1], ... R entries) — the
    ring placement that puts every slot's copies on DISTINCT hosts.
    ``replicas`` is clamped to the world size (a 2-host world cannot
    hold 3 distinct copies)."""
    world = len(endpoints)
    r = max(1, min(int(replicas), world))
    return [tuple(endpoints[(i + j) % world] for j in range(r))
            for i in range(world)]


@dataclasses.dataclass(frozen=True)
class ReplicaMap:
    """One membership generation's slot → ordered-endpoints assignment.

    ``assignment[slot][0]`` is the primary; the rest are backups in
    catch-up preference order. Slots are the ranges of ``table`` (the
    slot COUNT is fixed for the life of the replicated cluster — hosts
    come and go under it via promotion/repair; elastic world RESIZING
    remains the replicas=1 reshard path)."""

    table: ShardRangeTable
    assignment: Tuple[Tuple[str, ...], ...]

    def __post_init__(self):
        if len(self.assignment) != self.table.world:
            raise ValueError(
                f"{len(self.assignment)} slot assignments != "
                f"{self.table.world} ranges")
        for slot, eps in enumerate(self.assignment):
            if not eps:
                raise ValueError(f"slot {slot} has no endpoints")
            if len(set(eps)) != len(eps):
                raise ValueError(
                    f"slot {slot} lists a duplicate endpoint: {eps} — "
                    "replicas must live on distinct hosts")

    @staticmethod
    def ring(endpoints: Sequence[str], replicas: int,
             table: Optional[ShardRangeTable] = None) -> "ReplicaMap":
        table = table or ShardRangeTable.for_world(len(endpoints))
        return ReplicaMap(table=table, assignment=tuple(
            ring_assignment(endpoints, replicas)))

    @property
    def world(self) -> int:
        return self.table.world

    @property
    def replication(self) -> int:
        """The CURRENT replication factor = the thinnest slot (a dead
        host removed by promotion lowers it until repair restores R)."""
        return min(len(eps) for eps in self.assignment)

    def primary(self, slot: int) -> str:
        return self.assignment[slot][0]

    def replicas_of(self, slot: int) -> Tuple[str, ...]:
        return self.assignment[slot]

    def primaries(self) -> List[str]:
        return [eps[0] for eps in self.assignment]

    def all_endpoints(self) -> List[str]:
        """Every distinct endpoint, in first-appearance slot order."""
        out: List[str] = []
        for eps in self.assignment:
            for e in eps:
                if e not in out:
                    out.append(e)
        return out

    def slots_of(self, endpoint: str) -> Dict[int, str]:
        """slot -> role ('primary'|'backup') for one endpoint."""
        roles: Dict[int, str] = {}
        for slot, eps in enumerate(self.assignment):
            if endpoint == eps[0]:
                roles[slot] = "primary"
            elif endpoint in eps:
                roles[slot] = "backup"
        return roles

    def drop_endpoint(self, endpoint: str) -> "ReplicaMap":
        """Fail-over PROMOTION: remove a dead endpoint everywhere; a
        slot it led falls to its first surviving backup. Raises if any
        slot would be left with no replica (data loss — recovery must
        go through the checkpoint chain instead)."""
        out: List[Tuple[str, ...]] = []
        for slot, eps in enumerate(self.assignment):
            kept = tuple(e for e in eps if e != endpoint)
            if not kept:
                raise ValueError(
                    f"slot {slot} has no surviving replica after "
                    f"dropping {endpoint} — unrecoverable without a "
                    "checkpoint reload")
            out.append(kept)
        return ReplicaMap(table=self.table, assignment=tuple(out))

    def add_backup(self, slot: int, endpoint: str) -> "ReplicaMap":
        """Repair RE-REPLICATION: append a fresh backup to one slot."""
        if endpoint in self.assignment[slot]:
            return self
        out = list(self.assignment)
        out[slot] = self.assignment[slot] + (endpoint,)
        return ReplicaMap(table=self.table, assignment=tuple(out))

    def to_dict(self) -> dict:
        return {"table": self.table.to_dict(),
                "assignment": [list(eps) for eps in self.assignment]}

    @staticmethod
    def from_dict(d: dict) -> "ReplicaMap":
        return ReplicaMap(
            table=ShardRangeTable.from_dict(d["table"]),
            assignment=tuple(tuple(str(e) for e in eps)
                             for eps in d["assignment"]))


@dataclasses.dataclass
class JournalEntry:
    seq: int
    op: str                  # "push" | "apply" | "shrink"
    payload: dict            # numpy arrays / scalars, wire-encodable


class DeltaJournal:
    """Per-slot sequence-numbered mutation log on the PRIMARY.

    ``seq`` counts every mutation applied to the slot's store since this
    primary took over; backups track the last (epoch, seq) they applied.
    The log keeps the most recent ``cap`` entries: ``since(s)`` returns
    the entries a backup at seq ``s`` is missing, or ``None`` when the
    gap reaches past the retained window (→ full-snapshot catch-up).

    ``epoch`` names the HISTORY the seqs count over. It changes whenever
    the baseline under seq 0 changes — promotion, checkpoint load,
    reset — because a seq is only meaningful relative to its baseline: a
    freshly-loaded primary and a fresh-empty backup both sit at "seq 0"
    with different bytes, and replaying the journal across that
    mismatch would diverge silently. An epoch mismatch always forces a
    full snapshot. Thread-safe: the owning server appends under its
    slot lock but drills/benches read concurrently."""

    def __init__(self, cap: int, *, start_seq: int = 0,
                 epoch: str = ""):
        self._cap = int(cap)
        self._entries: deque = deque()
        self._seq = int(start_seq)
        self.epoch = epoch
        self._lock = threading.Lock()

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def append(self, op: str, payload: dict) -> int:
        """Assign the next seq to one applied mutation. With cap <= 0
        the journal only counts (every catch-up snapshots)."""
        with self._lock:
            self._seq += 1
            if self._cap > 0:
                self._entries.append(
                    JournalEntry(seq=self._seq, op=op, payload=payload))
                while len(self._entries) > self._cap:
                    self._entries.popleft()
            return self._seq

    def since(self, seq: int) -> Optional[List[JournalEntry]]:
        """Entries with ``entry.seq > seq`` — the delta catch-up — or
        None when the journal no longer reaches back that far (the
        backup must take a full snapshot)."""
        with self._lock:
            if seq >= self._seq:
                return []
            if not self._entries or self._entries[0].seq > seq + 1:
                return None
            return [e for e in self._entries if e.seq > seq]

    def reset(self, *, start_seq: int = 0, epoch: str = "") -> None:
        """New history baseline: entries dropped, seq re-anchored, and
        the epoch re-stamped so stale (old-epoch) backups snapshot."""
        with self._lock:
            self._entries.clear()
            self._seq = int(start_seq)
            self.epoch = epoch
