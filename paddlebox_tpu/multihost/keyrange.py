"""Stable hash-range key partition across hosts.

Role of the reference's cross-node key placement (``key % num_devices``,
``heter_comm.h:332``) re-shaped for ELASTIC membership: a modulo table
moves ~``(W-1)/W`` of all keys when the world grows by one host, which
turns every scale event into a full-table shuffle. Here keys map through
a fixed 64-bit mix (the same splitmix-style finalizer as
``embedding/sharded_store.py`` / the SSD tier, so sequential feasign
ranges spread) into a CONTIGUOUS hash range per host:

    owner(key) = searchsorted(bounds, mix(key))     bounds = equal split
                                                    of [0, 2^64)

Growing W -> W' re-draws the bounds; the set of keys whose owner changes
is exactly the symmetric difference of the two interval partitions — the
MINIMAL row movement any deterministic placement can achieve for that
membership change ("Memory-efficient array redistribution", PAPERS.md:
redistribution cost is the measure of the overlap complement, and
interval partitions minimize it for 1-D range placements).
:func:`plan_moves` emits that overlap complement as explicit
``(src, dst, lo, hi)`` segments, so the reshard executor transfers each
moved row exactly once and can be audited against
:func:`rows_moved_minimal`.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

_SPAN = 1 << 64


def mix_keys(keys: np.ndarray) -> np.ndarray:
    """The 64-bit placement hash (splitmix-style finalizer — identical
    math to ``sharded_store._bucket_of``'s first two stages). uint64 in,
    uint64 out, vectorized."""
    h = np.ascontiguousarray(keys, np.uint64)
    h = h ^ (h >> np.uint64(33))
    with np.errstate(over="ignore"):
        h = h * np.uint64(0xFF51AFD7ED558CCD)
    h = h ^ (h >> np.uint64(33))
    return h


def range_bounds(world: int) -> List[int]:
    """``world + 1`` python-int bounds of the equal interval partition of
    [0, 2^64): host i owns [bounds[i], bounds[i+1])."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return [(_SPAN * i) // world for i in range(world + 1)]


@dataclasses.dataclass(frozen=True)
class ShardRangeTable:
    """One membership generation's key placement: ``bounds`` as python
    ints (the top bound 2^64 does not fit uint64)."""

    bounds: tuple

    @staticmethod
    def for_world(world: int) -> "ShardRangeTable":
        return ShardRangeTable(bounds=tuple(range_bounds(world)))

    @property
    def world(self) -> int:
        return len(self.bounds) - 1

    def owner_of(self, keys: np.ndarray) -> np.ndarray:
        """int64 owner index per key (vectorized searchsorted over the
        interior bounds — bounds[0]=0 and bounds[-1]=2^64 never split)."""
        h = mix_keys(keys)
        interior = np.asarray(self.bounds[1:-1], np.uint64)
        return np.searchsorted(interior, h, side="right").astype(np.int64)

    def range_of(self, host: int) -> tuple:
        return (self.bounds[host], self.bounds[host + 1])

    def mask_in_range(self, keys: np.ndarray, lo: int, hi: int
                      ) -> np.ndarray:
        """Boolean mask of keys whose placement hash falls in [lo, hi).
        ``hi`` may be 2^64 (exclusive top — every hash qualifies)."""
        h = mix_keys(keys)
        m = h >= np.uint64(lo)
        if hi < _SPAN:
            m &= h < np.uint64(hi)
        return m

    def to_dict(self) -> dict:
        # Bounds as decimal strings: 2^64 overflows i64 and the typed
        # wire/json carry no u64 scalar.
        return {"bounds": [str(b) for b in self.bounds]}

    @staticmethod
    def from_dict(d: dict) -> "ShardRangeTable":
        return ShardRangeTable(bounds=tuple(int(b) for b in d["bounds"]))


@dataclasses.dataclass(frozen=True)
class MoveSegment:
    """One contiguous hash range that changes owner: rows with
    mix(key) in [lo, hi) move src -> dst."""

    src: int
    dst: int
    lo: int
    hi: int


def plan_moves(old: ShardRangeTable, new: ShardRangeTable
               ) -> List[MoveSegment]:
    """Minimal-transfer reshard plan between two range tables: the
    interval intersections of (old partition x new partition) whose
    owners differ. Every key whose owner changed is covered by exactly
    one segment; keys whose owner is unchanged appear in no segment —
    so executing the plan moves each changed row once and nothing else
    (the redistribution lower bound for this placement family)."""
    cuts = sorted(set(old.bounds) | set(new.bounds))
    segs: List[MoveSegment] = []
    oi = ni = 0
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        while old.bounds[oi + 1] <= lo:
            oi += 1
        while new.bounds[ni + 1] <= lo:
            ni += 1
        if oi != ni:
            segs.append(MoveSegment(src=oi, dst=ni, lo=lo, hi=hi))
    return segs


def rows_moved_minimal(old: ShardRangeTable, new: ShardRangeTable,
                       keys: np.ndarray) -> int:
    """Count of keys whose owner differs between the two tables — the
    audit bound a measured reshard's per-row move total must equal."""
    return int(np.sum(old.owner_of(keys) != new.owner_of(keys)))
