"""paddlebox_tpu — a TPU-native training framework with PaddleBox capabilities.

A brand-new JAX/XLA/Pallas framework reproducing the capabilities of
zhongweics/PaddleBox (Baidu's PaddlePaddle fork with the BoxPS/HeterPS
GPU-resident sparse parameter server for trillion-feature CTR models) —
re-designed TPU-first rather than ported:

- sparse embedding engine: pass-based tables sharded across TPU HBM,
  pull = all-to-all + gather, push = segment-sum + fused sparse optimizer
  (role of ``fleet/box_wrapper.h`` + ``fleet/heter_ps/`` in the reference)
- data pipeline: columnar slot-record batches with static padded shapes
  (role of ``framework/data_feed.{h,cc,cu}``, ``data_set.{h,cc}``)
- distributed: dp/mp/pp/sp/ep hybrid meshes over ICI/DCN via pjit/shard_map
  (role of ``python/paddle/distributed/fleet``), plus TPU-first long-context
  sequence parallelism (absent in the reference)
- metrics: exact distributed AUC via on-device bucketed histograms + psum
  (role of ``fleet/metrics.{h,cc}``)
- checkpointing: day/pass base+delta model dumps with done-file publication
  (role of ``BoxWrapper::SaveBase/SaveDelta``, ``fleet_util.py``)

See SURVEY.md at the repo root for the full structural map of the reference.
"""

from paddlebox_tpu.version import __version__

# Core runtime (role of paddle/fluid/platform: flags, monitor, timers).
from paddlebox_tpu.core import flags
from paddlebox_tpu.core.flags import get_flags, set_flags

__all__ = [
    "__version__",
    "flags",
    "get_flags",
    "set_flags",
]
