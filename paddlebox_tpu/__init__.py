"""paddlebox_tpu — a TPU-native training framework with PaddleBox capabilities.

A brand-new JAX/XLA/Pallas framework reproducing the capabilities of
zhongweics/PaddleBox (Baidu's PaddlePaddle fork with the BoxPS/HeterPS
GPU-resident sparse parameter server for trillion-feature CTR models) —
re-designed TPU-first rather than ported:

- sparse embedding engine: pass-based tables sharded across TPU HBM,
  pull = all-to-all + gather, push = segment-sum + fused sparse optimizer
  (role of ``fleet/box_wrapper.h`` + ``fleet/heter_ps/`` in the reference)
- data pipeline: columnar slot-record batches with static padded shapes
  (role of ``framework/data_feed.{h,cc,cu}``, ``data_set.{h,cc}``)
- distributed: dp/mp/pp/sp/ep hybrid meshes over ICI/DCN via pjit/shard_map
  (role of ``python/paddle/distributed/fleet``), plus TPU-first long-context
  sequence parallelism (absent in the reference)
- metrics: exact distributed AUC via on-device bucketed histograms + psum
  (role of ``fleet/metrics.{h,cc}``)
- checkpointing: day/pass base+delta model dumps with done-file publication
  (role of ``BoxWrapper::SaveBase/SaveDelta``, ``fleet_util.py``)

See SURVEY.md at the repo root for the full structural map of the reference.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under jax.experimental with the
    # replication check named check_rep; the codebase targets the
    # public jax.shard_map(check_vma=...) spelling. Adapt once here —
    # every module (and the tests) imports this package first.
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def _shard_map_compat(f, *, mesh, in_specs, out_specs,
                          check_vma=True, **kw):
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma,
                               **kw)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # jax < 0.4.38 has no lax.axis_size; psum of a python 1 folds to the
    # static axis size at trace time (tuples of names included), which
    # is exactly axis_size's contract inside shard_map bodies.
    def _axis_size_compat(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size_compat

from paddlebox_tpu.version import __version__

# Core runtime (role of paddle/fluid/platform: flags, monitor, timers).
from paddlebox_tpu.core import flags
from paddlebox_tpu.core.flags import get_flags, set_flags

__all__ = [
    "__version__",
    "flags",
    "get_flags",
    "set_flags",
]
