"""xDeepFM — Compressed Interaction Network over pulled sparse embeddings.

The vector-wise explicit-interaction member of the PaddleBox-era CTR zoo
(next to DeepFM's bit-wise FM and DCN's CrossNet; reference models
compose ``pull_box_sparse`` + ``fused_seqpool_cvm`` graphs the same
way). CIN keeps FIELDS intact: layer k forms every pairwise Hadamard
product between its H_{k-1} feature maps and the m raw field vectors,
then compresses them back to H_k maps with a learned [H_k, H_{k-1}*m]
matrix — degree-(k+1) interactions at the vector level. Each layer's
maps sum-pool over the embedding dim into the logit head.

TPU-first shape: both CIN steps are einsums — the outer product batches
as [B, H, m, D] elementwise (VPU) and the compression is one
[H_k, H_{k-1}m] x [B, H_{k-1}m, D] matmul (MXU) — no per-field loops.

Same functional contract as :class:`~paddlebox_tpu.models.DeepFM`
(init/apply, differentiable w.r.t. pulled emb/w for the sparse push).
CIN requires a UNIFORM embedding width (vector-wise products need equal
D); dynamic-mf per-slot widths are rejected loudly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.common import pool_slot_inputs, uniform_emb_dim
from paddlebox_tpu.nn import dense_apply, dense_init, mlp_apply, mlp_init


@dataclasses.dataclass(frozen=True)
class XDeepFM:
    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    dense_dim: int = 0
    cin_layers: Tuple[int, ...] = (16, 16)   # H_k map counts
    hidden: Tuple[int, ...] = (128, 64)

    def _d(self) -> int:
        return uniform_emb_dim(
            self.slot_names, self.emb_dim, "CIN",
            "vector-wise interactions cannot mix embedding sizes")

    def init(self, rng: jax.Array) -> Dict:
        d = self._d()
        m = len(self.slot_names)
        flat = m * d + self.dense_dim
        keys = jax.random.split(rng, len(self.cin_layers) + 2)
        cin = []
        h_prev = m
        for i, h in enumerate(self.cin_layers):
            cin.append(dense_init(keys[i], h_prev * m, h))
            h_prev = h
        out = {
            "cin": cin,
            "head": dense_init(
                keys[-1],
                sum(self.cin_layers)
                + (self.hidden[-1] if self.hidden else flat), 1),
            "bias": jnp.zeros((), jnp.float32),
        }
        if self.hidden:
            out["deep"] = mlp_init(keys[-2], flat, list(self.hidden))
        return out

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        d = self._d()
        m = len(self.slot_names)
        # Shared prelude (same helper as DeepFM/DCN): flat is the
        # slot-ordered pooled concat [B, m*d (+dense)] — the uniform
        # width lets the sparse prefix reshape back into fields.
        flat, wide = pool_slot_inputs(self.slot_names, emb, w, segments,
                                      batch_size, dense_feats,
                                      self.dense_dim)
        x0 = flat[:, :m * d].reshape(batch_size, m, d)   # [B, m, D]

        # CIN: x_k [B, H_k, D]; pooled per-layer maps feed the head.
        xk = x0
        pooled = []
        for layer in params["cin"]:
            z = xk[:, :, None, :] * x0[:, None, :, :]      # [B, H, m, D]
            z = z.reshape(z.shape[0], xk.shape[1] * m, d)  # [B, Hm, D]
            # Compression: one MXU matmul over the map axis.
            xk = jnp.einsum("bnd,nh->bhd", z, layer["w"]) \
                + layer["b"][None, :, None]
            xk = jnp.maximum(xk, 0.0)
            pooled.append(jnp.sum(xk, axis=-1))            # [B, H_k]
        cin_out = (jnp.concatenate(pooled, axis=-1) if pooled
                   else jnp.zeros((batch_size, 0), x0.dtype))

        if self.hidden:
            deep = mlp_apply(params["deep"], flat, final_activation=True)
        else:
            deep = flat
        h = jnp.concatenate([cin_out, deep], axis=-1)
        return dense_apply(params["head"], h)[:, 0] + wide + params["bias"]
