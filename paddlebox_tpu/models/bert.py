"""BERT encoder for masked-LM pretraining (BASELINE.md config 2).

Role of the reference's Fleet data-parallel BERT path (static-graph program
+ per-grad ``c_allreduce_sum``; SURVEY.md §3.4). TPU-first: one jitted
data-parallel train step — batch sharded over dp, params replicated,
gradient reduction from differentiating the global-mean loss under
shard_map (or plain pjit sharding annotations).

Reuses the GPT block machinery with bidirectional attention and adds MLM
heads; the hybrid-parallel path (tp/sp axes) composes exactly as in
models/gpt.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab: int = 2


def _ln(x, g, b, eps=1e-12):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def init_bert(rng: jax.Array, cfg: BertConfig) -> Dict[str, Any]:
    d, f = cfg.d_model, cfg.d_ff
    keys = iter(jax.random.split(rng, 8 * cfg.n_layers + 8))
    s = 0.02

    def nrm(shape):
        return jax.random.normal(next(keys), shape) * s

    layers = []
    for _ in range(cfg.n_layers):
        layers.append({
            "wqkv": nrm((d, 3 * d)), "bqkv": jnp.zeros((3 * d,)),
            "wo": nrm((d, d)), "bo": jnp.zeros((d,)),
            "ln1_g": jnp.ones((d,)), "ln1_b": jnp.zeros((d,)),
            "wi": nrm((d, f)), "bi": jnp.zeros((f,)),
            "wo2": nrm((f, d)), "bo2": jnp.zeros((d,)),
            "ln2_g": jnp.ones((d,)), "ln2_b": jnp.zeros((d,)),
        })
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return {
        "tok": nrm((cfg.vocab_size, d)),
        "pos": nrm((cfg.max_seq_len, d)),
        "typ": nrm((cfg.type_vocab, d)),
        "emb_ln_g": jnp.ones((d,)), "emb_ln_b": jnp.zeros((d,)),
        "layers": stacked,
        "mlm_w": nrm((d, d)), "mlm_b": jnp.zeros((d,)),
        "mlm_ln_g": jnp.ones((d,)), "mlm_ln_b": jnp.zeros((d,)),
        "mlm_out_b": jnp.zeros((cfg.vocab_size,)),
    }


def bert_encode(params: Dict, cfg: BertConfig, tokens: jax.Array,
                type_ids: jax.Array | None = None,
                attn_mask: jax.Array | None = None) -> jax.Array:
    """tokens [B, S] → hidden [B, S, D]."""
    b, s = tokens.shape
    hd = cfg.d_model // cfg.n_heads
    x = params["tok"][tokens] + params["pos"][jnp.arange(s)][None]
    if type_ids is not None:
        x = x + params["typ"][type_ids]
    x = _ln(x, params["emb_ln_g"], params["emb_ln_b"])

    if attn_mask is not None:
        bias = jnp.where(attn_mask[:, None, None, :] > 0, 0.0, -1e30)
    else:
        bias = None

    def block(x, p):
        in_dtype = x.dtype
        qkv = (jnp.dot(x, p["wqkv"], preferred_element_type=jnp.float32)
               + p["bqkv"]).reshape(b, s, cfg.n_heads, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * hd ** -0.5
        if bias is not None:
            sc = sc + bias.transpose(0, 2, 1, 3)
        a = jax.nn.softmax(sc, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v,
                       preferred_element_type=jnp.float32)
        o = o.reshape(b, s, cfg.d_model)
        o = jnp.dot(o, p["wo"], preferred_element_type=jnp.float32) + p["bo"]
        x = _ln(x + o, p["ln1_g"], p["ln1_b"])
        h = jax.nn.gelu(
            jnp.dot(x, p["wi"], preferred_element_type=jnp.float32)
            + p["bi"])
        h = jnp.dot(h, p["wo2"], preferred_element_type=jnp.float32) + p["bo2"]
        out = _ln(x + h, p["ln2_g"], p["ln2_b"])
        # Keep the residual stream in the policy dtype (bf16 under AMP):
        # the f32-accumulating dots must not widen the scan carry.
        return out.astype(in_dtype), None

    x, _ = lax.scan(block, x, params["layers"])
    return x


def bert_mlm_loss(params: Dict, cfg: BertConfig, tokens: jax.Array,
                  targets: jax.Array, mask: jax.Array,
                  axis_name: str | None = None) -> jax.Array:
    """Masked-LM loss. mask [B, S] — 1 where the token is predicted.
    Weight-tied output embedding (standard BERT)."""
    h = bert_encode(params, cfg, tokens)
    h = jax.nn.gelu(
        jnp.dot(h, params["mlm_w"], preferred_element_type=jnp.float32)
        + params["mlm_b"])
    h = _ln(h, params["mlm_ln_g"], params["mlm_ln_b"])
    logits = jnp.dot(h, params["tok"].T,
                     preferred_element_type=jnp.float32) + params["mlm_out_b"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    losses = (logz - tgt) * mask
    total = jnp.sum(losses)
    count = jnp.sum(mask)
    if axis_name is not None:
        total = lax.psum(total, axis_name)
        count = lax.psum(count, axis_name)
    return total / jnp.maximum(count, 1.0)
