"""DeepFM CTR model over pulled sparse embeddings.

The BASELINE.md config-4 model (DeepFM on Criteo, reference path
``pull_box_sparse`` + dense ops). Consumes the sparse pull outputs
(per-slot CSR embeddings) and produces logits:

  logit = wide(w) + FM2(v) + MLP(concat slot embeddings [, dense feats])

Functional: ``init`` returns the dense-param pytree; ``apply`` is pure so
the trainer can differentiate wrt (params, pulled_emb, pulled_w) and feed
the embedding grads straight into the sparse push.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.common import slot_dims
from paddlebox_tpu.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import seqpool


@dataclasses.dataclass(frozen=True)
class DeepFM:
    slot_names: Tuple[str, ...]
    # One width for every slot, or a per-slot mapping (dynamic mf, role of
    # CtrDymfAccessor per-slot mf dims). With mixed widths the FM term
    # zero-pads pooled vectors to the max width (missing dims contribute
    # nothing to the interaction); the deep tower concats true widths.
    emb_dim: Union[int, Mapping[str, int]]
    dense_dim: int = 0                    # width of concatenated dense slots
    hidden: Tuple[int, ...] = (400, 400, 400)

    def _dims(self) -> Dict[str, int]:
        return slot_dims(self.slot_names, self.emb_dim)

    def init(self, rng: jax.Array) -> Dict:
        in_dim = sum(self._dims().values()) + self.dense_dim
        rng, sub = jax.random.split(rng)
        return {
            "mlp": mlp_init(sub, in_dim, list(self.hidden) + [1]),
            "bias": jnp.zeros((), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],       # slot -> [cap_s, D_s] pulled
              w: Dict[str, jax.Array],         # slot -> [cap_s] pulled
              segments: Dict[str, jax.Array],  # slot -> [cap_s] row ids
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        dims = self._dims()
        dmax = max(dims.values())
        pooled_v: List[jax.Array] = []   # per-slot [B, D_s]
        wide_terms: List[jax.Array] = []  # per-slot [B]
        for name in self.slot_names:
            pooled_v.append(seqpool(emb[name], segments[name], batch_size))
            wide_terms.append(seqpool(w[name], segments[name], batch_size))

        # Wide (first-order) term.
        wide = sum(wide_terms) + params["bias"]           # [B]

        # FM second-order interaction: 0.5 * ((Σ_s v)^2 - Σ_s v^2), with
        # narrower slots zero-padded to the max width.
        padded = [jnp.pad(p, ((0, 0), (0, dmax - p.shape[-1])))
                  if p.shape[-1] < dmax else p for p in pooled_v]
        v = jnp.stack(padded, axis=1)                     # [B, S, Dmax]
        sum_v = jnp.sum(v, axis=1)                        # [B, Dmax]
        sum_sq = jnp.sum(v * v, axis=1)                   # [B, Dmax]
        fm = 0.5 * jnp.sum(sum_v * sum_v - sum_sq, axis=-1)  # [B]

        # Deep tower over true (unpadded) widths.
        flat = jnp.concatenate(pooled_v, axis=-1)         # [B, sum D_s]
        if dense_feats is not None and self.dense_dim:
            flat = jnp.concatenate([flat, dense_feats], axis=-1)
        deep = mlp_apply(params["mlp"], flat)[:, 0]       # [B]

        return wide + fm + deep
