"""DeepFM CTR model over pulled sparse embeddings.

The BASELINE.md config-4 model (DeepFM on Criteo, reference path
``pull_box_sparse`` + dense ops). Consumes the sparse pull outputs
(per-slot CSR embeddings) and produces logits:

  logit = wide(w) + FM2(v) + MLP(concat slot embeddings [, dense feats])

Functional: ``init`` returns the dense-param pytree; ``apply`` is pure so
the trainer can differentiate wrt (params, pulled_emb, pulled_w) and feed
the embedding grads straight into the sparse push.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import seqpool


@dataclasses.dataclass(frozen=True)
class DeepFM:
    slot_names: Tuple[str, ...]
    emb_dim: int
    dense_dim: int = 0                    # width of concatenated dense slots
    hidden: Tuple[int, ...] = (400, 400, 400)

    def init(self, rng: jax.Array) -> Dict:
        s = len(self.slot_names)
        in_dim = s * self.emb_dim + self.dense_dim
        rng, sub = jax.random.split(rng)
        return {
            "mlp": mlp_init(sub, in_dim, list(self.hidden) + [1]),
            "bias": jnp.zeros((), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],       # slot -> [cap_s, D] pulled
              w: Dict[str, jax.Array],         # slot -> [cap_s] pulled
              segments: Dict[str, jax.Array],  # slot -> [cap_s] row ids
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        pooled_v: List[jax.Array] = []   # per-slot [B, D]
        wide_terms: List[jax.Array] = []  # per-slot [B]
        for name in self.slot_names:
            pooled_v.append(seqpool(emb[name], segments[name], batch_size))
            wide_terms.append(seqpool(w[name], segments[name], batch_size))
        v = jnp.stack(pooled_v, axis=1)                   # [B, S, D]

        # Wide (first-order) term.
        wide = sum(wide_terms) + params["bias"]           # [B]

        # FM second-order interaction: 0.5 * ((Σ_s v)^2 - Σ_s v^2).
        sum_v = jnp.sum(v, axis=1)                        # [B, D]
        sum_sq = jnp.sum(v * v, axis=1)                   # [B, D]
        fm = 0.5 * jnp.sum(sum_v * sum_v - sum_sq, axis=-1)  # [B]

        # Deep tower.
        flat = v.reshape(v.shape[0], -1)                  # [B, S*D]
        if dense_feats is not None and self.dense_dim:
            flat = jnp.concatenate([flat, dense_feats], axis=-1)
        deep = mlp_apply(params["mlp"], flat)[:, 0]       # [B]

        return wide + fm + deep
