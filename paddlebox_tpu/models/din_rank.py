"""DIN-Rank: rank-aware CTR model over pv-grouped batches.

Role of the PaddleBox production rank-attention graphs (the consumers of
``rank_attention_op`` + pv-mode batches, ``data_feed.h:1701``): inside a
pv (one search/page view), each candidate attends over its PEER candidates
— the items shown alongside it — with a parameter block selected by the
(own position, peer position) pair. The model front-end is the same
pooled-slot-embedding tower as DeepFM; the rank-attention term adds the
in-pv context signal.

``build_rank_offset`` derives the op's rank_offset input from the group
ids that :meth:`Dataset.batches_grouped` yields — position within the pv
is the rank, other members are the peers — so the pv data path and the op
compose end to end.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import seqpool
from paddlebox_tpu.ops.rank_attention import rank_attention


def build_rank_offset(gids: np.ndarray, max_rank: int,
                      valid: np.ndarray | None = None) -> np.ndarray:
    """Group ids [B] → rank_offset [B, 1 + 2*max_rank] int32.

    Rows of the same group must be contiguous (batches_grouped
    guarantees it). Col 0 = 1-based position within the group, clipped
    at max_rank (0 for invalid rows); then (peer_rank, peer_row) pairs
    for up to max_rank OTHER members of the group (0,0 padding).
    """
    b = gids.shape[0]
    out = np.zeros((b, 1 + 2 * max_rank), np.int32)
    if valid is None:
        valid = np.ones((b,), bool)
    starts = np.concatenate(
        [[0], np.flatnonzero(gids[1:] != gids[:-1]) + 1, [b]])
    for g in range(starts.size - 1):
        lo, hi = int(starts[g]), int(starts[g + 1])
        members = [r for r in range(lo, hi) if valid[r]]
        for pos, r in enumerate(members):
            if pos >= max_rank:
                break
            out[r, 0] = pos + 1
            k = 0
            for ppos, peer in enumerate(members):
                if peer == r or ppos >= max_rank:
                    continue
                if k >= max_rank:
                    break
                out[r, 1 + 2 * k] = ppos + 1
                out[r, 2 + 2 * k] = peer
                k += 1
    return out


@dataclasses.dataclass(frozen=True)
class DINRank:
    """Pooled slot embeddings + rank attention over pv peers + MLP."""

    slot_names: Tuple[str, ...]
    emb_dim: int
    max_rank: int = 4
    att_dim: int = 16
    hidden: Tuple[int, ...] = (64, 32)

    @property
    def feat_dim(self) -> int:
        return len(self.slot_names) * self.emb_dim

    def init(self, rng: jax.Array) -> Dict:
        f = self.feat_dim
        k = self.max_rank
        r1, r2 = jax.random.split(rng)
        return {
            "rank_param": 0.1 * jax.random.normal(
                r1, (k * k, f, self.att_dim), jnp.float32),
            "mlp": mlp_init(r2, f + self.att_dim,
                            list(self.hidden) + [1]),
            "bias": jnp.zeros((), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              rank_offset: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]. Without rank_offset the attention term is
        zero (single-candidate pvs degrade gracefully)."""
        pooled: List[jax.Array] = []
        wide = params["bias"]
        for name in self.slot_names:
            pooled.append(seqpool(emb[name], segments[name], batch_size))
            wide = wide + seqpool(w[name], segments[name], batch_size)
        x = jnp.concatenate(pooled, axis=-1)              # [B, F]
        if rank_offset is not None:
            att, _ = rank_attention(x, rank_offset, params["rank_param"],
                                    max_rank=self.max_rank)
        else:
            att = jnp.zeros((x.shape[0], self.att_dim), x.dtype)
        deep = mlp_apply(params["mlp"],
                         jnp.concatenate([x, att], axis=-1))[:, 0]
        return wide + deep
