"""GPT-style transformer with full hybrid parallelism (dp×pp×sp×mp).

The BASELINE.md config-3 model (GPT 1.3B hybrid parallel; reference path
``fleet/meta_parallel/`` TP+PP+sharding). Composes the whole parallelism
suite in one train step:

- mp: vocab-parallel embedding + column/row-parallel attention & FFN +
  vocab-parallel cross entropy (roles of mp_layers.py / c_embedding /
  c_softmax_with_cross_entropy)
- pp: transformer blocks partitioned into stages streamed with the
  scan+ppermute pipeline (role of PipelineParallel.forward_backward_pipeline)
- sp: ring attention over the sequence axis (NEW capability, absent in the
  reference — SURVEY.md §5)
- dp: batch sharding; gradient reduction falls out of autodiff through the
  global-mean loss (role of EagerReducer/c_allreduce_sum)

Everything runs inside ONE ``shard_map`` over the hybrid mesh; jax.grad
through it yields the full hybrid backward (pipelined, ring-reversed,
TP-transposed) with XLA scheduling all collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddlebox_tpu.parallel import pp as pplib
from paddlebox_tpu.parallel import sp as splib
from paddlebox_tpu.parallel import tp as tplib


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_seq_len: int = 1024
    dtype: Any = jnp.float32
    # "auto": Pallas flash attention on TPU when the sequence is not
    # sharded (sp axis size 1), ring attention otherwise; "ring"/"flash"
    # force a path (role of the reference's fused_attention_op.cu choice).
    attention: str = "auto"


def _layer_init(rng, cfg: GPTConfig):
    d, f = cfg.d_model, cfg.d_ff
    k = iter(jax.random.split(rng, 6))
    s = d ** -0.5
    return {
        "ln1_g": jnp.ones((d,)), "ln1_b": jnp.zeros((d,)),
        # Column order is HEAD-MAJOR [head0(q,k,v) | head1(q,k,v) | ...] so
        # the mp sharding splits whole heads, not q/k/v mid-tensor.
        "wqkv": jax.random.normal(next(k), (d, 3 * d)) * s,
        "wo": jax.random.normal(next(k), (d, d)) * s,
        "ln2_g": jnp.ones((d,)), "ln2_b": jnp.zeros((d,)),
        "wi": jax.random.normal(next(k), (d, f)) * s,
        "bi": jnp.zeros((f,)),
        "wo2": jax.random.normal(next(k), (f, d)) * (f ** -0.5),
        "bo2": jnp.zeros((d,)),
    }


def _layer_specs():
    """TP shardings per layer leaf (with the stacked [pp, layer] dims
    prepended by the caller)."""
    return {
        "ln1_g": P(), "ln1_b": P(),
        "wqkv": P(None, "mp"),   # column-parallel: heads split over mp
        "wo": P("mp", None),     # row-parallel
        "ln2_g": P(), "ln2_b": P(),
        "wi": P(None, "mp"),     # column-parallel FFN in
        "bi": P("mp"),
        "wo2": P("mp", None),    # row-parallel FFN out
        "bo2": P(),
    }


def init_gpt(rng: jax.Array, cfg: GPTConfig, *, pp_stages: int = 1
             ) -> Tuple[Dict, Dict]:
    """Returns (params, partition_specs). Layer params are stacked
    [pp_stages, layers_per_stage, ...]."""
    if cfg.n_layers % pp_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible into "
                         f"{pp_stages} stages")
    lps = cfg.n_layers // pp_stages
    keys = jax.random.split(rng, cfg.n_layers + 3)
    layers = [_layer_init(keys[i], cfg) for i in range(cfg.n_layers)]
    # Stack [pp, layers_per_stage, ...].
    stages = [jax.tree.map(lambda *xs: jnp.stack(xs),
                           *layers[s * lps:(s + 1) * lps])
              for s in range(pp_stages)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)

    params = {
        "embed": jax.random.normal(keys[-3], (cfg.vocab_size, cfg.d_model))
        * 0.02,
        "pos": jax.random.normal(keys[-2], (cfg.max_seq_len, cfg.d_model))
        * 0.02,
        "layers": stacked,
        "lnf_g": jnp.ones((cfg.d_model,)), "lnf_b": jnp.zeros((cfg.d_model,)),
        "head": jax.random.normal(keys[-1], (cfg.d_model, cfg.vocab_size))
        * cfg.d_model ** -0.5,
    }
    lspecs = _layer_specs()
    specs = {
        "embed": P("mp", None),        # vocab-parallel
        "pos": P(None, None),
        "layers": jax.tree.map(
            lambda s: P("pp", None, *s), lspecs,
            is_leaf=lambda x: isinstance(x, P)),
        "lnf_g": P(), "lnf_b": P(),
        "head": P(None, "mp"),         # vocab-parallel head
    }
    return params, specs


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _block(p, x, cfg: GPTConfig, heads_local: int):
    """One transformer block on local shards: x [mb, S_local, D];
    wqkv local [D, 3*D/mp]."""
    b, s, d = x.shape
    in_dtype = x.dtype
    hd = cfg.d_model // cfg.n_heads
    h = _ln(x, p["ln1_g"], p["ln1_b"])
    qkv = jnp.dot(h, p["wqkv"], preferred_element_type=jnp.float32)
    qkv = qkv.reshape(b, s, heads_local, 3, hd)
    q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
    sp_n = lax.axis_size("sp")
    if cfg.attention not in ("auto", "ring", "flash"):
        raise ValueError(f"unknown attention mode {cfg.attention!r}; "
                         "choose from 'auto', 'ring', 'flash'")
    if cfg.attention == "flash" and sp_n > 1:
        # The flash kernel sees only the local K/V shard; with a sharded
        # sequence only ring attention is exact.
        raise ValueError("attention='flash' requires sp axis size 1; "
                         "use 'ring' or 'auto' with a sharded sequence")
    use_flash = cfg.attention == "flash" or (
        cfg.attention == "auto" and sp_n == 1
        and jax.default_backend() == "tpu")
    if use_flash:
        from paddlebox_tpu.ops.pallas_kernels import flash_attention
        attn = flash_attention(q, k, v, causal=True)
    else:
        attn = splib.ring_attention(q, k, v, axis="sp", causal=True)
    attn = attn.reshape(b, s, heads_local * hd)
    o = jnp.dot(attn, p["wo"], preferred_element_type=jnp.float32)
    o = lax.psum(o, "mp")                       # row-parallel combine
    x = x + o
    h2 = _ln(x, p["ln2_g"], p["ln2_b"])
    u = jnp.dot(h2, p["wi"], preferred_element_type=jnp.float32) + p["bi"]
    u = jax.nn.gelu(u)
    y = jnp.dot(u, p["wo2"], preferred_element_type=jnp.float32)
    y = lax.psum(y, "mp") + p["bo2"]
    # Residual stream stays in the input dtype (bf16-safe scan carry);
    # note x is rebound above, so use the dtype captured at entry.
    return (x + y).astype(in_dtype)


def _data_axes(mesh: Mesh) -> tuple:
    """Batch-dim axes: ("slice", "dp") on a multi-slice mesh (batch
    splits across DCN slices too; XLA decomposes the loss/grad psums
    hierarchically over the physical topology), else ("dp",)."""
    if "slice" in mesh.axis_names and int(mesh.shape["slice"]) > 1:
        return ("slice", "dp")
    return ("dp",)


def gpt_loss_fn(cfg: GPTConfig, mesh: Mesh, specs: Dict, *,
                num_microbatches: int = 1):
    """Builds loss(params, tokens, targets) -> scalar, shard_mapped over
    the hybrid mesh. tokens/targets [B, S] int32; B sharded over the data
    axes (dp, plus the DCN slice axis on multi-slice meshes), S over sp."""
    heads_local = cfg.n_heads // int(mesh.shape["mp"])
    daxes = _data_axes(mesh)
    raxes = daxes + ("sp",)

    def stage_fn(stage_params, x):
        # stage_params leaves [layers_per_stage, ...]; scan over layers.
        def body(h, lp):
            return _block(lp, h, cfg, heads_local), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    def body(params, tokens, targets):
        # tokens local [B_local, S_local]
        x = tplib.vocab_parallel_embedding(
            {"table": params["embed"]}, tokens, axis="mp")
        rank_sp = lax.axis_index("sp")
        s_local = tokens.shape[1]
        pos_ids = rank_sp * s_local + jnp.arange(s_local)
        x = x + params["pos"][pos_ids][None, :, :]

        # Microbatch the local batch for the pipeline.
        bl = x.shape[0]
        m = num_microbatches
        x_mb = x.reshape(m, bl // m, s_local, cfg.d_model)
        stage_params_local = jax.tree.map(lambda a: a[0], params["layers"])
        h_mb = pplib.gpipe_apply(stage_fn, stage_params_local, x_mb,
                                 axis="pp")
        h = h_mb.reshape(bl, s_local, cfg.d_model)

        h = _ln(h, params["lnf_g"], params["lnf_b"])
        logits_local = jnp.dot(h, params["head"],
                               preferred_element_type=jnp.float32)
        losses = tplib.parallel_cross_entropy(logits_local, targets,
                                              axis="mp")
        # Global mean over all tokens (replica × sp shards).
        total = lax.psum(jnp.sum(losses), raxes)
        count = lax.psum(jnp.asarray(losses.size, jnp.float32), raxes)
        return total / count

    in_specs = (specs, P(daxes, "sp"), P(daxes, "sp"))
    return jax.shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P(),
                         check_vma=False)


def gpt_value_and_grad_1f1b(cfg: GPTConfig, mesh: Mesh, specs: Dict, *,
                            num_microbatches: int = 1,
                            num_chunks: int = 1):
    """(params, tokens, targets) -> (loss, grads) using the 1F1B pipeline
    schedule (role of the reference's default train_batch path,
    ``meta_parallel/pipeline_parallel.py:82``): bounded activation memory
    — each stage holds O(pp) stage inputs instead of the
    GPipe-through-autodiff O(M) residuals. The embedding runs outside the
    pipeline (cotangents returned by the schedule), the final-LN/head pair
    rides the schedule's ``loss_params`` channel.

    ``num_chunks > 1`` selects the INTERLEAVED schedule (virtual pipeline
    stages, role of virtual_pp_degree): each rank's resident layer rows
    split into ``num_chunks`` chunks whose virtual depth is CYCLIC over
    ranks (chunk c on rank r sits at depth c*pp + r) — about half the
    fill/drain bubble time. Note the depth meaning of a given physical
    layer row therefore differs from the plain schedule; layers are
    iid-initialized so training from scratch is equivalent, but
    checkpoints are not interchangeable between num_chunks settings."""
    heads_local = cfg.n_heads // int(mesh.shape["mp"])

    def stage_fn(stage_params, x):
        def body(h, lp):
            return _block(lp, h, cfg, heads_local), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    mp_n = int(mesh.shape["mp"])
    daxes = _data_axes(mesh)
    raxes = daxes + ("sp",)

    def loss_head(lp, y, tgt):
        h = _ln(y, lp["lnf_g"], lp["lnf_b"])
        logits = jnp.dot(h, lp["head"], preferred_element_type=jnp.float32)
        losses = tplib.parallel_cross_entropy(logits, tgt, axis="mp")
        # The schedule seeds this (mp-replicated) value on EVERY mp rank,
        # and psum's transpose under shard_map sums seeded cotangents —
        # so the seeded objective is mp * L unless scaled down here; the
        # reported loss is scaled back up by the caller.
        return jnp.mean(losses) / mp_n

    def body(params, tokens, targets):
        s_local = tokens.shape[1]

        def embed_fn(ep):
            x = tplib.vocab_parallel_embedding(
                {"table": ep["embed"]}, tokens, axis="mp")
            rank_sp = lax.axis_index("sp")
            pos_ids = rank_sp * s_local + jnp.arange(s_local)
            return x + ep["pos"][pos_ids][None, :, :]

        ep = {"embed": params["embed"], "pos": params["pos"]}
        x, vjp_embed = jax.vjp(embed_fn, ep)
        bl = x.shape[0]
        m = num_microbatches
        x_mb = x.reshape(m, bl // m, s_local, cfg.d_model)
        tgt_mb = targets.reshape(m, bl // m, s_local)
        lp = {"lnf_g": params["lnf_g"], "lnf_b": params["lnf_b"],
              "head": params["head"]}
        stage_params_local = jax.tree.map(lambda a: a[0], params["layers"])
        if num_chunks > 1:
            lps = jax.tree.leaves(stage_params_local)[0].shape[0]
            if lps % num_chunks:
                raise ValueError(
                    f"{lps} layers per pp stage do not split into "
                    f"num_chunks={num_chunks} equal chunks")
            chunked = jax.tree.map(
                lambda a: a.reshape((num_chunks, a.shape[0] // num_chunks)
                                    + a.shape[1:]), stage_params_local)
            loss, cgrads, lpgrads, dx0 = \
                pplib.interleaved_one_f_one_b_value_and_grad(
                    stage_fn, loss_head, chunked, x_mb, tgt_mb,
                    num_chunks=num_chunks, axis="pp", loss_params=lp,
                    return_input_grads=True)
            sgrads = jax.tree.map(
                lambda g: g.reshape((g.shape[0] * g.shape[1],)
                                    + g.shape[2:]), cgrads)
        else:
            loss, sgrads, lpgrads, dx0 = pplib.one_f_one_b_value_and_grad(
                stage_fn, loss_head, stage_params_local, x_mb, tgt_mb,
                axis="pp", loss_params=lp, return_input_grads=True)
        (dep,) = vjp_embed(
            dx0.reshape(bl, s_local, cfg.d_model).astype(x.dtype))

        grads = {
            "embed": dep["embed"],
            "pos": dep["pos"],
            "layers": jax.tree.map(lambda g: g[None], sgrads),
            "lnf_g": lpgrads["lnf_g"],
            "lnf_b": lpgrads["lnf_b"],
            "head": lpgrads["head"],
        }

        # Reductions mirroring what autodiff-through-shard_map gives the
        # GPipe path implicitly: a param replicated over an axis gets the
        # SUM of per-rank partials over that axis (broadcast transpose) —
        # pp (grads exist only on the first/last rank) and mp (each rank
        # contributes through its own heads/vocab shard) — while dp/sp
        # average, because each shard's loss is normalized by its LOCAL
        # token count (mean of local means == global mean for equal
        # shards).
        def reduce_leaf(g, spec):
            sharded = set()
            for entry in spec:
                if entry is None:
                    continue
                if isinstance(entry, (tuple, list)):
                    sharded.update(entry)
                else:
                    sharded.add(entry)
            axes = [a for a in ("pp", "mp") if a not in sharded]
            if axes:
                g = lax.psum(g, tuple(axes))
            return lax.pmean(g, raxes)

        grads = jax.tree.map(reduce_leaf, grads, specs)
        return lax.pmean(loss * mp_n, raxes), grads

    in_specs = (specs, P(daxes, "sp"), P(daxes, "sp"))
    return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=(P(), specs), check_vma=False)


def make_gpt_train_step(cfg: GPTConfig, mesh: Mesh, specs: Dict,
                        optimizer, *, num_microbatches: int = 1,
                        schedule: str = "gpipe", num_chunks: int = 1,
                        out_shardings=None):
    """Jitted (params, opt_state, tokens, targets) -> (params, opt_state,
    loss) with donation. Gradient reduction across dp/pp/sp/mp falls out
    of differentiating through the shard_map (``schedule="gpipe"``) or is
    explicit in the 1F1B path (``schedule="1f1b"`` — the reference's
    default pipeline schedule, pipeline_parallel.py:82, with bounded
    activation memory; pick it when microbatch count × activation size
    would blow HBM under GPipe). ``schedule="interleaved_1f1b"`` with
    ``num_chunks=V`` runs the virtual-stage interleave (~half the
    pipeline bubble; see gpt_value_and_grad_1f1b for the layer-layout
    note)."""
    if schedule in ("gpipe", "1f1b") and num_chunks != 1:
        # Silently training the plain schedule while the caller believes
        # they got the interleave would also bake in the wrong layer
        # layout (checkpoints differ between num_chunks settings).
        raise ValueError(
            f"num_chunks={num_chunks} requires "
            f"schedule='interleaved_1f1b' (got {schedule!r})")
    if schedule == "gpipe":
        loss_fn = gpt_loss_fn(cfg, mesh, specs,
                              num_microbatches=num_microbatches)
        vg = jax.value_and_grad(loss_fn)
    elif schedule == "1f1b":
        vg = gpt_value_and_grad_1f1b(cfg, mesh, specs,
                                     num_microbatches=num_microbatches)
    elif schedule == "interleaved_1f1b":
        if num_chunks < 2:
            raise ValueError("interleaved_1f1b needs num_chunks >= 2 — "
                             "at 1 chunk it IS the plain 1f1b schedule")
        vg = gpt_value_and_grad_1f1b(cfg, mesh, specs,
                                     num_microbatches=num_microbatches,
                                     num_chunks=num_chunks)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         "choose 'gpipe', '1f1b', or 'interleaved_1f1b'")

    def step(params, opt_state, tokens, targets):
        loss, grads = vg(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    # out_shardings (a (params, opt_state, loss) pytree) lets a caller
    # pin the outputs — the ZeRO bench path shards opt_state over dp and
    # must pin params replicated, or the sharded state inputs would leak
    # their sharding into p+u (accidental ZeRO-3).
    jit_kw = {} if out_shardings is None else {
        "out_shardings": out_shardings}
    return jax.jit(step, donate_argnums=(0, 1), **jit_kw)
