"""AutoInt — multi-head self-attention feature interactions for CTR.

The attention member of the CTR zoo (next to DeepFM's FM, DCN's
CrossNet, and xDeepFM's CIN; reference models compose the same
``pull_box_sparse`` + ``fused_seqpool_cvm`` input graphs and differ only
in the interaction tower). Each attention layer lets every FIELD attend
over all fields — a learned, input-dependent interaction order, where
CIN/CrossNet fix the order per layer.

TPU-first shape: the whole tower is five einsums per layer (q/k/v
projections, score matmul, value matmul) over [B, fields, width] with
fields ~tens — small matmuls batch over B on the MXU, and the softmax
over the field axis fuses into the surrounding elementwise work. No
per-field loops, no masks (fields are dense by construction).

Same functional contract as :class:`~paddlebox_tpu.models.DeepFM`
(init/apply, differentiable w.r.t. pulled emb/w for the sparse push).
Attention mixes field vectors, so like CIN it requires a UNIFORM
embedding width; dense features (when present) project to that width
and join as one extra field.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.common import pool_slot_inputs, uniform_emb_dim
from paddlebox_tpu.nn import dense_apply, dense_init, mlp_apply, mlp_init


@dataclasses.dataclass(frozen=True)
class AutoInt:
    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    dense_dim: int = 0
    att_dim: int = 32            # per-layer output width (num_heads * dh)
    num_heads: int = 2
    num_layers: int = 2
    hidden: Tuple[int, ...] = () # optional parallel deep tower

    def _d(self) -> int:
        return uniform_emb_dim(self.slot_names, self.emb_dim, "AutoInt",
                               "attention cannot mix field widths")

    def _dh(self) -> int:
        if self.num_layers < 1:
            raise ValueError("AutoInt needs num_layers >= 1 — with zero "
                             "attention layers there is no interaction "
                             "tower to size the head for")
        if self.att_dim % self.num_heads:
            raise ValueError(f"att_dim {self.att_dim} must divide by "
                             f"num_heads {self.num_heads}")
        return self.att_dim // self.num_heads

    def init(self, rng: jax.Array) -> Dict:
        d = self._d()
        dh = self._dh()
        m = len(self.slot_names)
        flat = m * d + self.dense_dim
        n_fields = m + (1 if self.dense_dim else 0)
        keys = jax.random.split(rng, self.num_layers + 4)
        layers = []
        d_in = d
        for i in range(self.num_layers):
            s = (2.0 / (d_in + dh)) ** 0.5
            k1, k2, k3, k4 = jax.random.split(keys[i], 4)
            layers.append({
                "wq": jax.random.normal(
                    k1, (self.num_heads, d_in, dh)) * s,
                "wk": jax.random.normal(
                    k2, (self.num_heads, d_in, dh)) * s,
                "wv": jax.random.normal(
                    k3, (self.num_heads, d_in, dh)) * s,
                # Residual projection to the layer's output width.
                "wr": jax.random.normal(
                    k4, (d_in, self.att_dim)
                ) * (2.0 / (d_in + self.att_dim)) ** 0.5,
            })
            d_in = self.att_dim
        out = {
            "att": layers,
            "head": dense_init(
                keys[-1],
                n_fields * self.att_dim
                + (self.hidden[-1] if self.hidden else 0), 1),
            "bias": jnp.zeros((), jnp.float32),
        }
        if self.dense_dim:
            out["dense_proj"] = dense_init(keys[-3], self.dense_dim, d)
        if self.hidden:
            out["deep"] = mlp_init(keys[-2], flat, list(self.hidden))
        return out

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        d = self._d()
        dh = self._dh()
        m = len(self.slot_names)
        flat, wide = pool_slot_inputs(self.slot_names, emb, w, segments,
                                      batch_size, dense_feats,
                                      self.dense_dim)
        x = flat[:, :m * d].reshape(batch_size, m, d)     # [B, m, D]
        if self.dense_dim:
            dfield = dense_apply(params["dense_proj"],
                                 flat[:, m * d:])          # [B, D]
            x = jnp.concatenate([x, dfield[:, None, :]], axis=1)

        for layer in params["att"]:
            q = jnp.einsum("bmd,hde->bhme", x, layer["wq"])
            k = jnp.einsum("bmd,hde->bhme", x, layer["wk"])
            v = jnp.einsum("bmd,hde->bhme", x, layer["wv"])
            scores = jnp.einsum("bhme,bhne->bhmn", q, k) / (dh ** 0.5)
            att = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bhmn,bhne->bhme", att, v)      # [B,H,m,dh]
            o = jnp.moveaxis(o, 1, 2).reshape(
                x.shape[0], x.shape[1], self.att_dim)      # [B,m,H*dh]
            x = jnp.maximum(o + x @ layer["wr"], 0.0)      # residual+ReLU

        h = x.reshape(batch_size, -1)
        if self.hidden:
            deep = mlp_apply(params["deep"], flat, final_activation=True)
            h = jnp.concatenate([h, deep], axis=-1)
        return dense_apply(params["head"], h)[:, 0] + wide + params["bias"]
