"""Shared-bottom multi-task CTR model (click + conversion style heads).

Role of the reference's multi-task CTR setups whose metrics ship as
``MultiTaskMetricMsg`` (``fleet/metrics.h:346``) and the multi-task AUC
family in ``python/paddle/fluid/incubate/fleet/utils``: one shared
sparse-embedding bottom feeding T per-task towers, trained on
``num_labels >= T`` label columns with per-task AUC.

Same functional contract as :class:`~paddlebox_tpu.models.DeepFM`
(init/apply over pulled per-slot embeddings), but ``apply`` returns
``[B, T]`` logits; CTRTrainer keys multi-task behavior (per-task loss +
stacked AUC states) off the ``num_tasks`` attribute.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import seqpool


@dataclasses.dataclass(frozen=True)
class SharedBottomMultiTask:
    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    num_tasks: int = 2
    dense_dim: int = 0
    bottom_hidden: Tuple[int, ...] = (256, 128)
    tower_hidden: Tuple[int, ...] = (64,)

    def _dims(self) -> Dict[str, int]:
        if isinstance(self.emb_dim, int):
            return {n: self.emb_dim for n in self.slot_names}
        return {n: int(self.emb_dim[n]) for n in self.slot_names}

    def init(self, rng: jax.Array) -> Dict:
        in_dim = sum(self._dims().values()) + self.dense_dim
        keys = jax.random.split(rng, self.num_tasks + 1)
        bottom_out = self.bottom_hidden[-1]
        return {
            "bottom": mlp_init(keys[0], in_dim, list(self.bottom_hidden)),
            "towers": [mlp_init(keys[1 + t], bottom_out,
                                list(self.tower_hidden) + [1])
                       for t in range(self.num_tasks)],
            # Per-task wide bias over the pooled first-order weights.
            "task_bias": jnp.zeros((self.num_tasks,), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B, num_tasks]."""
        pooled: List[jax.Array] = []
        wide_terms: List[jax.Array] = []
        for name in self.slot_names:
            pooled.append(seqpool(emb[name], segments[name], batch_size))
            wide_terms.append(seqpool(w[name], segments[name], batch_size))
        wide = sum(wide_terms)                            # [B]
        flat = jnp.concatenate(pooled, axis=-1)
        if dense_feats is not None and self.dense_dim:
            flat = jnp.concatenate([flat, dense_feats], axis=-1)
        # final_activation: the shared representation feeding the towers
        # should be nonlinear (mlp_apply leaves the last layer linear by
        # default, which is right for logit heads, not for a bottom).
        shared = mlp_apply(params["bottom"], flat,
                           final_activation=True)         # [B, H]
        logits = [mlp_apply(params["towers"][t], shared)[:, 0]
                  + wide + params["task_bias"][t]
                  for t in range(self.num_tasks)]
        return jnp.stack(logits, axis=-1)                 # [B, T]
