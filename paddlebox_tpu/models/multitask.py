"""Shared-bottom multi-task CTR model (click + conversion style heads).

Role of the reference's multi-task CTR setups whose metrics ship as
``MultiTaskMetricMsg`` (``fleet/metrics.h:346``) and the multi-task AUC
family in ``python/paddle/fluid/incubate/fleet/utils``: one shared
sparse-embedding bottom feeding T per-task towers, trained on
``num_labels >= T`` label columns with per-task AUC.

Same functional contract as :class:`~paddlebox_tpu.models.DeepFM`
(init/apply over pulled per-slot embeddings), but ``apply`` returns
``[B, T]`` logits; CTRTrainer keys multi-task behavior (per-task loss +
stacked AUC states) off the ``num_tasks`` attribute.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.common import pool_slot_inputs, slot_dims
from paddlebox_tpu.nn import mlp_apply, mlp_init


@dataclasses.dataclass(frozen=True)
class SharedBottomMultiTask:
    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    num_tasks: int = 2
    dense_dim: int = 0
    bottom_hidden: Tuple[int, ...] = (256, 128)
    tower_hidden: Tuple[int, ...] = (64,)

    def _dims(self) -> Dict[str, int]:
        return slot_dims(self.slot_names, self.emb_dim)

    def init(self, rng: jax.Array) -> Dict:
        in_dim = sum(self._dims().values()) + self.dense_dim
        keys = jax.random.split(rng, self.num_tasks + 1)
        bottom_out = self.bottom_hidden[-1]
        return {
            "bottom": mlp_init(keys[0], in_dim, list(self.bottom_hidden)),
            "towers": [mlp_init(keys[1 + t], bottom_out,
                                list(self.tower_hidden) + [1])
                       for t in range(self.num_tasks)],
            # Per-task wide bias over the pooled first-order weights.
            "task_bias": jnp.zeros((self.num_tasks,), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B, num_tasks]."""
        flat, wide = pool_slot_inputs(self.slot_names, emb, w, segments,
                                       batch_size, dense_feats,
                                       self.dense_dim)
        # final_activation: the shared representation feeding the towers
        # should be nonlinear (mlp_apply leaves the last layer linear by
        # default, which is right for logit heads, not for a bottom).
        shared = mlp_apply(params["bottom"], flat,
                           final_activation=True)         # [B, H]
        logits = [mlp_apply(params["towers"][t], shared)[:, 0]
                  + wide + params["task_bias"][t]
                  for t in range(self.num_tasks)]
        return jnp.stack(logits, axis=-1)                 # [B, T]


@dataclasses.dataclass(frozen=True)
class MMoE:
    """Multi-gate Mixture-of-Experts multi-task CTR (Ma et al. 2018) —
    the step up from the shared bottom when tasks conflict: E expert
    MLPs share the input; each task mixes them through its own softmax
    gate before its tower. Same trainer contract as
    :class:`SharedBottomMultiTask` (``num_tasks`` + [B, T] logits).

    All experts evaluate densely and the mix is one einsum — the
    MXU-friendly formulation (no data-dependent routing; this is the
    multi-task MMoE, not a sparse-dispatch MoE layer — for expert
    parallelism over the ep mesh axis see ``parallel/moe.py``)."""

    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    num_tasks: int = 2
    num_experts: int = 4
    dense_dim: int = 0
    expert_hidden: Tuple[int, ...] = (128, 64)
    tower_hidden: Tuple[int, ...] = (32,)

    def _dims(self) -> Dict[str, int]:
        return slot_dims(self.slot_names, self.emb_dim)

    def init(self, rng: jax.Array) -> Dict:
        in_dim = sum(self._dims().values()) + self.dense_dim
        n_keys = self.num_experts + 2 * self.num_tasks
        keys = jax.random.split(rng, n_keys)
        h = self.expert_hidden[-1]
        ki = iter(keys)
        return {
            "experts": [mlp_init(next(ki), in_dim,
                                 list(self.expert_hidden))
                        for _ in range(self.num_experts)],
            "gates": [mlp_init(next(ki), in_dim, [self.num_experts])
                      for _ in range(self.num_tasks)],
            "towers": [mlp_init(next(ki), h,
                                list(self.tower_hidden) + [1])
                       for _ in range(self.num_tasks)],
            "task_bias": jnp.zeros((self.num_tasks,), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B, num_tasks]."""
        flat, wide = pool_slot_inputs(self.slot_names, emb, w, segments,
                                       batch_size, dense_feats,
                                       self.dense_dim)
        experts = jnp.stack(
            [mlp_apply(p, flat, final_activation=True)
             for p in params["experts"]], axis=1)         # [B, E, H]
        logits = []
        for t in range(self.num_tasks):
            gate = jax.nn.softmax(
                mlp_apply(params["gates"][t], flat), axis=-1)  # [B, E]
            mixed = jnp.einsum("be,beh->bh", gate, experts)
            logits.append(mlp_apply(params["towers"][t], mixed)[:, 0]
                          + wide + params["task_bias"][t])
        return jnp.stack(logits, axis=-1)                 # [B, T]
