"""Wide&Deep CTR model with CVM features.

The BASELINE.md config-5 model (Wide&Deep 100B-feature HeterPS-style).
Deep tower consumes ``fused_seqpool_cvm`` outputs — per-slot pooled
embeddings with leading [log(show+1), log(ctr)] channels, the PaddleBox
production pattern (fused_seqpool_cvm wrapper, contrib/layers/nn.py:1746);
wide tower is the pooled scalar-w linear term.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm, seqpool


@dataclasses.dataclass(frozen=True)
class WideDeep:
    slot_names: Tuple[str, ...]
    emb_dim: int
    dense_dim: int = 0
    hidden: Tuple[int, ...] = (512, 256, 128)
    use_cvm: bool = True

    def init(self, rng: jax.Array) -> Dict:
        s = len(self.slot_names)
        per_slot = self.emb_dim + (2 if self.use_cvm else 0)
        in_dim = s * per_slot + self.dense_dim
        rng, sub = jax.random.split(rng)
        return {
            "mlp": mlp_init(sub, in_dim, list(self.hidden) + [1]),
            "bias": jnp.zeros((), jnp.float32),
        }

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              show: Dict[str, jax.Array],
              click: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        pooled: List[jax.Array] = []
        wide = params["bias"]
        for name in self.slot_names:
            pooled.append(fused_seqpool_cvm(
                emb[name], show[name], click[name], segments[name],
                batch_size, use_cvm=self.use_cvm))
            wide = wide + seqpool(w[name], segments[name], batch_size)
        flat = jnp.concatenate(pooled, axis=-1)
        if dense_feats is not None and self.dense_dim:
            flat = jnp.concatenate([flat, dense_feats], axis=-1)
        deep = mlp_apply(params["mlp"], flat)[:, 0]
        return wide + deep
