"""ResNet-50 for image classification (BASELINE.md config 1).

Role of the reference's vision path (``paddle.vision.models.resnet50``).
TPU-first: NHWC layout (channels on the lane axis), bottleneck blocks as
fused conv+BN+relu chains XLA maps onto the MXU via implicit GEMM.
Functional params; BN running stats threaded explicitly (no mutable
module state to fight jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.nn.conv import (batchnorm_apply, batchnorm_init,
                                   conv2d_apply, conv2d_init)
from paddlebox_tpu.nn.layers import dense_apply, dense_init

BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
          101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


@dataclasses.dataclass(frozen=True)
class ResNet:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64

    @property
    def bottleneck(self) -> bool:
        return self.depth >= 50

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        keys = iter(jax.random.split(rng, 256))
        w = self.width
        params: Dict[str, Any] = {
            "stem_conv": conv2d_init(next(keys), 3, w, 7),
            "stem_bn": batchnorm_init(w),
        }
        in_ch = w
        exp = 4 if self.bottleneck else 1
        for stage, nblocks in enumerate(BLOCKS[self.depth]):
            ch = w * (2 ** stage)
            for b in range(nblocks):
                name = f"s{stage}b{b}"
                stride = 2 if (b == 0 and stage > 0) else 1
                out_ch = ch * exp
                blk: Dict[str, Any] = {}
                if self.bottleneck:
                    blk["c1"] = conv2d_init(next(keys), in_ch, ch, 1)
                    blk["bn1"] = batchnorm_init(ch)
                    blk["c2"] = conv2d_init(next(keys), ch, ch, 3)
                    blk["bn2"] = batchnorm_init(ch)
                    blk["c3"] = conv2d_init(next(keys), ch, out_ch, 1)
                    blk["bn3"] = batchnorm_init(out_ch)
                else:
                    blk["c1"] = conv2d_init(next(keys), in_ch, ch, 3)
                    blk["bn1"] = batchnorm_init(ch)
                    blk["c2"] = conv2d_init(next(keys), ch, out_ch, 3)
                    blk["bn2"] = batchnorm_init(out_ch)
                if in_ch != out_ch or stride != 1:
                    blk["proj"] = conv2d_init(next(keys), in_ch, out_ch, 1)
                    blk["proj_bn"] = batchnorm_init(out_ch)
                params[name] = blk
                in_ch = out_ch
        params["head"] = dense_init(next(keys), in_ch, self.num_classes)
        return params

    def apply(self, params: Dict, x: jax.Array, *, train: bool = False,
              axis_name: str | None = None) -> Tuple[jax.Array, Dict]:
        """x [B, H, W, 3] → (logits [B, classes], updated params w/ BN
        stats)."""
        new_params = dict(params)

        def bn(name_or_blk, blk_name, key, h):
            p = new_params[blk_name][key] if blk_name else new_params[key]
            y, p2 = batchnorm_apply(p, h, train=train, axis_name=axis_name)
            if blk_name:
                new_params[blk_name] = {**new_params[blk_name], key: p2}
            else:
                new_params[key] = p2
            return y

        h = conv2d_apply(params["stem_conv"], x, stride=2)
        h = jax.nn.relu(bn(None, None, "stem_bn", h))
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")

        for stage, nblocks in enumerate(BLOCKS[self.depth]):
            for b in range(nblocks):
                name = f"s{stage}b{b}"
                blk = params[name]
                stride = 2 if (b == 0 and stage > 0) else 1
                shortcut = h
                if self.bottleneck:
                    y = conv2d_apply(blk["c1"], h)
                    y = jax.nn.relu(bn(None, name, "bn1", y))
                    y = conv2d_apply(blk["c2"], y, stride=stride)
                    y = jax.nn.relu(bn(None, name, "bn2", y))
                    y = conv2d_apply(blk["c3"], y)
                    y = bn(None, name, "bn3", y)
                else:
                    y = conv2d_apply(blk["c1"], h, stride=stride)
                    y = jax.nn.relu(bn(None, name, "bn1", y))
                    y = conv2d_apply(blk["c2"], y)
                    y = bn(None, name, "bn2", y)
                if "proj" in blk:
                    shortcut = conv2d_apply(blk["proj"], h, stride=stride)
                    shortcut = bn(None, name, "proj_bn", shortcut)
                h = jax.nn.relu(y + shortcut)

        h = jnp.mean(h, axis=(1, 2))
        return dense_apply(params["head"], h), new_params
