"""Shared model-input helpers for the CTR zoo."""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops import seqpool


def pool_slot_inputs(slot_names, emb, w, segments, batch_size,
                     dense_feats, dense_dim):
    """Shared input prelude for the pooled CTR models: per-slot sum-pool
    of embeddings and first-order weights -> (flat [B, sum D + dense],
    wide [B])."""
    pooled: List[jax.Array] = []
    wide_terms: List[jax.Array] = []
    for name in slot_names:
        pooled.append(seqpool(emb[name], segments[name], batch_size))
        wide_terms.append(seqpool(w[name], segments[name], batch_size))
    flat = jnp.concatenate(pooled, axis=-1)
    if dense_feats is not None and dense_dim:
        flat = jnp.concatenate([flat, dense_feats], axis=-1)
    return flat, sum(wide_terms)


def slot_dims(slot_names, emb_dim):
    """Per-slot embedding widths from an int (uniform) or mapping
    (dynamic-mf per-slot override)."""
    if isinstance(emb_dim, int):
        return {n: emb_dim for n in slot_names}
    return {n: int(emb_dim[n]) for n in slot_names}
