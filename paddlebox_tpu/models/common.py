"""Shared model-input helpers for the CTR zoo."""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops import seqpool


def pool_slot_inputs(slot_names, emb, w, segments, batch_size,
                     dense_feats, dense_dim):
    """Shared input prelude for the pooled CTR models: per-slot sum-pool
    of embeddings and first-order weights -> (flat [B, sum D + dense],
    wide [B])."""
    pooled: List[jax.Array] = []
    wide_terms: List[jax.Array] = []
    for name in slot_names:
        pooled.append(seqpool(emb[name], segments[name], batch_size))
        wide_terms.append(seqpool(w[name], segments[name], batch_size))
    flat = jnp.concatenate(pooled, axis=-1)
    if dense_feats is not None and dense_dim:
        flat = jnp.concatenate([flat, dense_feats], axis=-1)
    return flat, sum(wide_terms)


def slot_dims(slot_names, emb_dim):
    """Per-slot embedding widths from an int (uniform) or mapping
    (dynamic-mf per-slot override)."""
    if isinstance(emb_dim, int):
        return {n: emb_dim for n in slot_names}
    return {n: int(emb_dim[n]) for n in slot_names}


def uniform_emb_dim(slot_names, emb_dim, model: str, why: str) -> int:
    """The single embedding width, for models whose interaction tower
    mixes field VECTORS (CIN, attention) and so cannot host dynamic-mf
    per-slot widths; raises with the model's reason otherwise."""
    dims = set(slot_dims(slot_names, emb_dim).values())
    if len(dims) != 1:
        raise ValueError(
            f"{model} needs one uniform emb_dim; got widths "
            f"{sorted(dims)} — {why}")
    return dims.pop()
