"""DCN — Deep & Cross Network over pulled sparse embeddings.

The other staple of the PaddleBox-era CTR zoo next to DeepFM/Wide&Deep
(reference models compose ``pull_box_sparse`` + ``fused_seqpool_cvm``
graphs with explicit feature crossing). CrossNet v2 form: each layer
``x_{l+1} = x0 * (W_l x_l + b_l) + x_l`` learns bounded-degree feature
interactions explicitly; a parallel deep tower learns implicit ones;
both feed one logit head.

Same functional contract as :class:`~paddlebox_tpu.models.DeepFM`
(init/apply, differentiable w.r.t. pulled emb/w for the sparse push) —
all dense ops are [B, F] matmuls the MXU eats directly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Tuple, Union

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.common import pool_slot_inputs, slot_dims
from paddlebox_tpu.nn import dense_apply, dense_init, mlp_apply, mlp_init


@dataclasses.dataclass(frozen=True)
class DCN:
    slot_names: Tuple[str, ...]
    emb_dim: Union[int, Mapping[str, int]]
    dense_dim: int = 0
    num_cross_layers: int = 3
    hidden: Tuple[int, ...] = (128, 64)

    def _dims(self) -> Dict[str, int]:
        return slot_dims(self.slot_names, self.emb_dim)

    def init(self, rng: jax.Array) -> Dict:
        f = sum(self._dims().values()) + self.dense_dim
        keys = jax.random.split(rng, self.num_cross_layers + 2)
        deep_out = self.hidden[-1] if self.hidden else 0
        out = {
            "cross": [dense_init(keys[i], f, f)
                      for i in range(self.num_cross_layers)],
            # Head over [cross_out | deep_out] (cross-only when
            # hidden=() — a standard DCN variant).
            "head": dense_init(keys[-1], f + deep_out, 1),
            "bias": jnp.zeros((), jnp.float32),
        }
        if self.hidden:
            out["deep"] = mlp_init(keys[-2], f, list(self.hidden))
        return out

    def apply(self, params: Dict,
              emb: Dict[str, jax.Array],
              w: Dict[str, jax.Array],
              segments: Dict[str, jax.Array],
              batch_size: int,
              dense_feats: jax.Array | None = None) -> jax.Array:
        """Returns logits [B]."""
        x0, wide = pool_slot_inputs(self.slot_names, emb, w, segments,
                                     batch_size, dense_feats,
                                     self.dense_dim)
        x = x0
        for layer in params["cross"]:
            x = x0 * dense_apply(layer, x) + x
        if self.hidden:
            deep = mlp_apply(params["deep"], x0, final_activation=True)
            x = jnp.concatenate([x, deep], axis=-1)
        return (dense_apply(params["head"], x)[:, 0] + wide
                + params["bias"])
