"""Model zoo.

CTR family (role of the PaddleBox production models built on
``_pull_box_sparse`` + ``fused_seqpool_cvm`` graphs,
``python/paddle/fluid/contrib/layers/nn.py:1746``): DeepFM, Wide&Deep.
Dense families (ResNet/BERT/GPT — the reference's fleet collective /
hybrid-parallel configs) live in their own modules.
"""

from paddlebox_tpu.models.autoint import AutoInt
from paddlebox_tpu.models.dcn import DCN
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.models.din_rank import DINRank, build_rank_offset
from paddlebox_tpu.models.multitask import MMoE, SharedBottomMultiTask
from paddlebox_tpu.models.wide_deep import WideDeep
from paddlebox_tpu.models.xdeepfm import XDeepFM

__all__ = ["AutoInt", "DCN", "DeepFM", "DINRank", "MMoE",
           "SharedBottomMultiTask", "WideDeep", "XDeepFM",
           "build_rank_offset"]
