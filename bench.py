"""Benchmark harness: DeepFM CTR training throughput on real TPU.

Runs the flagship sparse-CTR config (BASELINE.md config 4: DeepFM,
BoxPS-style pull/push through the pass-based embedding engine) on whatever
accelerator jax exposes, and prints ONE json line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured samples/sec/chip divided by the BASELINE.md target
proxy (the reference publishes no numbers; target proxy = 90% of an 8xA100
DeepFM-Criteo run ~= 1.3M samples/s/8 chips ~= 162k samples/s/chip,
BASELINE.md "≥90% of 8×A100 on v5e-8").
"""

import json
import sys
import time

import numpy as np

TARGET_SAMPLES_PER_SEC_PER_CHIP = 162_000.0

# Realistic CTR shapes: 26 sparse slots (Criteo-like), dim-16 embeddings,
# 13 dense features. Batch 16384 per chip: CTR models are small, so
# smaller batches leave the step dispatch-bound (measured ~2x throughput
# going 4096 -> 16384 on v5e) — production CTR batches sit in this range.
NUM_SLOTS = 26
EMB_DIM = 16
DENSE_DIM = 13
BATCH = 16384
NUM_FEATURES = 2_000_000
AVG_IDS_PER_SLOT = 1.0
STEPS_WARMUP = 3
STEPS_TIMED = 20


def main() -> None:
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
    from paddlebox_tpu.embedding import TableConfig
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import HybridTopology, build_mesh
    from paddlebox_tpu.train import CTRTrainer, TrainerConfig

    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    slots = tuple(SlotConf(f"s{i}", avg_len=AVG_IDS_PER_SLOT)
                  for i in range(NUM_SLOTS))
    feed = DataFeedConfig(slots=slots, batch_size=BATCH)
    table_cfg = TableConfig(dim=EMB_DIM, learning_rate=0.05)
    model = DeepFM(slot_names=tuple(s.name for s in slots), emb_dim=EMB_DIM,
                   hidden=(400, 400, 400))
    trainer = CTRTrainer(model, feed, table_cfg, mesh=mesh,
                         config=TrainerConfig(auc_num_buckets=1 << 16))
    trainer.init(seed=0)

    # Synthetic pass: keys uniform over the feature space.
    rng = np.random.default_rng(0)
    pass_keys = rng.choice(np.arange(1, NUM_FEATURES, dtype=np.uint64),
                           size=NUM_FEATURES // 4, replace=False)
    trainer.engine.feed_pass(pass_keys)
    table = trainer.engine.begin_pass()

    # One synthetic packed batch reused every step (isolates device+host-map
    # throughput from disk IO, like the reference's in-memory pass).
    caps = {s.name: feed.sparse_capacity(s, num_shards=ndev) for s in slots}
    ids = {}
    segments = {}
    for s in slots:
        cap = caps[s.name]
        cap_local = cap // ndev
        bs_local = BATCH // ndev
        segs = np.concatenate([
            np.sort(rng.integers(0, bs_local, cap_local)).astype(np.int32)
            for _ in range(ndev)])
        ids[s.name] = rng.choice(pass_keys, cap).astype(np.uint64)
        segments[s.name] = segs
    labels = (rng.random((BATCH, 1)) < 0.25).astype(np.float32)
    valid = np.ones((BATCH,), bool)

    step = trainer._build_step()
    names = [s.name for s in slots]
    all_ids = np.concatenate([ids[n] for n in names])
    rows = trainer.engine.lookup_rows(all_ids)
    from paddlebox_tpu.train.ctr_trainer import _interleave_slots
    rows = _interleave_slots(rows, names, caps, ndev)
    segs_j = {n: jnp.asarray(segments[n]) for n in names}
    dense = jnp.zeros((BATCH, 0), jnp.float32)
    args = lambda t, p, o, a: (t, p, o, a, jnp.asarray(rows), segs_j,
                               jnp.asarray(labels), jnp.asarray(valid), dense)

    params, opt_state, auc = trainer.params, trainer.opt_state, trainer.auc_state
    for _ in range(STEPS_WARMUP):
        table, params, opt_state, auc, loss = step(
            *args(table, params, opt_state, auc))
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(STEPS_TIMED):
        table, params, opt_state, auc, loss = step(
            *args(table, params, opt_state, auc))
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = STEPS_TIMED * BATCH / dt
    per_chip = samples_per_sec / ndev
    print(json.dumps({
        "metric": "deepfm_ctr_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(per_chip / TARGET_SAMPLES_PER_SEC_PER_CHIP, 4),
    }))


if __name__ == "__main__":
    main()
